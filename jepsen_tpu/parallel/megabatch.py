"""Megabatch: the batch-throughput path for thousands of small histories.

``check_batch`` treats a batch as one barrier: every lane is padded to
the batch max shape, every dispatch transfers a ``[lanes, 5]`` flag
array back to the host, and a batch does not finish until its slowest
lane does.  That is the wrong shape for the serving fleet, whose
steady-state traffic is thousands of SHORT per-key histories (the
product of P-compositional decomposition): the device spends its time
waiting on per-dispatch host polls and on retired lanes idling inside a
barrier.

This module keeps the device saturated instead:

* **Bucket bin-packing.**  Prepared histories are packed into the
  power-of-two bucket ladder (events x window x ghost-words x
  state-width, the same ladder serve/buckets.py pins the compile cache
  to), so one compiled engine serves every lane of a bucket and the
  shape universe stays bounded.
* **Model-agnostic carries.**  The engine carry layout is the same for
  every device model — only the packed ``states`` width varies — so any
  model family with a registered carry descriptor
  (``engine.plugins.has_carry_descriptor``; the
  ``JaxModel.carry_descriptor()`` shape+dtype seam) bin-packs into this
  loop: queue rings, set bitmasks, and txn-register key vectors ride
  the same dispatch machinery as registers, with chunk and start
  capacity damped per state-width rung (``engine.ladder.mega_chunk`` /
  ``state_capacity``).
* **Contiguous staging + double-buffered transfer.**  Each lane group's
  event streams live in ONE contiguous pinned host buffer; refills
  rewrite rows host-side and re-upload with an async ``device_put``
  that overlaps the in-flight scan (JAX async dispatch) — the host
  never calls ``block_until_ready`` between dispatches.
* **Fused O(1) readback.**  The per-dispatch verdict reduction runs
  inside the jitted step: each dispatch returns a single
  ``int32[SUMMARY_WIDTH]`` vector per group (live/done/failed/overflow
  counts), not per-lane arrays.  Per-lane results are read only at
  harvest points (a retire/refill event), amortized over many
  dispatches.
* **Continuous lane refill.**  Lanes that finish early retire and are
  backfilled from the staging queue inside the jitted ``reset`` (a
  masked select against the initial carry) — no batch barriers.
* **Donated carries.**  The per-chunk carry is donated
  (``donate_argnums``) on non-CPU backends so XLA updates it in place
  (see parallel.batch.donate_carry_argnums).

Overflowing lanes retire with a sentinel and are re-checked through
plain :func:`jepsen_tpu.parallel.batch.check_batch` at escalated
capacity after the megabatch drains — capacity only affects overflow,
never verdicts, so results are identical to the barrier path lane for
lane.

Host↔device traffic discipline is observable: every device→host read
on this path goes through one counted chokepoint (`megabatch_stats`),
and ``transfer_guard=True`` additionally arms JAX's transfer guard so
an uncounted transfer raises instead of silently costing a sync.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict, deque
from contextlib import contextmanager, nullcontext
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from jepsen_tpu.checker.prep import prepare
from jepsen_tpu.checker.wgl_tpu import (EV_NOP, _round_window, chosen_gwords,
                                        events_array, make_engine)
from jepsen_tpu.history import History
from jepsen_tpu.models.base import JaxModel
from jepsen_tpu.parallel.batch import (MAX_LANES_PER_GROUP, _CACHE,
                                       check_batch, donate_carry_argnums)

__all__ = ["check_megabatch", "megabatch_enabled", "megabatch_stats",
           "reset_megabatch_stats", "SUMMARY_WIDTH"]

#: ints per per-dispatch summary readback: live, done, failed, overflow
#: lane counts over the group.  O(1) — independent of the lane count.
SUMMARY_WIDTH = 4

#: ints per lane in a harvest readback: status, failed_op, explored,
#: consumed.  Status codes below.
HARVEST_WIDTH = 4
STATUS_RUNNING = 0   # still live (or an empty pad lane)
STATUS_VALID = 1
STATUS_FAILED = 2
STATUS_OVERFLOW = 3

#: default cap on concurrently-resident lanes (across a bucket's groups);
#: the lane-count ladder in serve/buckets.py (mega_lane_bucket) feeds
#: this from the scheduler side.
DEFAULT_MAX_LANES = 4096


def megabatch_enabled() -> bool:
    """The ``JEPSEN_TPU_MEGABATCH`` kill switch (default: enabled)."""
    return os.environ.get("JEPSEN_TPU_MEGABATCH", "1").lower() \
        not in ("0", "false", "no", "off")


def staging_depth_default() -> int:
    """In-flight dispatches per group (``JEPSEN_TPU_STAGING_DEPTH``).

    Depth 2 is the classic double-buffer: while the host blocks on
    dispatch N's summary, dispatch N+1 is already queued on the device.
    """
    try:
        return max(1, int(os.environ.get("JEPSEN_TPU_STAGING_DEPTH", "2")))
    except ValueError:
        return 2


# ---------------------------------------------------------------------------
# Readback accounting
# ---------------------------------------------------------------------------

_STATS_LOCK = threading.Lock()


def _zero_stats() -> Dict[str, int]:
    return {"calls": 0, "staged_lanes": 0, "buckets": 0, "groups": 0,
            "dispatches": 0, "summary_reads": 0, "summary_ints": 0,
            "harvests": 0, "harvest_ints": 0, "refills": 0,
            "lanes_refilled": 0, "lanes_retired": 0, "escalated_lanes": 0}


_STATS = _zero_stats()


def megabatch_stats() -> Dict[str, int]:
    """Counters over every megabatch run in this process.  The O(1)
    readback invariant is checkable from the outside: per-dispatch reads
    are ``summary_ints == summary_reads * SUMMARY_WIDTH`` with
    ``summary_reads <= dispatches`` (a harvest discards its group's
    unread in-flight summaries), and every other device→host read is a
    (rare, refill-amortized) harvest."""
    with _STATS_LOCK:
        return dict(_STATS)


def reset_megabatch_stats() -> None:
    with _STATS_LOCK:
        _STATS.update(_zero_stats())


def _bump(**kw: int) -> None:
    with _STATS_LOCK:
        for k, v in kw.items():
            _STATS[k] += v


@contextmanager
def _allow_d2h():
    """Readback chokepoint escape hatch for the armed transfer guard."""
    with jax.transfer_guard_device_to_host("allow"):
        yield


def _read_summary(dev) -> np.ndarray:
    with _allow_d2h():
        a = np.asarray(dev)
    _bump(summary_reads=1, summary_ints=int(a.size))
    return a


def _read_harvest(dev) -> np.ndarray:
    from jepsen_tpu.obs.recorder import RECORDER
    t0 = time.monotonic()
    with _allow_d2h():
        a = np.asarray(dev)
    _bump(harvests=1, harvest_ints=int(a.size))
    RECORDER.record("transfer", "d2h:harvest",
                    dur_s=time.monotonic() - t0,
                    args={"ints": int(a.size)})
    return a


# ---------------------------------------------------------------------------
# Bucketing (the same power-of-two ladder serve pins the compile cache to)
# ---------------------------------------------------------------------------

def _pow2_at_least(n: int, floor: int) -> int:
    # One rung definition for the whole stack: delegate to the shared
    # ladder (resolved lazily — the serve import behind it would cycle at
    # module-import time).
    from jepsen_tpu.engine.ladder import pow2_at_least
    return pow2_at_least(n, max(1, floor))


def _prep_bucket(p, window_floor: int, ev_floor: int, gw_b: int,
                 sw_b: int) -> Tuple[int, int, int, int]:
    """(events, window, gwords, state-width) bucket of one prepared
    history.

    Events and window are pure functions of the single history, so
    packing order and group makeup can never change the engine shape a
    lane runs under (the packing-invariance contract the tests fuzz).
    The ghost-word rung is the CALL-level pow2 ceiling (check_batch's
    "lean only when every lane qualifies" rule): an engine with at least
    a lane's chosen ghost words is result-identical for that lane
    (LEAN_GHOST_MAX=0 means lean only ever runs zero-ghost histories),
    and one shared rung keeps a mixed call in one bucket instead of
    fragmenting the lane groups on ghost count.  The state-width rung is
    the model's packed-carry width off the state-width ladder — constant
    per call (one model per call) but part of the key so the chunk and
    start-capacity derivations downstream are pure functions of the
    bucket tuple alone."""
    ev_b = _pow2_at_least(max(1, len(p)), max(64, ev_floor))
    w_b = _pow2_at_least(_round_window(max(p.window, window_floor)), 8)
    return (ev_b, w_b, gw_b, sw_b)


def _call_gwords(preps) -> int:
    gw = max(chosen_gwords(p) for p in preps)
    return 0 if gw == 0 else _pow2_at_least(gw, 1)


def _default_capacity(ev_b: int, w_b: int, sw_b: int) -> int:
    from jepsen_tpu.engine.ladder import state_capacity
    return state_capacity(ev_b, w_b, sw_b)


# ---------------------------------------------------------------------------
# The jitted group programs (cached in the shared engine LRU)
# ---------------------------------------------------------------------------

def _mega_runner(model: JaxModel, window: int, capacity: int, gwords: int,
                 chunk: int, width: int, group_reuse: bool = False):
    """(carry0, step, harvest, reset) for one group shape.

    ``step``   : (carry, events, lane_len) -> (carry', int32[SUMMARY_WIDTH])
                 — one vmapped single-round chunk plus the fused verdict
                 reduction; the carry is donated.
    ``harvest``: (carry, lane_len) -> int32[width, HARVEST_WIDTH]
                 — per-lane (status, failed_op, explored, consumed).
    ``reset``  : (carry, refill_mask) -> carry' with refilled lanes set
                 back to the initial engine carry; the carry is donated.
    """
    key = ("megav", model.name, model.variant, model.state_size,
           tuple(model.init_state_array().tolist()), window, capacity,
           gwords, chunk, width)
    hit = _CACHE.get(key, group_reuse=group_reuse)
    if hit is not None:
        return hit

    carry0, _, run_chunk = make_engine(model, window, capacity,
                                       gwords=gwords, work_budget=0,
                                       single_round_closure=True,
                                       steps_per_dispatch=chunk)
    vrun = jax.vmap(run_chunk, in_axes=(0, 0))

    def _liveness(failed, overflow, consumed, stalled, lane_len):
        real = lane_len > 0
        live = real & ~failed & ~overflow \
            & ((consumed < lane_len) | stalled)
        done = real & ~live
        return real, live, done

    def step(carry, events, lane_len):
        carry, flags = vrun(carry, events)
        failed = flags[:, 0] != 0
        overflow = flags[:, 1] != 0
        consumed = flags[:, 3]
        stalled = flags[:, 4] != 0
        _, live, done = _liveness(failed, overflow, consumed, stalled,
                                  lane_len)
        summary = jnp.stack([
            live.sum().astype(jnp.int32),
            done.sum().astype(jnp.int32),
            (done & failed).sum().astype(jnp.int32),
            (done & overflow).sum().astype(jnp.int32),
        ])
        return carry, summary

    def harvest(carry, lane_len):
        failed = carry[6]
        overflow = carry[8]
        consumed = carry[14]
        stalled = carry[18] >= 0
        real, live, _ = _liveness(failed, overflow, consumed, stalled,
                                  lane_len)
        status = jnp.where(
            ~real | live, STATUS_RUNNING,
            jnp.where(overflow, STATUS_OVERFLOW,
                      jnp.where(failed, STATUS_FAILED, STATUS_VALID)))
        return jnp.stack([status.astype(jnp.int32),
                          carry[7].astype(jnp.int32),
                          carry[9].astype(jnp.int32),
                          consumed.astype(jnp.int32)], axis=1)

    c0 = carry0()
    c0b = jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (width,) + x.shape), c0)

    def reset(carry, refill_mask):
        def sel(cur, init):
            m = refill_mask.reshape((width,) + (1,) * (cur.ndim - 1))
            return jnp.where(m, init, cur)
        return jax.tree.map(sel, carry, c0b)

    donate = donate_carry_argnums()
    from jepsen_tpu.obs.hist import timed_first_call
    step_j = timed_first_call(
        jax.jit(step, donate_argnums=donate),
        f"compile:megav:{model.name}:w{window}:c{capacity}"
        f":k{chunk}:l{width}")
    harvest_j = jax.jit(harvest)
    reset_j = jax.jit(reset, donate_argnums=donate)
    return _CACHE.put(key, (carry0, step_j, harvest_j, reset_j))


# ---------------------------------------------------------------------------
# Host-side group state
# ---------------------------------------------------------------------------

class _Group:
    """One vmapped lane group: a contiguous host staging buffer, its
    device mirror, the engine carry, and the lane→history bookkeeping."""

    def __init__(self, width: int, rows: int, carry0):
        self.width = width
        # The contiguous pinned staging buffer: all of a group's lanes in
        # one [width, rows, 10] block, so a refill's device_put is one
        # coalesced transfer instead of per-lane scatters.
        self.host_ev = np.zeros((width, rows, 10), np.int32)
        self.host_ev[:, :, 0] = EV_NOP
        self.host_len = np.zeros(width, np.int32)
        self.slots: List[Optional[int]] = [None] * width
        c0 = carry0()
        self.carry = jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (width,) + x.shape), c0)
        self.ev_dev = None
        self.len_dev = None
        self.pending: "deque" = deque()     # in-flight dispatch summaries
        self.live_est = 0                   # from the last summary read
        self.expect = 0                     # dispatches this fill needs

    def load(self, lane: int, hist_idx: int, ev: np.ndarray) -> None:
        self.host_ev[lane, :, 0] = EV_NOP
        self.host_ev[lane, :, 1:] = 0
        self.host_ev[lane, :ev.shape[0]] = ev
        self.host_len[lane] = ev.shape[0]
        self.slots[lane] = hist_idx

    def upload(self) -> None:
        """Async device_put of the coalesced staging buffer — enqueued
        behind the in-flight dispatches, overlapping their compute."""
        self.ev_dev = jax.device_put(np.ascontiguousarray(self.host_ev))
        self.len_dev = jax.device_put(self.host_len.copy())


# ---------------------------------------------------------------------------
# The megabatch driver
# ---------------------------------------------------------------------------

def check_megabatch(model: JaxModel,
                    histories: Sequence[History],
                    capacity: Optional[int] = None,
                    max_capacity: int = 65536,
                    window_floor: int = 0,
                    ev_floor: int = 0,
                    lanes: int = DEFAULT_MAX_LANES,
                    chunk: Optional[int] = None,
                    staging_depth: Optional[int] = None,
                    refill_quantum: Optional[int] = None,
                    transfer_guard: bool = False) -> List[Dict[str, Any]]:
    """Check many (small) histories with continuous lane refill; returns
    one result dict per history, in input order.

    Verdicts, refuting ops, and ``configs-explored`` are identical to
    :func:`check_batch` and to the CPU oracle lane for lane, and are
    invariant under input order and group-size choices: every lane runs
    under an engine shape derived purely from its own (events, window,
    ghost-words) bucket, never from what it happens to be packed with.

    ``lanes`` caps concurrently-resident device lanes (the scheduler
    feeds it from the serve lane-count ladder); ``staging_depth`` is the
    per-group in-flight dispatch depth (default: env
    ``JEPSEN_TPU_STAGING_DEPTH`` or 2); ``refill_quantum`` is the retired
    lane count that triggers a harvest+refill (default: width // 4).
    ``transfer_guard=True`` arms JAX's device→host transfer guard outside
    the counted readback chokepoints, so any stray per-dispatch transfer
    raises loudly (the CI smoke runs with it armed).
    """
    if not histories:
        return []
    _bump(calls=1, staged_lanes=len(histories))
    depth = staging_depth if staging_depth else staging_depth_default()
    preps = [prepare(h, model) for h in histories]

    gw_b = _call_gwords(preps)
    from jepsen_tpu.engine.ladder import state_width_bucket
    sw_b = state_width_bucket(model.state_size)
    buckets: "OrderedDict[Tuple[int, int, int, int], List[int]]" = \
        OrderedDict()
    for i, p in enumerate(preps):
        buckets.setdefault(
            _prep_bucket(p, window_floor, ev_floor, gw_b, sw_b),
            []).append(i)

    out: List[Optional[Dict[str, Any]]] = [None] * len(histories)
    guard = jax.transfer_guard_device_to_host("disallow") \
        if transfer_guard else nullcontext()
    with guard:
        for bi, (bucket, idxs) in enumerate(buckets.items()):
            _drain_bucket(model, histories, preps, bucket, idxs, out,
                          capacity=capacity, max_capacity=max_capacity,
                          lanes=lanes, chunk=chunk, depth=depth,
                          refill_quantum=refill_quantum,
                          group_reuse=bi > 0)
    return out  # type: ignore[return-value]


def _drain_bucket(model, histories, preps, bucket, idxs, out, *,
                  capacity, max_capacity, lanes, chunk, depth,
                  refill_quantum, group_reuse) -> None:
    """Run every history of one (events, window, gwords, state-width)
    bucket through a refilled set of lane groups, writing results into
    ``out``."""
    from jepsen_tpu.engine.ladder import mega_chunk
    ev_b, w_b, gw_b, sw_b = bucket
    _bump(buckets=1)
    width = min(_pow2_at_least(min(len(idxs), lanes), 1),
                MAX_LANES_PER_GROUP)
    # Chunk and start capacity come off the state-width-aware ladder
    # shared with check_batch: pure functions of the bucket tuple, so a
    # queue ring and a register cell compile into the same bounded shape
    # universe (just on different state rungs).
    cc = chunk if chunk else mega_chunk(width, ev_b, sw_b)
    # Buffer rows are a pure function of the bucket (+1 trailing NOP row
    # that finished cursors clamp onto), never of the lanes present.
    rows = max(cc, ((ev_b + cc - 1) // cc) * cc) + 1
    cap = capacity if capacity else _default_capacity(ev_b, w_b, sw_b)
    cap = min(cap, max_capacity)
    n_groups = max(1, min((len(idxs) + width - 1) // width,
                          max(1, lanes // width)))
    quantum = refill_quantum if refill_quantum else max(1, width // 4)
    # Dispatches a stall-free fill takes: the whole staged buffer is one
    # chunk scan per `cc` rows.  This caps the prefetch depth so the
    # pipeline never burns a full extra chunk scan on a done carry.
    exp0 = max(1, (rows - 1) // cc)

    staging = deque(idxs)
    escalate: List[int] = []

    groups: List[_Group] = []
    for g in range(n_groups):
        if not staging:
            break
        # Each group re-fetches the cached runner: the call's first fetch
        # is an ordinary hit/miss, every later group is a same-dispatch
        # executable reuse (the group_reuses counter in the engine LRU).
        carry0, step_j, harvest_j, reset_j = _mega_runner(
            model, w_b, cap, gw_b, cc, width,
            group_reuse=group_reuse or g > 0)
        grp = _Group(width, rows, carry0)
        _fill(grp, range(width), staging, preps, cc)
        grp.upload()
        grp.expect = exp0
        groups.append(grp)
    _bump(groups=len(groups))

    # Generous progress bound: every real lane finishes within
    # (window + 2) rounds per event (a pending return stalls at most
    # window + 1 closure rounds), plus slack for NOP tails and refills.
    fills = (len(idxs) + width * max(1, len(groups)) - 1) \
        // (width * max(1, len(groups))) + 1
    max_disp = 64 + 8 * fills * len(groups) * (w_b + 2) \
        * ((rows + cc - 1) // cc)

    active = list(groups)
    dispatched = 0
    while active:
        for grp in list(active):
            # Keep the pipeline as full as the remaining work plausibly
            # needs: `expect` is the stall-free dispatch count of the
            # current fill; once it is spent, lanes that are still live
            # (stalled on pending returns) get one dispatch at a time.
            # The carry chains on device; the host never blocks between
            # dispatches.
            while len(grp.pending) < depth \
                    and (grp.expect > 0 or not grp.pending):
                grp.carry, summ = step_j(grp.carry, grp.ev_dev,
                                         grp.len_dev)
                grp.pending.append(summ)
                grp.expect = max(0, grp.expect - 1)
                dispatched += 1
                _bump(dispatches=1)
            # O(1) readback: the oldest in-flight summary (4 ints).
            s = _read_summary(grp.pending.popleft())
            live, done = int(s[0]), int(s[1])
            grp.live_est = live
            if live == 0 and not staging:
                # Bucket drained through this group: final harvest.
                grp.pending.clear()
                _harvest(grp, harvest_j, preps, out, escalate, staging,
                         cc, refill=False)
                active.remove(grp)
            elif staging and (done >= min(quantum, len(staging))
                              or live == 0):
                # Early-retiring lanes: harvest the finished ones and
                # backfill from the staging queue (continuous refill).
                grp.pending.clear()
                freed = _harvest(grp, harvest_j, preps, out, escalate,
                                 staging, cc, refill=True)
                if freed:
                    reset_mask = np.zeros(grp.width, bool)
                    reset_mask[freed] = True
                    # The refilled staging buffer rides up on an async
                    # device_put that overlaps whatever compute other
                    # groups have in flight.
                    grp.upload()
                    grp.carry = reset_j(grp.carry,
                                        jax.device_put(reset_mask))
                    grp.expect = exp0
                    _bump(refills=1, lanes_refilled=len(freed))
        if dispatched > max_disp:
            raise RuntimeError(
                f"megabatch made no progress after {dispatched} dispatches "
                f"(bucket {bucket}, {len(staging)} staged remaining)")

    if escalate:
        # Overflowed lanes re-run through the barrier path at escalated
        # capacity; capacity never changes verdicts, only whether the
        # frontier fits, so parity is preserved.
        _bump(escalated_lanes=len(escalate))
        esc = check_batch(model, [histories[i] for i in escalate],
                          capacity=min(cap * 8, max_capacity),
                          max_capacity=max_capacity,
                          window_floor=w_b)
        for i, r in zip(escalate, esc):
            out[i] = r


def _fill(grp: _Group, lanes_iter, staging, preps, cc) -> None:
    """Load staged histories into free lanes of ``grp`` (host side)."""
    for lane in lanes_iter:
        if not staging:
            break
        hist_idx = staging.popleft()
        grp.load(lane, hist_idx, events_array(preps[hist_idx], cc))


def _harvest(grp: _Group, harvest_j, preps, out, escalate, staging,
             cc, refill: bool) -> List[int]:
    """Read per-lane results for finished lanes, record them, and (when
    refilling) reload the freed lanes from the staging queue.  Returns
    the refilled lane indices."""
    h = _read_harvest(harvest_j(grp.carry, grp.len_dev))
    freed: List[int] = []
    for lane in range(grp.width):
        hist_idx = grp.slots[lane]
        if hist_idx is None or h[lane, 0] == STATUS_RUNNING:
            continue
        status, failed_op, explored = (int(h[lane, 0]), int(h[lane, 1]),
                                       int(h[lane, 2]))
        if status == STATUS_OVERFLOW:
            escalate.append(hist_idx)
        elif status == STATUS_FAILED:
            # witness: the lane's frontier emptied; its refuting op rides
            out[hist_idx] = {
                "valid": False, "analyzer": "wgl-tpu-megabatch",
                "op": preps[hist_idx].ops[failed_op].to_dict(),
                "configs-explored": explored}
        else:
            out[hist_idx] = {"valid": True,
                             "analyzer": "wgl-tpu-megabatch",
                             "configs-explored": explored}
        grp.slots[lane] = None
        grp.host_len[lane] = 0
        _bump(lanes_retired=1)
        if refill and staging:
            _fill(grp, [lane], staging, preps, cc)
            freed.append(lane)
    return freed
