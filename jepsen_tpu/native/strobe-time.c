/* Oscillate the system realtime clock between now and now+delta.
 *
 * Usage: strobe-time <delta-ms> <period-ms> <duration-ms>
 *
 * Every <period-ms> the clock flips between the true timeline and a
 * timeline offset by <delta-ms>, for <duration-ms> total.  Node-side helper
 * for the clock-skew nemesis; compiled on the target node.  Serves the role
 * of the reference's resources/strobe-time.c (independent implementation).
 */
#include <stdio.h>
#include <stdlib.h>
#include <time.h>
#include <unistd.h>

static void shift_clock(long long delta_ms) {
  struct timespec ts;
  if (clock_gettime(CLOCK_REALTIME, &ts) != 0) {
    perror("clock_gettime");
    exit(1);
  }
  long long ns = ts.tv_nsec + (delta_ms % 1000) * 1000000LL;
  ts.tv_sec += delta_ms / 1000 + ns / 1000000000LL;
  ts.tv_nsec = ns % 1000000000LL;
  if (ts.tv_nsec < 0) {
    ts.tv_nsec += 1000000000LL;
    ts.tv_sec -= 1;
  }
  if (clock_settime(CLOCK_REALTIME, &ts) != 0) {
    perror("clock_settime");
    exit(1);
  }
}

int main(int argc, char **argv) {
  if (argc != 4) {
    fprintf(stderr, "usage: %s <delta-ms> <period-ms> <duration-ms>\n",
            argv[0]);
    return 2;
  }
  long long delta_ms = atoll(argv[1]);
  long long period_ms = atoll(argv[2]);
  long long duration_ms = atoll(argv[3]);
  if (period_ms <= 0) {
    fprintf(stderr, "period must be positive\n");
    return 2;
  }

  long long elapsed = 0;
  int shifted = 0;
  while (elapsed < duration_ms) {
    shift_clock(shifted ? -delta_ms : delta_ms);
    shifted = !shifted;
    usleep((useconds_t)(period_ms * 1000));
    elapsed += period_ms;
  }
  if (shifted) shift_clock(-delta_ms); /* leave the clock where we found it */
  return 0;
}
