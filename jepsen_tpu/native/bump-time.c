/* Jump the system realtime clock by a signed number of milliseconds.
 *
 * Usage: bump-time <delta-ms>
 *
 * Node-side helper for the clock-skew nemesis (jepsen_tpu.nemesis.time);
 * compiled on the target node with `gcc -O2 -o bump-time bump-time.c`.
 * Serves the role of the reference's resources/bump-time.c (independent
 * implementation).
 */
#include <stdio.h>
#include <stdlib.h>
#include <time.h>

int main(int argc, char **argv) {
  if (argc != 2) {
    fprintf(stderr, "usage: %s <delta-ms>\n", argv[0]);
    return 2;
  }
  long long delta_ms = atoll(argv[1]);

  struct timespec ts;
  if (clock_gettime(CLOCK_REALTIME, &ts) != 0) {
    perror("clock_gettime");
    return 1;
  }

  long long ns = ts.tv_nsec + (delta_ms % 1000) * 1000000LL;
  ts.tv_sec += delta_ms / 1000 + ns / 1000000000LL;
  ts.tv_nsec = ns % 1000000000LL;
  if (ts.tv_nsec < 0) {
    ts.tv_nsec += 1000000000LL;
    ts.tv_sec -= 1;
  }

  if (clock_settime(CLOCK_REALTIME, &ts) != 0) {
    perror("clock_settime");
    return 1;
  }
  return 0;
}
