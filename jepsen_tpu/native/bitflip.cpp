// Flip random bits in a file, corrupting it in place.
//
// Usage: bitflip <path> <probability>
//
// Each byte of the file independently has its lowest-entropy corruption:
// with probability p, one random bit of that byte is flipped.  Node-side
// helper for the bit-rot nemesis (jepsen_tpu.nemesis.faults.Bitflip);
// compiled on the target node with g++.  Plays the role the reference
// fills by downloading a prebuilt Go binary (independent implementation).

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <random>
#include <vector>

int main(int argc, char **argv) {
  if (argc != 3) {
    std::fprintf(stderr, "usage: %s <path> <probability>\n", argv[0]);
    return 2;
  }
  const char *path = argv[1];
  double p = std::atof(argv[2]);
  if (p <= 0 || p > 1) {
    std::fprintf(stderr, "probability must be in (0, 1]\n");
    return 2;
  }

  std::FILE *f = std::fopen(path, "r+b");
  if (!f) {
    std::perror("fopen");
    return 1;
  }
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  if (size <= 0) {
    std::fclose(f);
    return 0;
  }

  std::random_device rd;
  std::mt19937_64 rng(rd());
  std::uniform_real_distribution<double> coin(0.0, 1.0);
  std::uniform_int_distribution<int> bit(0, 7);

  const long CHUNK = 1 << 20;
  std::vector<unsigned char> buf(CHUNK);
  long flipped = 0;
  for (long off = 0; off < size; off += CHUNK) {
    long n = std::min(CHUNK, size - off);
    std::fseek(f, off, SEEK_SET);
    if (std::fread(buf.data(), 1, n, f) != (size_t)n) break;
    bool dirty = false;
    for (long i = 0; i < n; i++) {
      if (coin(rng) < p) {
        buf[i] ^= (1u << bit(rng));
        dirty = true;
        flipped++;
      }
    }
    if (dirty) {
      std::fseek(f, off, SEEK_SET);
      std::fwrite(buf.data(), 1, n, f);
    }
  }
  std::fclose(f);
  std::printf("%ld bits flipped\n", flipped);
  return 0;
}
