/* Oscillate the realtime clock, paced by the MONOTONIC clock.
 *
 * Usage: strobe-time-mono <delta-ms> <period-ms> <duration-ms>
 *
 * The plain strobe-time sleeps a relative period each flip, so loop
 * overhead and scheduling jitter accumulate phase drift over long strobes.
 * This variant captures a realtime<->monotonic correspondence once, then
 * flips on ABSOLUTE monotonic deadlines (clock_nanosleep TIMER_ABSTIME)
 * and recomputes the target realtime from the monotonic clock at every
 * flip — the strobe stays phase-accurate for its whole duration however
 * noisy the scheduler is.  Role of the reference's
 * resources/strobe-time-experiment.c (independent implementation).
 */
#include <stdio.h>
#include <stdlib.h>
#include <time.h>

static const long long NS = 1000000000LL;

static long long to_ns(struct timespec t) {
  return t.tv_sec * NS + t.tv_nsec;
}

static struct timespec from_ns(long long ns) {
  struct timespec t;
  t.tv_sec = ns / NS;
  t.tv_nsec = ns % NS;
  if (t.tv_nsec < 0) {
    t.tv_nsec += NS;
    t.tv_sec -= 1;
  }
  return t;
}

int main(int argc, char **argv) {
  if (argc != 4) {
    fprintf(stderr, "usage: %s <delta-ms> <period-ms> <duration-ms>\n",
            argv[0]);
    return 2;
  }
  long long delta_ns = atoll(argv[1]) * 1000000LL;
  long long period_ns = atoll(argv[2]) * 1000000LL;
  long long duration_ns = atoll(argv[3]) * 1000000LL;
  if (period_ns <= 0) {
    fprintf(stderr, "period must be positive\n");
    return 2;
  }

  struct timespec mono, real;
  if (clock_gettime(CLOCK_MONOTONIC, &mono) != 0 ||
      clock_gettime(CLOCK_REALTIME, &real) != 0) {
    perror("clock_gettime");
    return 1;
  }
  /* true realtime = monotonic + base, by this one-shot correspondence */
  long long base = to_ns(real) - to_ns(mono);
  long long start = to_ns(mono);
  long long end = start + duration_ns;

  int phase = 1;
  for (long long deadline = start; deadline < end;
       deadline += period_ns, phase = !phase) {
    struct timespec now;
    clock_gettime(CLOCK_MONOTONIC, &now);
    /* derive the target from the monotonic clock, not from the (already
       strobed) realtime clock, so errors never compound */
    long long target = to_ns(now) + base + (phase ? delta_ns : 0);
    struct timespec t = from_ns(target);
    if (clock_settime(CLOCK_REALTIME, &t) != 0) {
      perror("clock_settime");
      return 1;
    }
    struct timespec d = from_ns(deadline + period_ns);
    clock_nanosleep(CLOCK_MONOTONIC, TIMER_ABSTIME, &d, NULL);
  }

  /* leave the clock on the true timeline */
  struct timespec now;
  clock_gettime(CLOCK_MONOTONIC, &now);
  struct timespec t = from_ns(to_ns(now) + base);
  if (clock_settime(CLOCK_REALTIME, &t) != 0) {
    perror("clock_settime");
    return 1;
  }
  return 0;
}
