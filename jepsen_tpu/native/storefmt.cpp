// jepsen-tpu binary store format — append-only CRC32-checked blocks.
//
// Plays the role of the reference's custom .jepsen block file + positioned
// output stream (store/format.clj, FileOffsetOutputStream.java):
// crash-safe appends for larger-than-memory histories.  Independent design:
//
//   file   := magic blocks*            magic = "JTSF0001" (8 bytes)
//   block  := len:u32le crc:u32le tag:u8 payload[len]
//             crc = CRC32(tag || payload)
//
// Built as a shared library (ctypes); the Python side falls back to a pure
// implementation of the same format when no compiler is available.

#include <cstdint>
#include <cstdio>
#include <cstring>

namespace {

const char MAGIC[8] = {'J', 'T', 'S', 'F', '0', '0', '0', '1'};

uint32_t crc_table[256];
bool crc_init_done = false;

void crc_init() {
  if (crc_init_done) return;
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++)
      c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc_table[i] = c;
  }
  crc_init_done = true;
}

uint32_t crc32_update(uint32_t crc, const uint8_t *buf, size_t len) {
  crc_init();
  crc ^= 0xFFFFFFFFu;
  for (size_t i = 0; i < len; i++)
    crc = crc_table[(crc ^ buf[i]) & 0xFF] ^ (crc >> 8);
  return crc ^ 0xFFFFFFFFu;
}

}  // namespace

extern "C" {

// Open for append; writes the magic if the file is new/empty.
// Returns a FILE* handle as void*, or null on failure.
void *jtsf_open(const char *path) {
  FILE *f = std::fopen(path, "ab+");
  if (!f) return nullptr;
  std::fseek(f, 0, SEEK_END);
  if (std::ftell(f) == 0) {
    if (std::fwrite(MAGIC, 1, 8, f) != 8) {
      std::fclose(f);
      return nullptr;
    }
    std::fflush(f);
  }
  return f;
}

// Append one block; returns 0 on success.
int jtsf_append(void *handle, uint8_t tag, const uint8_t *data,
                uint32_t len) {
  FILE *f = static_cast<FILE *>(handle);
  uint32_t crc = crc32_update(0, &tag, 1);
  crc = crc32_update(crc, data, len);
  uint8_t hdr[9];
  std::memcpy(hdr, &len, 4);
  std::memcpy(hdr + 4, &crc, 4);
  hdr[8] = tag;
  if (std::fwrite(hdr, 1, 9, f) != 9) return 1;
  if (len && std::fwrite(data, 1, len, f) != len) return 1;
  return 0;
}

int jtsf_flush(void *handle) {
  return std::fflush(static_cast<FILE *>(handle));
}

int jtsf_close(void *handle) {
  return std::fclose(static_cast<FILE *>(handle));
}

// Verify a whole file's structure and checksums.
// Returns the number of valid blocks, or -1 - <block#> on first corruption.
long jtsf_verify(const char *path) {
  FILE *f = std::fopen(path, "rb");
  if (!f) return -1;
  char magic[8];
  if (std::fread(magic, 1, 8, f) != 8 || std::memcmp(magic, MAGIC, 8)) {
    std::fclose(f);
    return -1;
  }
  long n = 0;
  for (;;) {
    uint8_t hdr[9];
    size_t got = std::fread(hdr, 1, 9, f);
    if (got == 0) break;
    if (got != 9) { std::fclose(f); return -1 - n; }
    uint32_t len, crc;
    std::memcpy(&len, hdr, 4);
    std::memcpy(&crc, hdr + 4, 4);
    uint32_t actual = crc32_update(0, hdr + 8, 1);
    const size_t CH = 1 << 20;
    static uint8_t buf[1 << 20];
    uint32_t left = len;
    while (left) {
      size_t want = left < CH ? left : CH;
      if (std::fread(buf, 1, want, f) != want) { std::fclose(f); return -1 - n; }
      actual = crc32_update(actual, buf, want);
      left -= want;
    }
    if (actual != crc) { std::fclose(f); return -1 - n; }
    n++;
  }
  std::fclose(f);
  return n;
}

uint32_t jtsf_crc32(const uint8_t *data, uint32_t len) {
  return crc32_update(0, data, len);
}

}  // extern "C"
