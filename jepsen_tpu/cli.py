"""Command-line runner for test suites.

Parity: jepsen.cli (jepsen/src/jepsen/cli.clj): a shared option vocabulary
(nodes, ssh, concurrency with the "3n" syntax, time limits, repeat counts —
cli.clj:64-168), a ``test`` subcommand built from a suite's test function
(single-test-cmd, cli.clj:355), ``test-all`` sweeps (cli.clj:491), an
``analyze`` mode for re-checking stored histories (the store/REPL pattern),
and ``serve`` for the results browser.  Beyond the reference: ``submit``
POSTs a stored history to a running serve, and ``trace`` fetches a
request's merged distributed trace (optionally exporting Chrome
trace-event JSON for ui.perfetto.dev).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Callable, Dict, List, Optional, Sequence

from jepsen_tpu import core, store


def add_test_opts(p: argparse.ArgumentParser) -> None:
    """Shared test options (cli.clj:64-111 test-opt-spec)."""
    p.add_argument("--node", "-n", action="append", dest="nodes",
                   help="node hostname (repeatable)")
    p.add_argument("--nodes", dest="nodes_csv",
                   help="comma-separated node list")
    p.add_argument("--nodes-file", help="file with one node per line")
    p.add_argument("--username", default="root")
    p.add_argument("--password")
    p.add_argument("--ssh-private-key")
    p.add_argument("--ssh-port", type=int, default=22)
    p.add_argument("--dummy-ssh", action="store_true",
                   help="no-op control plane (in-process testing)")
    p.add_argument("--dummy-ssh-record", action="store_true",
                   help="record-only control plane: log commands, execute "
                        "nothing (smoke-tests suite control logic)")
    p.add_argument("--no-ssh", action="store_true",
                   help="never open SSH connections (cli.clj:85); "
                        "control commands are recorded, not executed")
    p.add_argument("--strict-host-key-checking", action="store_true",
                   help="verify SSH host keys (cli.clj:82; default off, "
                        "like the reference's default)")
    p.add_argument("--concurrency", "-c", default="1n",
                   help="worker count; '3n' = 3x node count")
    p.add_argument("--time-limit", type=float, default=60.0,
                   help="workload duration in seconds")
    p.add_argument("--test-count", type=int, default=1,
                   help="how many times to run the test")
    p.add_argument("--leave-db-running", action="store_true")
    p.add_argument("--logging-json", action="store_true",
                   help="jepsen.log as JSON lines (cli.clj:98)")
    p.add_argument("--store", default="store", help="results directory")
    p.add_argument("--monitor", action="store_true",
                   help="check the run online: stream ops into the "
                        "checker during the run, refute early, resume "
                        "the final check from monitor state")
    p.add_argument("--monitor-epoch", type=int, default=None,
                   help="monitor epoch size in ops (default 256)")
    p.add_argument("--monitor-abort", action="store_true",
                   help="cut the generator as soon as the monitor "
                        "confirms a refutation")


def parse_nodes(args) -> List[str]:
    if args.nodes:
        return args.nodes
    if getattr(args, "nodes_csv", None):
        return [n.strip() for n in args.nodes_csv.split(",") if n.strip()]
    if args.nodes_file:
        with open(args.nodes_file) as f:
            return [l.strip() for l in f if l.strip()]
    return ["n1", "n2", "n3", "n4", "n5"]  # cli.clj:18 default


def test_opts_to_map(args) -> Dict[str, Any]:
    return {
        "nodes": parse_nodes(args),
        "ssh": {"username": args.username,
                "password": args.password,
                "private_key_path": args.ssh_private_key,
                "port": args.ssh_port,
                "strict_host_key_checking":
                    getattr(args, "strict_host_key_checking", False),
                "dummy": "record"
                if (getattr(args, "dummy_ssh_record", False)
                    or getattr(args, "no_ssh", False))
                else args.dummy_ssh},
        "concurrency": args.concurrency,
        "time_limit": args.time_limit,
        "leave_db_running": args.leave_db_running,
        "logging_json": getattr(args, "logging_json", False),
        "store_base": args.store,
        "monitor": getattr(args, "monitor", False),
        "monitor_epoch": getattr(args, "monitor_epoch", None),
        "monitor_abort": getattr(args, "monitor_abort", False),
    }


def single_test_cmd(test_fn: Callable[[Dict[str, Any]], Dict[str, Any]],
                    opt_fn: Optional[Callable] = None,
                    argv: Optional[Sequence[str]] = None,
                    prog: str = "jepsen-tpu") -> int:
    """Build and run the standard CLI around a suite's test constructor
    (cli.clj:355 single-test-cmd).  ``opt_fn`` may add suite options."""
    parser = argparse.ArgumentParser(prog=prog)
    sub = parser.add_subparsers(dest="cmd", required=True)

    pt = sub.add_parser("test", help="run one test")
    add_test_opts(pt)
    if opt_fn:
        opt_fn(pt)

    pa = sub.add_parser("analyze", help="re-check a stored run")
    pa.add_argument("dir", help="store run directory (or .../latest)")

    ps = sub.add_parser("serve",
                        help="results web browser + checking service")
    ps.add_argument("--port", type=int, default=8080)
    ps.add_argument("--store", default="store")
    ps.add_argument("--no-service", action="store_true",
                    help="results browser only, no checking service")
    ps.add_argument("--max-lanes", type=int, default=64,
                    help="lanes per device dispatch")
    ps.add_argument("--max-queue", type=int, default=4096,
                    help="admission-control queue depth (cells)")
    ps.add_argument("--workers", type=int, default=3,
                    help="checking-service worker replicas (the fault-"
                         "tolerant fleet; 1 = a single CheckService)")
    ps.add_argument("--journal-dir", default=None,
                    help="fleet in-flight journal directory (default "
                         "<store>/fleet-journal); 'none' disables "
                         "crash journaling")
    ps.add_argument("--procs", action="store_true",
                    help="run fleet workers as real OS processes behind "
                         "the wire protocol (serve/transport.py), each "
                         "dialed through a chaos-controllable net_proxy "
                         "link; implies the fleet path even with "
                         "--workers 1")
    ps.add_argument("--telemetry-s", type=float, default=None,
                    help="worker telemetry push interval in seconds "
                         "(default JEPSEN_TPU_TELEMETRY_S or 1.0; <= 0 "
                         "disables the push plane)")
    ps.add_argument("--recorder", action="store_true",
                    help="arm the flight recorder at startup (fleet-wide "
                         "with --procs); also togglable at runtime via "
                         "POST /recorder?on=1")

    pf = sub.add_parser("fleet",
                        help="run a fleetport: the multi-host control "
                             "plane workers register with "
                             "(serve/fleetport.py)")
    pf.add_argument("--listen", default="0.0.0.0:7600",
                    metavar="HOST:PORT",
                    help="address the REGISTER/renewal listener binds "
                         "(default 0.0.0.0:7600)")
    pf.add_argument("--port", type=int, default=8080,
                    help="web port (GET /fleet, /metrics, /healthz)")
    pf.add_argument("--store", default="store")
    pf.add_argument("--lease-s", type=float, default=None,
                    help="worker lease duration in seconds (default "
                         "JEPSEN_TPU_LEASE_S or 10)")
    pf.add_argument("--max-lanes", type=int, default=64)
    pf.add_argument("--max-queue", type=int, default=4096)
    pf.add_argument("--journal-dir", default=None,
                    help="in-flight journal directory (default "
                         "<store>/fleet-journal); 'none' disables")
    pf.add_argument("--telemetry-s", type=float, default=None)

    pq = sub.add_parser("submit",
                        help="submit a stored history to a running serve")
    pq.add_argument("dir", help="store run directory (or .../latest)")
    pq.add_argument("--url", default="http://127.0.0.1:8080",
                    help="base URL of the running serve")
    pq.add_argument("--kind", choices=["wgl", "elle"], default="wgl")
    pq.add_argument("--model", default="cas-register",
                    help="device model name (wgl kind)")
    pq.add_argument("--workload", default="list-append",
                    help="elle workload (elle kind)")
    pq.add_argument("--realtime", action="store_true")
    pq.add_argument("--independent", action="store_true",
                    help="history is an independent workload: restore "
                         "[k, v] values to keyed tuples so the service "
                         "splits per key")
    pq.add_argument("--deadline", type=float, default=None,
                    help="per-request deadline in seconds")
    pq.add_argument("--tenant", default=None,
                    help="attribute the request to this tenant (quota, "
                         "priority, per-tenant SLO cut)")
    pq.add_argument("--tenant-token", default=None,
                    help="tenant auth token, sent as X-Tenant-Token; "
                         "defaults to JEPSEN_TPU_TENANT_TOKEN from the "
                         "environment (prefer the env — argv leaks into "
                         "process listings)")

    ptr = sub.add_parser("trace",
                         help="fetch a request's merged distributed trace "
                              "from a running serve")
    ptr.add_argument("request_id", help="request id (serve.request-id in a "
                                        "verdict, or X-Request-Id)")
    ptr.add_argument("--url", default="http://127.0.0.1:8080",
                     help="base URL of the running serve")
    ptr.add_argument("--perfetto", metavar="PATH", default=None,
                     help="also write the trace as Chrome trace-event JSON "
                          "to PATH (load it at ui.perfetto.dev)")

    args = parser.parse_args(argv)

    if args.cmd == "test":
        from jepsen_tpu.ops.cache import init_compilation_cache
        init_compilation_cache(args.store)
        opts = test_opts_to_map(args)
        for k, v in vars(args).items():
            if k not in opts and v is not None:
                opts[k.replace("-", "_")] = v
        failures = 0
        for i in range(args.test_count):
            test = test_fn(dict(opts))
            done = core.run(test)
            valid = done.get("results", {}).get("valid")
            print(json.dumps({"run": i, "dir": done.get("store_dir"),
                              "valid": valid}))
            if valid is not True:
                failures += 1
        return 1 if failures else 0

    if args.cmd == "analyze":
        test = store.load_test(args.dir)
        history = store.load_history(args.dir)
        full = test_fn(test)  # rebuild checker from suite
        results = core.analyze(full, history)
        print(json.dumps(results, indent=2, default=str))
        return 0 if results.get("valid") is True else 1

    if args.cmd == "serve":
        from jepsen_tpu.web import serve
        service = None
        if not args.no_service:
            # The fleet is the default serving path: N worker services
            # behind the fault-tolerant router (serve/fleet.py).
            # --workers 1 keeps the old single-service behaviour.
            if max(1, args.workers) > 1 or args.procs:
                from jepsen_tpu.serve.fleet import Fleet, ProcFleet
                jdir = args.journal_dir
                if jdir is None:
                    jdir = os.path.join(args.store, "fleet-journal")
                elif jdir == "none":
                    jdir = None
                fleet_cls = ProcFleet if args.procs else Fleet
                service = fleet_cls(workers=args.workers,
                                    store_base=args.store,
                                    journal_dir=jdir,
                                    max_lanes=args.max_lanes,
                                    max_queue_cells=args.max_queue,
                                    telemetry_s=args.telemetry_s)
            else:
                from jepsen_tpu.serve import CheckService
                service = CheckService(store_base=args.store,
                                       max_lanes=args.max_lanes,
                                       max_queue_cells=args.max_queue)
        if args.recorder:
            setter = getattr(service, "set_recorder", None)
            if setter is not None:
                setter(True)
            else:
                from jepsen_tpu.obs.recorder import RECORDER
                RECORDER.enable()
        # SIGTERM must reach the finally below: with --procs the workers
        # are setsid'd OS processes — dying without service.close() would
        # orphan them (SIGINT already raises KeyboardInterrupt).
        import signal

        def _term(signum, frame):  # noqa: ARG001 — signal signature
            raise SystemExit(143)

        try:
            signal.signal(signal.SIGTERM, _term)
        except ValueError:  # not the main thread (library-embedded call)
            pass
        try:
            serve(base=args.store, port=args.port, service=service)
        finally:
            if service is not None:
                service.close(timeout=30.0)
        return 0

    if args.cmd == "fleet":
        from jepsen_tpu.serve.fleetport import Fleetport
        from jepsen_tpu.web import serve
        lhost, _, lport = args.listen.rpartition(":")
        jdir = args.journal_dir
        if jdir is None:
            jdir = os.path.join(args.store, "fleet-journal")
        elif jdir == "none":
            jdir = None
        service = Fleetport(listen_host=lhost or "0.0.0.0",
                            listen_port=int(lport),
                            lease_s=args.lease_s,
                            store_base=args.store,
                            journal_dir=jdir,
                            max_lanes=args.max_lanes,
                            max_queue_cells=args.max_queue,
                            telemetry_s=args.telemetry_s)
        print(json.dumps({
            "fleetport": {"host": service.listen_host,
                          "port": service.listen_port},
            "lease-s": service.registry.lease_s,
            # boolean only — the token itself is never printed
            "auth-enabled": bool(service._token)}), flush=True)
        import signal as _signal

        def _fterm(signum, frame):  # noqa: ARG001 — signal signature
            raise SystemExit(143)

        try:
            _signal.signal(_signal.SIGTERM, _fterm)
        except ValueError:  # not the main thread
            pass
        try:
            serve(base=args.store, port=args.port, service=service)
        finally:
            service.close(timeout=30.0)
        return 0

    if args.cmd == "submit":
        return submit_cmd(args)

    if args.cmd == "trace":
        return trace_cmd(args)

    return 2


def submit_cmd(args) -> int:
    """POST a stored run's history to a running serve's /submit endpoint
    and print the verdict JSON."""
    import urllib.request
    history = store.load_history(args.dir)
    body = {"ops": [op.to_dict() for op in history],
            "kind": args.kind, "realtime": args.realtime,
            "independent": args.independent}
    if args.kind == "wgl":
        body["model"] = args.model
    else:
        body["workload"] = args.workload
    if args.deadline is not None:
        body["deadline_s"] = args.deadline
    headers = {"Content-Type": "application/json"}
    if args.tenant is not None:
        body["tenant"] = args.tenant
        token = args.tenant_token \
            or os.environ.get("JEPSEN_TPU_TENANT_TOKEN", "")
        if token:
            headers["X-Tenant-Token"] = token
    req = urllib.request.Request(
        args.url.rstrip("/") + "/submit",
        data=json.dumps(body).encode(),
        headers=headers, method="POST")
    with urllib.request.urlopen(req) as resp:
        results = json.loads(resp.read())
    print(json.dumps(results, indent=2, default=str))
    return 0 if results.get("valid") is True else 1


def trace_cmd(args) -> int:
    """GET /trace/<request-id> from a running serve and print the merged
    causal tree; ``--perfetto PATH`` additionally exports it as Chrome
    trace-event JSON for ui.perfetto.dev."""
    import urllib.error
    import urllib.request
    url = f"{args.url.rstrip('/')}/trace/{args.request_id}"
    try:
        with urllib.request.urlopen(url) as resp:
            trace = json.loads(resp.read())
    except urllib.error.HTTPError as e:
        print(json.dumps({"error": f"HTTP {e.code}: {e.read().decode()}"}),
              file=sys.stderr)
        return 1
    print(json.dumps(trace, indent=2, default=str))
    if args.perfetto:
        from jepsen_tpu.obs.trace import chrome_events_from_trace, write_chrome
        write_chrome(args.perfetto, chrome_events_from_trace(trace))
        print(f"perfetto export: {args.perfetto}", file=sys.stderr)
    return 0


def test_all_cmd(tests_fn: Callable[[Dict[str, Any]], List[Dict[str, Any]]],
                 opt_fn: Optional[Callable] = None,
                 argv: Optional[Sequence[str]] = None) -> int:
    """Run a suite's whole sweep matrix (cli.clj:433-519).

    The whole campaign shares one checking service: every test's analyze
    phase routes through a single CheckService, so the sweep's histories
    are continuously batched onto the device engines and compiled shapes
    are reused across tests.  ``--campaign-workers N`` overlaps N runs
    (their checks coalesce into shared dispatches); ``--no-service``
    restores the per-test direct checker path."""
    parser = argparse.ArgumentParser()
    add_test_opts(parser)
    parser.add_argument("--campaign-workers", type=int, default=1,
                        help="concurrent test runs in the sweep")
    parser.add_argument("--no-service", action="store_true",
                        help="check each test directly, no shared service")
    if opt_fn:
        opt_fn(parser)
    args = parser.parse_args(argv)
    opts = test_opts_to_map(args)
    service = None
    if not args.no_service:
        from jepsen_tpu.serve import CheckService
        service = CheckService(store_base=args.store)
    try:
        summary = core.run_tests(tests_fn(dict(opts)),
                                 workers=max(1, args.campaign_workers),
                                 service=service)
    finally:
        if service is not None:
            service.close(timeout=60.0)
    for r in summary["results"]:
        print(json.dumps(r, default=str))
    print(json.dumps({"failures": summary["failures"],
                      "unknown": summary["unknown"]}))
    return summary["exit"]


def _main() -> int:
    """`python -m jepsen_tpu.cli` — suite-less entry point: analyze a
    stored run (stats-only: the persisted test map carries no checker
    objects) or serve the results browser (cli.clj:521's -main).
    Running a *test* needs a suite module's test function — refuse it
    rather than report an empty workload as valid."""
    def test_fn(opts: Dict[str, Any]) -> Dict[str, Any]:
        if "checker" not in opts:
            from jepsen_tpu.checker import Stats
            opts = {**opts, "checker": Stats()}
        return opts

    if sys.argv[1:2] == ["test"]:
        print("jepsen-tpu: `test` needs a suite runner "
              "(python -m suites.<name>.runner test ...); the bare module "
              "only supports analyze/serve", file=sys.stderr)
        return 2
    return single_test_cmd(test_fn, prog="jepsen-tpu")


if __name__ == "__main__":
    sys.exit(_main())
