"""Workload kits — reusable generator+checker bundles.

Parity: jepsen.tests.* (jepsen/src/jepsen/tests/): each workload returns a
dict {generator, checker, client-ops...} a suite merges into its test map.
"""
