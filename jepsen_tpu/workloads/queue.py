"""Queue workload: enqueues/dequeues with a final drain.

Parity: the queue workloads of the disque/rabbitmq suites
(disque/src/jepsen/disque.clj:280-300, rabbitmq/src/jepsen/rabbitmq.clj)
checked with checker/total-queue (jepsen/src/jepsen/checker.clj:628):
every enqueued element should be dequeued exactly once; duplicates and
losses are counted, unacked in-flight elements tolerated per the queue's
contract.
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Dict

from jepsen_tpu import generator as gen
from jepsen_tpu.checker.core import TotalQueueChecker


def enq_deq(enq_p: float = 0.5):
    counter = itertools.count()

    def one():
        if random.random() < enq_p:
            return {"f": "enqueue", "value": next(counter)}
        return {"f": "dequeue"}

    return gen.FnGen(one)


def drain():
    """Each thread drains until exhaustion (disque.clj's :drain op)."""
    return gen.each_thread(gen.once({"f": "drain"}))


def workload(enq_p: float = 0.5) -> Dict[str, Any]:
    return {"generator": enq_deq(enq_p),
            "final_generator": drain(),
            "checker": TotalQueueChecker()}
