"""Kafka-style log workload: sends, polls, and offset/order analyses.

Parity: jepsen.tests.kafka (jepsen/src/jepsen/tests/kafka.clj): transactions
of ``send``/``poll`` micro-ops against partitioned logs, analyzed for
log-specific anomalies (kafka.clj's lost-write, duplicate, aborted-read,
poll-skip, nonmonotonic-poll, unseen analyses, checker at kafka.clj:2049,
workload at 2106).

Op language (completed mops):
  ["send", k, [offset, value]]    — producer appended value at offset
                                    (invocation carries ["send", k, value])
  ["poll", {k: [[offset, value], ...]}]
                                  — consumer read records, per partition

Anomalies:
  duplicate        — one value at multiple offsets of a partition
  lost-write       — acked send never seen although later offsets of the
                     same partition were observed by some poll
  aborted-read     — polled value from a failed send
  poll-skip        — a process's consecutive polls of a partition skip over
                     offsets that are known to exist
  nonmonotonic-poll— a process's poll rewinds behind its previous position
  internal-nonmonotonic — offsets within one poll not strictly ascending
  unseen           — committed values never observed by any poll (info)
"""

from __future__ import annotations

import itertools
import random
from collections import defaultdict
from typing import Any, Dict, List, Optional, Tuple

from jepsen_tpu import generator as gen
from jepsen_tpu.checker.core import Checker, UNKNOWN
from jepsen_tpu.history import FAIL, History, OK


def generator(partitions: int = 4, max_mops: int = 3):
    counter = itertools.count(1)

    def one():
        mops = []
        for _ in range(random.randint(1, max_mops)):
            k = random.randrange(partitions)
            if random.random() < 0.5:
                mops.append(["send", k, next(counter)])
            else:
                mops.append(["poll", {}])
        return {"f": "txn", "value": mops}

    return gen.FnGen(one)


class KafkaChecker(Checker):
    def check(self, test, history: History, opts=None):
        sends_ok: Dict[Tuple[Any, int], Any] = {}   # (k, offset) -> value
        send_of_value: Dict[Tuple[Any, Any], int] = {}  # (k, value) -> offset
        failed_values: set = set()                   # (k, value) of failed sends
        polls: List[Tuple[Any, Dict]] = []           # (process, {k: [[o,v]..]})
        anomalies: Dict[str, List[Any]] = defaultdict(list)

        for op in history:
            if not isinstance(op.value, (list, tuple)):
                continue
            if op.type == OK:
                for mop in op.value:
                    if mop[0] == "send":
                        k, ov = mop[1], mop[2]
                        if isinstance(ov, (list, tuple)) and len(ov) == 2:
                            o, v = ov
                            if (k, o) in sends_ok and sends_ok[(k, o)] != v:
                                anomalies["offset-conflict"].append(
                                    {"key": k, "offset": o,
                                     "values": [sends_ok[(k, o)], v]})
                            if (k, v) in send_of_value and \
                                    send_of_value[(k, v)] != o:
                                anomalies["duplicate"].append(
                                    {"key": k, "value": v,
                                     "offsets": [send_of_value[(k, v)], o]})
                            sends_ok[(k, o)] = v
                            send_of_value[(k, v)] = o
                    elif mop[0] == "poll" and isinstance(mop[1], dict):
                        polls.append((op.process, mop[1]))
            elif op.type == FAIL:
                for mop in op.value:
                    if mop[0] == "send":
                        failed_values.add((mop[1], mop[2]))

        # observed offsets per partition + in-poll order + aborted reads
        observed: Dict[Any, set] = defaultdict(set)
        for proc, pd in polls:
            for k, recs in pd.items():
                last = None
                for o, v in recs:
                    observed[k].add(o)
                    if (k, v) in failed_values:
                        anomalies["aborted-read"].append(
                            {"key": k, "offset": o, "value": v})
                    if (k, o) in sends_ok and sends_ok[(k, o)] != v:
                        anomalies["poll-send-mismatch"].append(
                            {"key": k, "offset": o,
                             "polled": v, "sent": sends_ok[(k, o)]})
                    if (k, v) in send_of_value and \
                            send_of_value[(k, v)] != o:
                        anomalies["duplicate"].append(
                            {"key": k, "value": v,
                             "offsets": [send_of_value[(k, v)], o]})
                    if last is not None and o <= last:
                        anomalies["internal-nonmonotonic"].append(
                            {"key": k, "offsets": [last, o]})
                    last = o

        # per-process poll position tracking: skips and rewinds
        pos: Dict[Tuple[Any, Any], int] = {}  # (process, k) -> last offset
        for proc, pd in polls:
            for k, recs in pd.items():
                if not recs:
                    continue
                first, last = recs[0][0], recs[-1][0]
                prev = pos.get((proc, k))
                if prev is not None:
                    if first <= prev:
                        anomalies["nonmonotonic-poll"].append(
                            {"process": proc, "key": k,
                             "prev": prev, "rewound-to": first})
                    else:
                        skipped = [o for o in range(prev + 1, first)
                                   if (k, o) in sends_ok or o in observed[k]]
                        if skipped:
                            anomalies["poll-skip"].append(
                                {"process": proc, "key": k,
                                 "prev": prev, "next": first,
                                 "skipped": skipped})
                pos[(proc, k)] = last

        # lost writes: acked send at offset o never observed, while some
        # poll observed an offset > o in that partition
        for (k, o), v in sends_ok.items():
            if o in observed[k]:
                continue
            if observed[k] and max(observed[k]) > o:
                anomalies["lost-write"].append({"key": k, "offset": o,
                                                "value": v})
        unseen = [{"key": k, "offset": o, "value": v}
                  for (k, o), v in sends_ok.items()
                  if o not in observed[k]
                  and not (observed[k] and max(observed[k]) > o)]

        hard = {k: v for k, v in anomalies.items()}
        return {"valid": (UNKNOWN if (not hard and unseen and not polls)
                          else not hard),
                "anomaly-types": sorted(hard),
                "anomalies": {k: v[:8] for k, v in hard.items()},
                "sends": len(sends_ok), "polls": len(polls),
                "unseen-count": len(unseen), "unseen": unseen[:8]}


def workload(partitions: int = 4) -> Dict[str, Any]:
    return {"generator": generator(partitions), "checker": KafkaChecker()}
