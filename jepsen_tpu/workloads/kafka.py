"""Kafka-style log workload: sends, polls, and offset/order analyses.

Parity: jepsen.tests.kafka (jepsen/src/jepsen/tests/kafka.clj): transactions
of ``send``/``poll`` micro-ops against partitioned logs, plus consumer-group
``assign``/``subscribe`` control ops, analyzed for log-specific anomalies
(kafka.clj's lost-write, duplicate, aborted-read, poll-skip,
nonmonotonic-poll, int-send-skip, nonmonotonic-send, unseen analyses;
checker at kafka.clj:2049, workload at 2106).

Op language (completed mops):
  ["send", k, [offset, value]]    — producer appended value at offset
                                    (invocation carries ["send", k, value])
  ["poll", {k: [[offset, value], ...]}]
                                  — consumer read records, per partition
Control ops (not txns):
  {"f": "assign",    "value": [k, ...]}   — consumer now owns exactly these
                                            partitions; poll positions reset
  {"f": "subscribe", "value": [k, ...]}   — group-managed rebalance; same
                                            position-reset consequences
  {"f": "crash"}                          — consumer crashed; fresh state

Anomalies:
  duplicate          — one value at multiple offsets of a partition
  lost-write         — acked send never seen although later offsets of the
                       same partition were observed by some poll
  aborted-read       — polled value from a failed send
  poll-skip          — a process's consecutive polls of a partition (within
                       one assignment era) skip over known offsets
  nonmonotonic-poll  — a process's poll rewinds behind its previous
                       position within one assignment era
  internal-nonmonotonic — offsets within one poll mop not strictly ascending
  nonmonotonic-send  — consecutive sends to a partition within one txn
                       landed at non-increasing offsets
  int-send-skip      — consecutive sends to a partition within one txn
                       skipped over offsets known to exist
  offset-conflict    — two values acked at one (partition, offset)
  inconsistent-offsets — the cross-observation version order (every send and
                       every poll, including *recovered* indeterminate txns)
                       maps one (partition, offset) to several values
                       (kafka.clj:820-870 version-orders :errors)
  unseen             — committed values never observed by any poll (info)

Indeterminate-transaction recovery (kafka.clj:726-737
``must-have-committed?``): an :info transaction's sends join the committed
universe iff some OK poll observed one of its written values — those
recovered sends then participate in version orders, duplicates, lost-write
and unseen accounting exactly like acked ones.

Realtime lag (kafka.clj:1358-1460, 1564): for each OK poll, the
conservative lower bound on how stale its most-recent observed offset was
at poll invocation — ``worst-realtime-lag`` reports the maximum, per key
and globally, and ``realtime-lag.png`` plots lag over time per key.
"""

from __future__ import annotations

import itertools
import random
from collections import defaultdict
from typing import Any, Dict, List, Optional, Tuple

from jepsen_tpu import generator as gen
from jepsen_tpu.checker.core import Checker, UNKNOWN
from jepsen_tpu.elle.graph import Graph, cycle_edge_kinds, peeled_cycles
from jepsen_tpu.elle.list_append import classify_cycle
from jepsen_tpu.history import FAIL, History, INFO, INVOKE, OK


def _mops(op) -> List[Any]:
    if not isinstance(op.value, (list, tuple)):
        return []
    return [m for m in op.value if isinstance(m, (list, tuple)) and m]


def _send_pairs(op):
    """(k, offset, value) for send mops carrying an [offset, value] pair."""
    for m in _mops(op):
        if m[0] == "send":
            ov = m[2]
            if isinstance(ov, (list, tuple)) and len(ov) == 2:
                yield m[1], ov[0], ov[1]


def _send_values(op):
    """(k, value) for every send mop, acked or not (op-writes parity)."""
    for m in _mops(op):
        if m[0] == "send":
            ov = m[2]
            if isinstance(ov, (list, tuple)) and len(ov) == 2:
                yield m[1], ov[1]
            else:
                yield m[1], ov


def _poll_records(op):
    """(k, offset, value) for every polled record."""
    for m in _mops(op):
        if m[0] == "poll" and isinstance(m[1], dict):
            for k, recs in m[1].items():
                for o, v in recs:
                    yield k, o, v


# -- drill-down neighborhoods (kafka.clj:600-737) ---------------------------
#
# The reference keeps these as debug-inspection helpers for reading an
# error report: clip the history to just the mops around a suspect
# (key, offset) or (key, value), and index writes/reads by completion type.
# The checker attaches them to refuted results (see KafkaChecker.check) so
# an artifact shows the neighborhood of each anomaly.


def op_around_key_offset(k, offset, op, n: int = 3):
    """Trim ``op`` to the send/poll mops touching key ``k`` within ``n`` of
    ``offset``; None if nothing remains (op-around-key-offset,
    kafka.clj:600-628)."""
    if op.type == INVOKE or op.f not in ("send", "poll", "txn"):
        return None
    kept = []
    for m in _mops(op):
        if m[0] == "send":
            ov = m[2]
            if (m[1] == k and isinstance(ov, (list, tuple)) and len(ov) == 2
                    and ov[0] is not None
                    and offset - n <= ov[0] <= offset + n):
                kept.append(list(m))
        elif m[0] == "poll" and isinstance(m[1], dict) and k in m[1]:
            recs = [[o, v] for o, v in m[1][k]
                    if o is not None and offset - n <= o <= offset + n]
            if recs:
                kept.append(["poll", {k: recs}])
    return op.with_(value=kept) if kept else None


def around_key_offset(k, offset, history, n: int = 3) -> List[Any]:
    """All ops around (key, offset), trimmed (around-key-offset,
    kafka.clj:630-636)."""
    out = []
    for op in history:
        t = op_around_key_offset(k, offset, op, n)
        if t is not None:
            out.append(t)
    return out


def around_some(pred, n: int, coll):
    """Elements of ``coll`` within ``n`` positions of one matching ``pred``
    (around-some, kafka.clj:638-655)."""
    idx = set()
    for i, x in enumerate(coll):
        if pred(x):
            idx.update(range(i - n, i + n + 1))
    return [x for i, x in enumerate(coll) if i in idx]


def op_around_key_value(k, value, op, n: int = 3):
    """Trim an OK op to mops touching key ``k`` near records whose value is
    ``value`` (op-around-key-value, kafka.clj:657-680)."""
    if op.type != OK or op.f not in ("send", "poll", "txn"):
        return None
    kept = []
    for m in _mops(op):
        if m[0] == "send":
            ov = m[2]
            v = ov[1] if isinstance(ov, (list, tuple)) and len(ov) == 2 \
                else ov
            if m[1] == k and v == value:
                kept.append(list(m))
        elif m[0] == "poll" and isinstance(m[1], dict) and k in m[1]:
            recs = around_some(lambda r: r[1] == value, n, list(m[1][k]))
            if recs:
                kept.append(["poll", {k: [list(r) for r in recs]}])
    return op.with_(value=kept) if kept else None


def around_key_value(k, value, history, n: int = 3) -> List[Any]:
    """All ops around (key, value), trimmed (around-key-value,
    kafka.clj:682-688)."""
    out = []
    for op in history:
        t = op_around_key_value(k, value, op, n)
        if t is not None:
            out.append(t)
    return out


def writes_by_type(history) -> Dict[Any, Dict[Any, set]]:
    """type -> {key -> set of values written} over send/txn completions
    (writes-by-type, kafka.clj:690-707)."""
    out: Dict[Any, Dict[Any, set]] = {}
    for op in history:
        if op.type == INVOKE or op.f not in ("send", "txn"):
            continue
        by_k = out.setdefault(op.type, {})
        for k, v in _send_values(op):
            by_k.setdefault(k, set()).add(v)
    return out


def reads_by_type(history) -> Dict[Any, Dict[Any, set]]:
    """type -> {key -> set of values polled} over poll/txn completions
    (reads-by-type, kafka.clj:709-724)."""
    out: Dict[Any, Dict[Any, set]] = {}
    for op in history:
        if op.type == INVOKE or op.f not in ("poll", "txn"):
            continue
        by_k = out.setdefault(op.type, {})
        for k, _o, v in _poll_records(op):
            by_k.setdefault(k, set()).add(v)
    return out


def must_have_committed(rbt: Dict[Any, Dict[Any, set]], op) -> bool:
    """True iff ``op`` is ok, or is an info txn one of whose sends was
    observed by an OK poll (must-have-committed?, kafka.clj:726-737).
    ``rbt`` is a :func:`reads_by_type` map."""
    if op.type == OK:
        return True
    if op.type != INFO:
        return False
    ok_reads = rbt.get(OK, {})
    return any(v in ok_reads.get(k, ())
               for k, v in _send_values(op))


def recovered_info_ops(history: History) -> List[Any]:
    """Indeterminate (:info) transactions proven committed because an OK
    poll observed one of their written values (kafka.clj:726-737) — the
    must-have-committed? predicate over the reads-by-type index."""
    rbt = reads_by_type(history)
    return [op for op in history
            if op.type == INFO and must_have_committed(rbt, op)]


def realtime_lag(history: History) -> List[Dict[str, Any]]:
    """Per-poll conservative staleness lower bound (kafka.clj:1358-1460).

    ``known_at[k][o]`` = earliest time offset ``o`` of partition ``k`` was
    known to exist (any op mentioning an offset proves every lower offset
    too).  A poll invoked at ``t`` whose highest observation for ``k`` is
    ``m`` lags at least ``t - known_at[k][m+1]``: by that time offset m+1
    existed, so m was no longer the newest record."""
    known_at: Dict[Any, List[Any]] = defaultdict(list)
    for op in history:
        if op.type not in (OK, INFO, FAIL):
            continue
        max_off: Dict[Any, int] = {}
        for k, o, _v in itertools.chain(_send_pairs(op), _poll_records(op)):
            if o is not None and o > max_off.get(k, -1):
                max_off[k] = o
        for k, o in max_off.items():
            vec = known_at[k]
            if len(vec) <= o:
                vec.extend([None] * (o + 1 - len(vec)))
            for i in range(o, -1, -1):
                if vec[i] is not None:
                    break
                vec[i] = op.time
    pairs = history.pair_index()
    lags = []
    for i, op in enumerate(history):
        if op.type != OK:
            continue
        by_key: Dict[Any, int] = {}
        saw_poll = False
        for m in _mops(op):
            if m[0] == "poll" and isinstance(m[1], dict):
                saw_poll = True
                for k, recs in m[1].items():
                    mx = max((o for o, _v in recs), default=-1)
                    by_key[k] = max(by_key.get(k, -1), mx)
        if not saw_poll:
            continue
        j = pairs[i]
        t_invoke = history[j].time if j >= 0 else op.time
        if t_invoke is None:
            continue
        for k, m in by_key.items():
            vec = known_at.get(k, [])
            expired = vec[m + 1] if m + 1 < len(vec) else None
            lag = max(0, t_invoke - expired) if expired is not None else 0
            lags.append({"process": op.process, "key": k,
                         "time": t_invoke, "lag": lag})
    return lags


def generator(partitions: int = 4, max_mops: int = 3,
              sub_p: float = 0.05):
    """Simple mix of txn ops and occasional assign/subscribe rebalances
    (the quick-test generator; the reference-shaped pipeline is
    :func:`workload` / :func:`txn_generator` + the wrappers below)."""
    counter = itertools.count(1)

    def one():
        r = random.random()
        if r < sub_p:
            ks = sorted(random.sample(range(partitions),
                                      random.randint(1, partitions)))
            f = "assign" if random.random() < 0.5 else "subscribe"
            return {"f": f, "value": ks}
        mops = []
        for _ in range(random.randint(1, max_mops)):
            k = random.randrange(partitions)
            if random.random() < 0.5:
                mops.append(["send", k, next(counter)])
            else:
                mops.append(["poll", {}])
        return {"f": "txn", "value": mops}

    return gen.FnGen(one)


# ---------------------------------------------------------------------------
# Reference-shaped generator machinery (kafka.clj:195-443)
# ---------------------------------------------------------------------------


def txn_generator(la_gen=None, keys: int = 4):
    """Rewrite list-append transactions into send/poll micro-ops
    (kafka.clj:195-210 txn-generator): ``append k v`` -> ``["send", k, v]``,
    ``r k`` -> ``["poll", {}]``.  The keys the original txn touched ride in
    ``op.extra["keys"]`` so interleave_subscribes can subscribe to them."""
    if la_gen is None:
        from jepsen_tpu.workloads.cycle import append_gen
        la_gen = append_gen(keys=keys)

    def rewrite(op):
        mops = []
        ks = set()
        for m in _mops(op):
            ks.add(m[1])
            if m[0] == "append":
                mops.append(["send", m[1], m[2]])
            else:
                mops.append(["poll", {}])
        op2 = op.with_(value=mops)
        op2.extra["keys"] = sorted(ks, key=repr)
        return op2

    return gen.gen_map(rewrite, la_gen)


def tag_rw(g):
    """Tag ops whose mops are all sends / all polls as :f send / poll
    (kafka.clj:244-253 tag-rw)."""
    def tag(op):
        fs = {m[0] for m in _mops(op)}
        if fs == {"poll"}:
            return op.with_(f="poll")
        if fs == {"send"}:
            return op.with_(f="send")
        return op
    return gen.gen_map(tag, g)


SUBSCRIBE_RATIO = 1 / 8  # subscribe ops per txn op (kafka.clj:212-214)


class InterleaveSubscribes(gen.Generator):
    """With probability SUBSCRIBE_RATIO, emit a subscribe/assign op for the
    keys the pending txn would touch BEFORE that same txn, which is queued
    and dispensed on the next draw — kafka.clj:216-236.  (Queuing the
    drawn txn, rather than redrawing later, matters because the inner
    generator's draws are impure — a redraw would produce a DIFFERENT
    txn and the subscribe would name a phantom txn's keys.)"""

    def __init__(self, inner, sub_via=("subscribe", "assign"),
                 queued=None):
        self.inner = gen.lift(inner)
        self.sub_via = tuple(sub_via)
        self.queued = queued  # an Op template awaiting dispatch

    def op(self, test, ctx):
        if self.queued is not None:
            filled = gen.fill_op(self.queued, ctx)
            if filled is gen.PENDING:
                return (gen.PENDING, self)
            return (filled,
                    InterleaveSubscribes(self.inner, self.sub_via))
        if self.inner is None:
            return None
        r = self.inner.op(test, ctx)
        if r is None:
            return None
        v, g2 = r
        if v is gen.PENDING:
            return (gen.PENDING, InterleaveSubscribes(g2, self.sub_via))
        ks = v.extra.get("keys") if isinstance(v.extra, dict) else None
        if isinstance(v.extra, dict):
            v.extra.pop("keys", None)
        if ks and random.random() < SUBSCRIBE_RATIO:
            f = random.choice(tuple(test.get("sub_via", self.sub_via)))
            sub = gen.fill_op({"f": f, "value": list(ks)}, ctx)
            if sub is gen.PENDING:
                return (gen.PENDING,
                        InterleaveSubscribes(g2, self.sub_via,
                                             v.with_(process=None)))
            # the drawn txn is QUEUED (inner already advanced to g2) and
            # re-filled with a fresh process/time on the next draw
            return (sub, InterleaveSubscribes(g2, self.sub_via,
                                              v.with_(process=None)))
        return (v, InterleaveSubscribes(g2, self.sub_via))

    def update(self, test, ctx, event):
        g2 = self.inner.update(test, ctx, event) if self.inner else None
        if g2 is self.inner:
            return self
        return InterleaveSubscribes(g2, self.sub_via, self.queued)


def interleave_subscribes(g, sub_via=("subscribe", "assign")):
    return InterleaveSubscribes(g, sub_via)


def op_max_send_offsets(op) -> Dict[Any, int]:
    """key -> highest offset SENT by this op (kafka.clj:277-295)."""
    out: Dict[Any, int] = {}
    for k, o, _v in _send_pairs(op):
        if o is not None and o > out.get(k, -1):
            out[k] = o
    return out


def op_max_poll_offsets(op) -> Dict[Any, int]:
    """key -> highest offset POLLED by this op (kafka.clj:256-275)."""
    out: Dict[Any, int] = {}
    for k, o, _v in _poll_records(op):
        if o is not None and o > out.get(k, -1):
            out[k] = o
    return out


def op_max_offsets(op) -> Dict[Any, int]:
    out = op_max_send_offsets(op)
    for k, o in op_max_poll_offsets(op).items():
        if o > out.get(k, -1):
            out[k] = o
    return out


class PollUnseen(gen.Generator):
    """Track sent-but-never-polled keys; ~1/3 of assign/subscribe ops get
    those keys spliced into their value so consumers chase the unseen tail
    (kafka.clj:297-350 poll-unseen)."""

    def __init__(self, inner, sent=None, polled=None):
        self.inner = gen.lift(inner)
        self.sent = dict(sent or {})      # key -> max offset sent
        self.polled = dict(polled or {})  # key -> max offset polled

    def _with(self, inner, sent=None, polled=None):
        c = PollUnseen.__new__(PollUnseen)
        c.inner = inner
        c.sent = self.sent if sent is None else sent
        c.polled = self.polled if polled is None else polled
        return c

    def op(self, test, ctx):
        if self.inner is None:
            return None
        r = self.inner.op(test, ctx)
        if r is None:
            return None
        v, g2 = r
        if v is gen.PENDING:
            return (gen.PENDING, self._with(g2))
        if v.f in ("assign", "subscribe") and self.sent \
                and random.random() < 1 / 3:
            merged = list(v.value or [])
            merged += [k for k in self.sent if k not in merged]
            v = v.with_(value=merged)
        return (v, self._with(g2))

    def update(self, test, ctx, event):
        inner2 = self.inner.update(test, ctx, event) if self.inner else None
        if getattr(event, "type", None) != OK:
            return self if inner2 is self.inner else self._with(inner2)
        sent = dict(self.sent)
        polled = dict(self.polled)
        for k, o in op_max_send_offsets(event).items():
            if o > sent.get(k, -1):
                sent[k] = o
        for k, o in op_max_poll_offsets(event).items():
            if o > polled.get(k, -1):
                polled[k] = o
        for k in list(sent):  # trim keys we're caught up on
            if polled.get(k, -1) >= sent[k]:
                sent.pop(k, None)
                polled.pop(k, None)
        return self._with(inner2, sent, polled)


def poll_unseen(g):
    return PollUnseen(g)


class TrackKeyOffsets(gen.Generator):
    """Record the highest offset seen per key into a shared dict (the
    'atom' final_polls reads) — kafka.clj:352-371."""

    def __init__(self, offsets: Dict[Any, int], inner):
        self.offsets = offsets  # SHARED, mutated in place
        self.inner = gen.lift(inner)

    def op(self, test, ctx):
        if self.inner is None:
            return None
        r = self.inner.op(test, ctx)
        if r is None:
            return None
        v, g2 = r
        nxt = self if g2 is self.inner else TrackKeyOffsets(self.offsets, g2)
        return (v, nxt)

    def update(self, test, ctx, event):
        if getattr(event, "type", None) == OK:
            for k, o in op_max_offsets(event).items():
                if o > self.offsets.get(k, -1):
                    self.offsets[k] = o
        inner2 = self.inner.update(test, ctx, event) if self.inner else None
        if inner2 is self.inner:
            return self
        return TrackKeyOffsets(self.offsets, inner2)


def track_key_offsets(offsets: Dict[Any, int], g):
    return TrackKeyOffsets(offsets, g)


class FinalPolls(gen.Generator):
    """Drive the inner crash/assign/poll loop until polls catch up to the
    target offsets (kafka.clj:373-436 final-polls): exhausts as soon as
    every target key has been polled to its recorded max offset."""

    def __init__(self, targets: Dict[Any, int], inner):
        self.targets = dict(targets)
        self.inner = gen.lift(inner)

    def op(self, test, ctx):
        if not self.targets or self.inner is None:
            return None
        r = self.inner.op(test, ctx)
        if r is None:
            return None
        v, g2 = r
        nxt = self if g2 is self.inner else FinalPolls(self.targets, g2)
        return (v, nxt)

    def update(self, test, ctx, event):
        inner2 = self.inner.update(test, ctx, event) if self.inner else None
        targets = self.targets
        if getattr(event, "type", None) == OK and \
                getattr(event, "f", None) in ("poll", "txn"):
            seen = op_max_offsets(event)
            t2 = {k: o for k, o in targets.items()
                  if seen.get(k, -1) < o}
            if len(t2) != len(targets):
                targets = t2
        if targets is self.targets and inner2 is self.inner:
            return self
        return FinalPolls(targets, inner2)


def final_polls(offsets: Dict[Any, int], rounds_s: float = 10.0):
    """Build the reference's catch-up phase from the tracked offsets:
    crash the client (fresh state), assign every key with
    seek-to-beginning, then poll repeatedly; the whole cycle repeats
    until FinalPolls sees every target offset (kafka.clj:404-436).

    Built lazily via FnGen-on-first-draw semantics: ``offsets`` is the
    live dict track_key_offsets mutates, so the snapshot happens when the
    final phase actually starts (the reference's ``delay``)."""
    built: List[Any] = []

    class _Delay(gen.Generator):
        def op(self, test, ctx):
            if not built:
                targets = dict(offsets)
                ks = sorted(targets, key=repr)
                cycle = [{"f": "crash"},
                         {"f": "debug-topic-partitions", "value": ks},
                         {"f": "assign", "value": ks,
                          "seek_to_beginning": True},
                         gen.stagger(0.2, gen.repeat({"f": "poll",
                                                      "value": [["poll",
                                                                 {}]]}))]
                built.append(FinalPolls(
                    targets, gen.cycle(gen.time_limit(rounds_s,
                                                      gen.lift(cycle)))))
            return built[0].op(test, ctx)

        def update(self, test, ctx, event):
            if built:
                built[0] = built[0].update(test, ctx, event)
            return self

    return _Delay()


def crash_client_gen(opts: Optional[Dict[str, Any]] = None):
    """Periodic client crashes when the test asks for them
    (kafka.clj:438-445 crash-client-gen); None otherwise."""
    opts = opts or {}
    if not opts.get("crash_clients"):
        return None
    interval = float(opts.get("crash_client_interval", 30.0))
    conc = max(1, int(opts.get("concurrency", 1)))
    return gen.stagger(interval / conc, gen.repeat({"f": "crash"}))


class KafkaStats(Checker):
    """Wraps the standard Stats checker but never invalidates over
    ``crash`` / ``debug-topic-partitions`` ops, which by design never
    complete ok (kafka.clj:2089-2104 stats-checker)."""

    def __init__(self, inner=None):
        from jepsen_tpu.checker.core import Stats
        self.inner = inner or Stats()

    def check(self, test, history: History, opts=None):
        res = self.inner.check(test, history, opts)
        if res.get("valid") is True:
            return res
        by_f = dict(res.get("by-f") or {})
        by_f.pop("crash", None)
        by_f.pop("debug-topic-partitions", None)
        bad = [f for f, c in by_f.items()
               if not c.get(OK, 0) and (c.get(FAIL, 0) or c.get(INFO, 0))]
        if not bad:
            # The exempt fs' own by-f blocks keep their UNKNOWN (they
            # never complete ok by design — kafka.clj:2100-2103 likewise
            # leaves the per-f verdicts and only lifts the top level).
            return {**res, "valid": True}
        return res


def allowed_error_types(test, sub_via=None, ww_deps=None) -> set:
    """Anomaly types that do NOT invalidate the test
    (kafka.clj:2019-2047 allowed-error-types): int-send-skip and G0 are
    normal in the Kafka transactional model (writes are never isolated);
    with subscribe in play, rebalances legitimately skip/rewind polls;
    with ww edges in the dependency graph, t0 <ww t1 <wr t0 cycles (G1c)
    are expected for the same lack of write isolation.  Explicit args
    (from the workload's configuration) win over test-map keys."""
    test = test or {}
    if sub_via is None:
        sub_via = test.get("sub_via", ("subscribe", "assign"))
    if ww_deps is None:
        ww_deps = test.get("ww_deps", True)
    allowed = {"int-send-skip", "G0", "process-G0"}
    if "subscribe" in tuple(sub_via):
        allowed |= {"poll-skip", "nonmonotonic-poll"}
    if ww_deps:
        allowed |= {"G1c", "process-G1c"}
    return allowed


class KafkaChecker(Checker):
    def __init__(self, sub_via=None, ww_deps=None):
        # workload-configured semantics: which error types are allowed
        # (allowed_error_types) and whether ww edges join the dependency
        # graph at all (kafka.clj's :ww-deps).  None = read the test map /
        # defaults at check time.
        self.sub_via = sub_via
        self.ww_deps = ww_deps

    def _ww_deps(self, test) -> bool:
        if self.ww_deps is not None:
            return bool(self.ww_deps)
        return bool((test or {}).get("ww_deps", True))

    def check(self, test, history: History, opts=None):
        sends_ok: Dict[Tuple[Any, int], Any] = {}   # (k, offset) -> value
        send_of_value: Dict[Tuple[Any, Any], int] = {}  # (k, value) -> offset
        failed_values: set = set()                  # (k, value) of failed sends
        n_polls = 0
        anomalies: Dict[str, List[Any]] = defaultdict(list)

        # Pass 1: index every offset the history proves to exist — acked
        # sends AND polled records (an offset whose send crashed is still
        # real once any poll saw it) — so the ordered pass can ask "is
        # offset o known?" for the skip analyses with full knowledge.
        observed: Dict[Any, set] = defaultdict(set)
        for op in history:
            if op.type == OK and isinstance(op.value, (list, tuple)):
                for mop in op.value:
                    if not (isinstance(mop, (list, tuple)) and mop):
                        continue
                    if mop[0] == "send":
                        k, ov = mop[1], mop[2]
                        if isinstance(ov, (list, tuple)) and len(ov) == 2:
                            o, v = ov
                            if (k, o) in sends_ok and sends_ok[(k, o)] != v:
                                anomalies["offset-conflict"].append(
                                    {"key": k, "offset": o,
                                     "values": [sends_ok[(k, o)], v]})
                            if (k, v) in send_of_value and \
                                    send_of_value[(k, v)] != o:
                                anomalies["duplicate"].append(
                                    {"key": k, "value": v,
                                     "offsets": [send_of_value[(k, v)], o]})
                            sends_ok[(k, o)] = v
                            send_of_value[(k, v)] = o
                    elif mop[0] == "poll" and isinstance(mop[1], dict):
                        for k, recs in mop[1].items():
                            for o, _v in recs:
                                observed[k].add(o)
            elif op.type == FAIL and isinstance(op.value, (list, tuple)):
                for mop in op.value:
                    if isinstance(mop, (list, tuple)) and mop \
                            and mop[0] == "send":
                        failed_values.add((mop[1], mop[2]))

        # Indeterminate-txn recovery (must-have-committed?): sends of an
        # :info txn observed by an OK poll are committed — they join the
        # committed universe for version orders / lost-write / unseen.
        recovered = recovered_info_ops(history)
        for op in recovered:
            for k, o, v in _send_pairs(op):
                if (k, o) not in sends_ok:
                    sends_ok[(k, o)] = v
                    send_of_value.setdefault((k, v), o)
            for k, o, _v in _poll_records(op):
                observed[k].add(o)
        if recovered:
            anomalies_info_recovered = [
                {"process": op.process, "index": op.index}
                for op in recovered]
        else:
            anomalies_info_recovered = []

        # Cross-observation version orders (kafka.clj:820-870): every send
        # and every poll of every committed/recovered txn votes for the
        # value at (k, offset); an offset with >1 distinct values is an
        # inconsistent-offsets error, a value at >1 offsets a duplicate.
        votes: Dict[Tuple[Any, int], set] = defaultdict(set)
        value_offsets: Dict[Tuple[Any, Any], set] = defaultdict(set)
        for op in itertools.chain(
                (o for o in history if o.type == OK), recovered):
            for k, o, v in itertools.chain(_send_pairs(op),
                                           _poll_records(op)):
                votes[(k, o)].add(v)
                value_offsets[(k, v)].add(o)
        for (k, o), vs in sorted(votes.items(), key=repr):
            if len(vs) > 1:
                anomalies["inconsistent-offsets"].append(
                    {"key": k, "offset": o, "values": sorted(vs, key=repr)})
        dup_reported = {(d["key"], d["value"])
                        for d in anomalies.get("duplicate", ())}
        for (k, v), offs in sorted(value_offsets.items(), key=repr):
            if len(offs) > 1 and (k, v) not in dup_reported:
                anomalies["duplicate"].append(
                    {"key": k, "value": v, "offsets": sorted(offs)})

        def known(k, o):
            return (k, o) in sends_ok or o in observed[k]

        # Pass 2, in history order: per-process poll positions within
        # assignment eras, per-txn send monotonicity, per-poll order.
        pos: Dict[Tuple[Any, Any], int] = {}  # (process, k) -> last offset
        for op in history:
            if op.type != OK:
                continue
            if op.f in ("assign", "subscribe", "crash"):
                # rebalance / restart: all positions of this process reset —
                # a later poll legitimately rewinds or jumps (kafka.clj
                # treats cross-rebalance polls as a fresh era).
                for pk in [pk for pk in pos if pk[0] == op.process]:
                    del pos[pk]
                continue
            if not isinstance(op.value, (list, tuple)):
                continue
            txn_send_last: Dict[Any, int] = {}  # k -> last offset this txn
            for mop in op.value:
                if not isinstance(mop, (list, tuple)) or not mop:
                    continue
                if mop[0] == "send":
                    k, ov = mop[1], mop[2]
                    if not (isinstance(ov, (list, tuple)) and len(ov) == 2):
                        continue
                    o, _v = ov
                    prev = txn_send_last.get(k)
                    if prev is not None:
                        if o <= prev:
                            anomalies["nonmonotonic-send"].append(
                                {"key": k, "offsets": [prev, o]})
                        else:
                            skipped = [oo for oo in range(prev + 1, o)
                                       if known(k, oo)]
                            if skipped:
                                anomalies["int-send-skip"].append(
                                    {"key": k, "offsets": [prev, o],
                                     "skipped": skipped})
                    txn_send_last[k] = o
                elif mop[0] == "poll" and isinstance(mop[1], dict):
                    pd = mop[1]
                    n_polls += 1
                    for k, recs in pd.items():
                        last = None
                        for o, v in recs:
                            if (k, v) in failed_values:
                                anomalies["aborted-read"].append(
                                    {"key": k, "offset": o, "value": v})
                            if (k, o) in sends_ok and sends_ok[(k, o)] != v:
                                anomalies["poll-send-mismatch"].append(
                                    {"key": k, "offset": o,
                                     "polled": v, "sent": sends_ok[(k, o)]})
                            if (k, v) in send_of_value and \
                                    send_of_value[(k, v)] != o:
                                anomalies["duplicate"].append(
                                    {"key": k, "value": v,
                                     "offsets": [send_of_value[(k, v)], o]})
                            if last is not None and o <= last:
                                anomalies["internal-nonmonotonic"].append(
                                    {"key": k, "offsets": [last, o]})
                            last = o
                        if not recs:
                            continue
                        first = recs[0][0]
                        prev = pos.get((op.process, k))
                        if prev is not None:
                            if first <= prev:
                                anomalies["nonmonotonic-poll"].append(
                                    {"process": op.process, "key": k,
                                     "prev": prev, "rewound-to": first})
                            else:
                                skipped = [o for o in range(prev + 1, first)
                                           if known(k, o)]
                                if skipped:
                                    anomalies["poll-skip"].append(
                                        {"process": op.process, "key": k,
                                         "prev": prev, "next": first,
                                         "skipped": skipped})
                        pos[(op.process, k)] = recs[-1][0]

        # lost writes: acked send at offset o never observed, while some
        # poll observed an offset > o in that partition
        for (k, o), v in sends_ok.items():
            if o in observed[k]:
                continue
            if observed[k] and max(observed[k]) > o:
                anomalies["lost-write"].append({"key": k, "offset": o,
                                                "value": v})
        unseen = [{"key": k, "offset": o, "value": v}
                  for (k, o), v in sends_ok.items()
                  if o not in observed[k]
                  and not (observed[k] and max(observed[k]) > o)]

        # Pass 3: transaction dependency graph over the log (the reference's
        # elle-style cycle pass, kafka.clj:110-2049) — catches cycles the
        # per-mop offset/order analyses above cannot (e.g. two txns each
        # polling the other's send: G1c on the log).
        cycles = _graph_pass(history, ww_deps=self._ww_deps(test))
        for c in cycles:
            anomalies[c["type"]].append(c)

        hard = {k: v for k, v in anomalies.items()}
        # Graded unseen accounting (kafka.clj's unseen: per-partition counts,
        # informational unless nothing was ever polled at all).
        per_part: Dict[Any, Dict[str, int]] = {}
        for (k, o), v in sends_ok.items():
            d = per_part.setdefault(k, {"acked": 0, "observed": 0,
                                        "unseen": 0})
            d["acked"] += 1
            if o in observed[k]:
                d["observed"] += 1
            else:
                d["unseen"] += 1
        # Realtime lag (worst-case staleness per key + global worst).
        lags = realtime_lag(history)
        worst = max(lags, key=lambda d: d["lag"], default=None)
        worst_by_key: Dict[Any, Dict[str, Any]] = {}
        for d in lags:
            cur = worst_by_key.get(d["key"])
            if cur is None or d["lag"] > cur["lag"]:
                worst_by_key[d["key"]] = d

        cc = consume_counts(history)
        allowed = allowed_error_types(test, sub_via=self.sub_via,
                                      ww_deps=self._ww_deps(test))
        bad = sorted(t for t in hard if t not in allowed)
        # Refuted runs get the reference's drill-down surface attached
        # per-anomaly: the trimmed history neighborhood around the suspect
        # (key, offset) / (key, value) plus the writes/reads-by-type index
        # (kafka.clj:600-737) — the artifact a human reads under incident
        # pressure should carry its own context.
        drill = {}
        if bad:
            for t in bad:
                ctx = []
                for a in hard[t][:2]:
                    if not isinstance(a, dict) or "key" not in a:
                        continue
                    entry = dict(a)
                    if a.get("offset") is not None:
                        near = around_key_offset(a["key"], a["offset"],
                                                 history)
                    elif a.get("value") is not None:
                        near = around_key_value(a["key"], a["value"],
                                                history)
                    elif a.get("offsets"):
                        near = around_key_offset(a["key"], a["offsets"][-1],
                                                 history)
                    else:
                        continue
                    entry["around"] = [o.to_dict() for o in near[:12]]
                    ctx.append(entry)
                if ctx:
                    drill[t] = ctx
            wbt = writes_by_type(history)
            rbt = reads_by_type(history)
            drill["writes-by-type"] = {
                str(t): {str(k): sorted(vs, key=repr)[:16]
                         for k, vs in by_k.items()}
                for t, by_k in wbt.items()}
            drill["reads-by-type"] = {
                str(t): {str(k): sorted(vs, key=repr)[:16]
                         for k, vs in by_k.items()}
                for t, by_k in rbt.items()}
        res = {"valid": (UNKNOWN if (not bad and unseen and n_polls == 0)
                         else not bad),
               "bad-error-types": bad,
               "allowed-error-types": sorted(allowed),
               "anomaly-types": sorted(hard),
               "anomalies": {k: v[:8] for k, v in hard.items()},
               "anomalies-full": hard,
               "drill-down": drill,
               "sends": len(sends_ok), "polls": n_polls,
               "recovered-info-txns": anomalies_info_recovered[:8],
               "recovered-info-count": len(anomalies_info_recovered),
               "worst-realtime-lag": worst,
               "worst-realtime-lag-by-key": worst_by_key,
               # exactly-once accounting (informational, kafka.clj
               # consume-counts): subscribed polls reading a value twice
               "consume-counts": cc,
               "unseen-count": len(unseen), "unseen": unseen[:8],
               "unseen-by-partition": {
                   k: d for k, d in sorted(per_part.items())
                   if d["unseen"]}}
        self._plot_lag(lags, opts or {}, test or {})
        render_order_viz(test, history, hard, unseen, opts)
        from jepsen_tpu.elle.render import write_artifacts
        write_artifacts(test, res, opts)
        return res

    @staticmethod
    def _plot_lag(lags, opts, test) -> None:
        """realtime-lag.png: per-key lag over time (kafka.clj:1505-1560
        plot-realtime-lag!).  Best-effort artifact; never affects the
        verdict."""
        d = opts.get("store_dir") or test.get("store_dir")
        if not d or not lags:
            return
        try:
            import os
            import matplotlib
            matplotlib.use("Agg")
            import matplotlib.pyplot as plt
            by_key: Dict[Any, List] = defaultdict(list)
            t0 = min(x["time"] for x in lags)
            for x in lags:
                by_key[x["key"]].append(((x["time"] - t0) / 1e9,
                                         x["lag"] / 1e9))
            fig, ax = plt.subplots(figsize=(8, 4))
            for k, pts in sorted(by_key.items(), key=repr):
                pts.sort()
                ax.plot([p[0] for p in pts], [p[1] for p in pts],
                        drawstyle="steps-post", label=f"key {k}")
            ax.set_xlabel("time (s)")
            ax.set_ylabel("realtime lag (s)")
            ax.legend(fontsize=7)
            fig.tight_layout()
            fig.savefig(os.path.join(d, "realtime-lag.png"), dpi=110)
            plt.close(fig)
        except Exception:  # noqa: BLE001
            pass


def _graph_pass(history: History,
                ww_deps: bool = True) -> List[Dict[str, Any]]:
    """Elle-style dependency cycles over the log (kafka.clj:110-2049).

    Edges between OK transactions:
      ww      — writer of a partition's offset -> writer of the next known
                offset of that partition (the log's version order is the
                offset order, so this is exact); OMITTED when ``ww_deps``
                is false — the reference drops ww edges from the graph
                entirely in that mode, it doesn't just whitelist the
                cycles they close;
      wr      — writer of (k, offset) -> each txn that polled that record
                (self-reads of a txn's own sends are precommitted reads,
                legitimate, and excluded with all self-edges);
      process — consecutive OK txns of one process.

    Cycles over {ww, wr} are typed with elle's classifier (G0 ww-only,
    G1c otherwise — no rw edges exist on a log, polls read explicit
    offsets).  Cycles that additionally need process edges are typed
    ``process-<base>`` (kafka.clj's process-order anomaly family)."""
    # Same shape predicate as the offset analyses (passes 1-2): any OK op
    # whose value contains send/poll mops is a transaction — histories
    # loaded from external logs may not tag f="txn".  Control ops (assign/
    # subscribe: value is a partition list) contain no mops and drop out.
    oks: List[Tuple[int, Any]] = []
    for i, op in enumerate(history):
        if op.type == OK and isinstance(op.value, (list, tuple)) \
                and any(isinstance(m, (list, tuple)) and m
                        and m[0] in ("send", "poll") for m in op.value):
            oks.append((i, op))
    writer_of: Dict[Tuple[Any, int], int] = {}  # (k, offset) -> tid
    for tid, (_, op) in enumerate(oks):
        for mop in op.value:
            if isinstance(mop, (list, tuple)) and mop and mop[0] == "send":
                k, ov = mop[1], mop[2]
                if isinstance(ov, (list, tuple)) and len(ov) == 2:
                    writer_of[(k, ov[0])] = tid

    g = Graph()
    for tid in range(len(oks)):
        g.add_node(tid)
    # ww: offset order of each partition, over offsets with known writers
    if ww_deps:
        by_part: Dict[Any, List[int]] = defaultdict(list)
        for (k, o) in writer_of:
            by_part[k].append(o)
        for k, offs in by_part.items():
            offs.sort()
            for o1, o2 in zip(offs, offs[1:]):
                a, b = writer_of[(k, o1)], writer_of[(k, o2)]
                if a != b:
                    g.add_edge(a, b, "ww")
    # wr: sender -> poller of the same record
    for tid, (_, op) in enumerate(oks):
        for mop in op.value:
            if isinstance(mop, (list, tuple)) and mop and mop[0] == "poll" \
                    and isinstance(mop[1], dict):
                for k, recs in mop[1].items():
                    for o, _v in recs:
                        w = writer_of.get((k, o))
                        if w is not None and w != tid:
                            g.add_edge(w, tid, "wr")
    # process order
    last_of_process: Dict[Any, int] = {}
    for tid, (_, op) in enumerate(oks):
        prev = last_of_process.get(op.process)
        if prev is not None:
            g.add_edge(prev, tid, "process")
        last_of_process[op.process] = tid

    out: List[Dict[str, Any]] = []
    seen_cycles = set()

    def scan(graph: Graph):
        for cyc in peeled_cycles(graph):
            key = frozenset(cyc)
            if key in seen_cycles:
                continue  # already reported from the ww+wr scan
            seen_cycles.add(key)
            kinds = cycle_edge_kinds(graph, cyc)
            base_kinds = [ks - {"process"} for ks in kinds]
            if all(bk for bk in base_kinds):
                typ = classify_cycle(base_kinds)
            else:
                # at least one step exists only by process order;
                # process edges type like ww for severity
                typ = "process-" + classify_cycle(
                    [bk or {"ww"} for bk in base_kinds])
            out.append({
                "type": typ,
                "cycle": [_txn_brief(oks[t][1]) for t in cyc],
                "edges": [sorted(ks) for ks in kinds],
            })

    scan(g.filter_kinds({"ww", "wr"}))  # pure log cycles first (G0/G1c)
    scan(g)                             # then cycles needing process order
    return out


def _txn_brief(op) -> Dict[str, Any]:
    return {"process": op.process, "index": op.index, "value": op.value}


def consume_counts(history: History) -> Dict[str, Any]:
    """Exactly-once accounting (kafka.clj:1651-1704 consume-counts): for
    every committed txn polling while SUBSCRIBED (assign polls may freely
    double-consume), count how often each (process, key, value) was read.
    Returns the count distribution plus the key->value->count map of
    anything consumed more than once."""
    counts: Dict[Any, Dict[Any, Dict[Any, int]]] = {}
    subscribed: set = set()
    for op in history:
        if op.type != OK:
            continue
        if op.f == "subscribe":
            subscribed.add(op.process)
        elif op.f == "assign":
            subscribed.discard(op.process)
        elif op.f in ("txn", "poll") or (
                op.f is None and any(True for _ in _poll_records(op))):
            if op.process not in subscribed:
                continue
            per = counts.setdefault(op.process, {})
            for k, _o, v in _poll_records(op):
                kk = per.setdefault(k, {})
                kk[v] = kk.get(v, 0) + 1
    dist: Dict[int, int] = {}
    dups: Dict[Any, Dict[Any, int]] = {}
    for _p, by_k in counts.items():
        for k, by_v in by_k.items():
            for v, c in by_v.items():
                dist[c] = dist.get(c, 0) + 1
                if c > 1:
                    dups.setdefault(k, {})[v] = c
    return {"distribution": dict(sorted(dist.items())),
            "dup-counts": {k: dict(sorted(v.items(), key=repr))
                           for k, v in sorted(dups.items(), key=repr)}}


def key_order_viz(k, history: History) -> str:
    """SVG visualization of every OK op's sends/polls of key ``k``'s log:
    one row per op, offsets on the x axis, the observed value as the cell
    text, with cells of offsets that carry conflicting values highlighted
    (kafka.clj:1570-1630 key-order-viz)."""
    votes: Dict[int, set] = defaultdict(set)
    rows = []
    for op in history:
        if op.type != OK:
            continue
        pairs = [(o, v) for kk, o, v in itertools.chain(_send_pairs(op),
                                                        _poll_records(op))
                 if kk == k and o is not None]
        if pairs:
            rows.append((op, pairs))
            for o, v in pairs:
                votes[o].add(v)
    cells = []
    max_x = max_y = 0
    for i, (op, pairs) in enumerate(rows):
        y = (i + 1) * 14
        max_y = max(max_y, y)
        title = (f"{op.type} {op.f} by process {op.process} "
                 f"(index {op.index})")
        row_cells = []
        for o, v in pairs:
            x = o * 24
            max_x = max(max_x, x)
            conflict = len(votes[o]) > 1
            style = ' style="fill:#c0392b;font-weight:bold"' if conflict \
                else ""
            row_cells.append(f'<text x="{x}" y="{y}"{style}>{v}</text>')
        cells.append(f"<g><title>{title}</title>" + "".join(row_cells)
                     + "</g>")
    return (f'<svg xmlns="http://www.w3.org/2000/svg" version="1.1" '
            f'width="{max_x + 40}" height="{max_y + 20}">'
            '<style>svg { font-family: Helvetica, Arial, sans-serif; '
            'font-size: 10px; }</style>'
            + "".join(cells) + "</svg>")


def render_order_viz(test, history: History, anomalies: Dict[str, Any],
                     unseen, opts=None) -> None:
    """Write orders/<k>.svg for every key implicated in offset anomalies
    (kafka.clj:1632-1650 render-order-viz!).  Best-effort artifact."""
    d = (opts or {}).get("store_dir") or (test or {}).get("store_dir")
    if not d:
        return
    keys = {a["key"] for t in ("inconsistent-offsets", "duplicate",
                               "lost-write")
            for a in anomalies.get(t, ()) if "key" in a}
    keys |= {u["key"] for u in unseen}
    if not keys:
        return
    try:
        import os
        od = os.path.join(d, "orders")
        os.makedirs(od, exist_ok=True)
        for k in sorted(keys, key=repr):
            name = f"{k:03d}.svg" if isinstance(k, int) else f"{k}.svg"
            with open(os.path.join(od, name), "w") as f:
                f.write(key_order_viz(k, history))
    except Exception:  # noqa: BLE001
        pass


def workload(partitions: int = 4, sub_via=("subscribe", "assign"),
             crash_clients: bool = False,
             crash_client_interval: float = 30.0,
             concurrency: int = 4,
             reference_shape: bool = False) -> Dict[str, Any]:
    """Kafka workload.  With ``reference_shape``, the generator is the
    reference's full pipeline (kafka.clj:2106-2150 workload): list-append
    txns rewritten to send/poll, rw-tagged, subscribe-interleaved,
    unseen-chasing, offset-tracked — plus a ``final_generator`` that
    crashes clients and polls until every tracked offset has been seen,
    and an optional crash-client schedule."""
    if not reference_shape:
        return {"generator": generator(partitions),
                "checker": KafkaChecker(sub_via=sub_via)}
    offsets: Dict[Any, int] = {}
    g = txn_generator(keys=partitions)
    g = tag_rw(g)
    g = interleave_subscribes(g, sub_via)
    g = poll_unseen(g)
    g = track_key_offsets(offsets, g)
    crash = crash_client_gen({"crash_clients": crash_clients,
                              "crash_client_interval": crash_client_interval,
                              "concurrency": concurrency})
    if crash is not None:
        g = gen.any_gen(g, crash)
    # each worker runs its OWN crash/assign/poll catch-up cycle
    # (kafka.clj:2142 wraps final-polls in gen/each-thread) — otherwise
    # the assign lands on one worker and the polls on another, and
    # coverage of the log is accidental
    return {"generator": g,
            "final_generator": gen.each_thread(final_polls(offsets)),
            "tracked_offsets": offsets,
            "checker": KafkaChecker(sub_via=sub_via)}
