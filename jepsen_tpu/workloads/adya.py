"""Adya anomaly workloads: G2 (anti-dependency cycles) and dirty updates.

Parity: jepsen.tests.adya (jepsen/src/jepsen/tests/adya.clj:12-87):
generators that specifically provoke G2 write skew and dirty-update
anomalies, plus checkers that detect them.

- G2: pairs of transactions each read the other's predicate/key and insert
  if absent; both succeeding is a write-skew cycle (two rw edges).
- Dirty update: an update chain built on a value written by an aborted
  transaction.
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Dict, List

from jepsen_tpu import generator as gen
from jepsen_tpu.checker.core import Checker, UNKNOWN
from jepsen_tpu.elle import rw_register
from jepsen_tpu.history import FAIL, History, OK


def g2_generator(keys: int = 32):
    """Each logical attempt: two txns on a key pair (a, b): txn1 reads b,
    inserts a-if-b-absent; txn2 reads a, inserts b-if-a-absent
    (adya.clj's g2 generator)."""
    pair = itertools.count(0)

    def one():
        p = next(pair) % keys
        a, b = f"a{p}", f"b{p}"
        if random.random() < 0.5:
            return {"f": "txn", "value": [["r", b, None], ["w", a, p]],
                    "pair": p}
        return {"f": "txn", "value": [["r", a, None], ["w", b, p]],
                "pair": p}

    return gen.FnGen(one)


class G2Checker(Checker):
    """Both halves of a G2 pair succeeded with each reading the other's key
    as absent -> write skew (adya.clj's g2 checker; also derivable from the
    general rw-cycle engine)."""

    def check(self, test, history: History, opts=None):
        by_pair: Dict[Any, List] = {}
        for op in history:
            if op.type != OK or not isinstance(op.value, (list, tuple)):
                continue
            p = op.extra.get("pair")
            if p is None:
                continue
            by_pair.setdefault(p, []).append(op)
        skews = []
        for p, ops in by_pair.items():
            wrote_a = [o for o in ops
                       if any(f == "w" and str(k).startswith("a")
                              for f, k, v in o.value)
                       and all(v is None for f, k, v in o.value
                               if f == "r")]
            wrote_b = [o for o in ops
                       if any(f == "w" and str(k).startswith("b")
                              for f, k, v in o.value)
                       and all(v is None for f, k, v in o.value
                               if f == "r")]
            if wrote_a and wrote_b:
                skews.append({"pair": p,
                              "txns": [wrote_a[0].to_dict(),
                                       wrote_b[0].to_dict()]})
        # also run the general cycle detector for corroboration
        cyc = rw_register.check(history)
        return {"valid": not skews,
                "write-skews": skews[:8],
                "cycle-analysis": {"valid": cyc["valid"],
                                   "anomaly-types": cyc["anomaly-types"]}}


def dirty_update_generator(keys: int = 16):
    """Update chains: each txn reads k and writes read_value + 1; some
    writers abort after writing (simulated by the client) — a later update
    building on an aborted value is dirty (adya.clj dirty-update)."""
    key = itertools.count(0)

    def one():
        k = next(key) % keys
        return {"f": "txn", "value": [["r", k, None], ["w", k, None]],
                "update": True}

    return gen.FnGen(one)


class DirtyUpdateChecker(Checker):
    """A committed update whose read value was written by an aborted txn
    (G1a restricted to update chains) — reported with the chain."""

    def check(self, test, history: History, opts=None):
        aborted = set()
        for op in history:
            if op.type == FAIL and isinstance(op.value, (list, tuple)):
                for f, k, v in op.value:
                    if f == "w" and v is not None:
                        aborted.add((k, v))
        dirty = []
        for op in history:
            if op.type != OK or not isinstance(op.value, (list, tuple)):
                continue
            for f, k, v in op.value:
                if f == "r" and v is not None and (k, v) in aborted:
                    dirty.append({"key": k, "aborted-value": v,
                                  "txn": op.to_dict()})
        return {"valid": not dirty, "dirty-updates": dirty[:8]}


def g2_workload(keys: int = 32) -> Dict[str, Any]:
    return {"generator": g2_generator(keys), "checker": G2Checker()}


def dirty_update_workload(keys: int = 16) -> Dict[str, Any]:
    return {"generator": dirty_update_generator(keys),
            "checker": DirtyUpdateChecker()}
