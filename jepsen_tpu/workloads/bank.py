"""Bank workload: concurrent transfers must conserve total balance.

Parity: jepsen.tests.bank (jepsen/src/jepsen/tests/bank.clj): transfer ops
move money between accounts; reads return the whole account map; under
snapshot isolation the total must never change (bank.clj:41-179).
"""

from __future__ import annotations

import random
from typing import Any, Dict, List, Optional

from jepsen_tpu import generator as gen
from jepsen_tpu.checker.core import Checker, UNKNOWN
from jepsen_tpu.history import History, INVOKE, OK, Op

DEFAULT_ACCOUNTS = list(range(8))
DEFAULT_TOTAL = 100
DEFAULT_MAX_TRANSFER = 5


def transfer_gen(accounts=None, max_transfer=DEFAULT_MAX_TRANSFER):
    accounts = accounts or DEFAULT_ACCOUNTS

    def one():
        frm, to = random.sample(accounts, 2)
        return {"f": "transfer",
                "value": {"from": frm, "to": to,
                          "amount": random.randint(1, max_transfer)}}

    return gen.FnGen(one)


def read_gen():
    return gen.repeat({"f": "read"})


def generator(accounts=None, max_transfer=DEFAULT_MAX_TRANSFER):
    """Mixed reads and transfers (bank.clj:41)."""
    return gen.mix([read_gen(), transfer_gen(accounts, max_transfer)])


class BankChecker(Checker):
    """Every read's total must equal the invariant total; negative balances
    are illegal unless negative_balances is allowed (bank.clj:84-179)."""

    def __init__(self, total: int = DEFAULT_TOTAL,
                 negative_balances: bool = False):
        self.total = total
        self.negative_balances = negative_balances

    def check(self, test, history: History, opts=None):
        bad_reads: List[Dict[str, Any]] = []
        n_reads = 0
        for op in history:
            if op.f == "read" and op.type == OK and op.value is not None:
                n_reads += 1
                balances = dict(op.value)
                total = sum(balances.values())
                neg = {k: v for k, v in balances.items() if v < 0}
                if total != self.total:
                    bad_reads.append({"op": op.to_dict(), "total": total,
                                      "expected": self.total})
                elif neg and not self.negative_balances:
                    bad_reads.append({"op": op.to_dict(), "negative": neg})
        if n_reads == 0:
            return {"valid": UNKNOWN, "error": "no reads completed"}
        return {"valid": not bad_reads,
                "read-count": n_reads,
                "bad-reads-count": len(bad_reads),
                "bad-reads": bad_reads[:10]}


def workload(accounts=None, total=DEFAULT_TOTAL,
             max_transfer=DEFAULT_MAX_TRANSFER) -> Dict[str, Any]:
    accounts = accounts or DEFAULT_ACCOUNTS
    return {"accounts": accounts,
            "total_amount": total,
            "generator": generator(accounts, max_transfer),
            "checker": BankChecker(total)}
