"""Long-fork detection — the parallel-snapshot-isolation anomaly.

Parity: jepsen.tests.long-fork (jepsen/src/jepsen/tests/long_fork.clj):
writers update distinct keys with unique values; readers read groups of
keys.  Under PSI, two readers may observe two writes in *opposite* orders —
the "long fork".  Detection: for writes w(x) and w(y) (distinct keys), a
reader r1 seeing x-written but y-unwritten and a reader r2 seeing
y-written but x-unwritten form a fork: no single order of w(x), w(y) can
explain both.
"""

from __future__ import annotations

import itertools
import random
from collections import defaultdict
from typing import Any, Dict, List, Optional, Tuple

from jepsen_tpu import generator as gen
from jepsen_tpu.checker.core import Checker, UNKNOWN
from jepsen_tpu.history import History, OK
from jepsen_tpu.txn import READ_FS, WRITE_FS


def generator(group_size: int = 2, keys_per_group: Optional[int] = None):
    """Write txns touch one key; read txns read a whole key group
    (long_fork.clj's generator shape)."""
    keys_per_group = keys_per_group or group_size
    counter = itertools.count(1)
    group = itertools.count(0)

    def one():
        g = next(group) % 4
        base = g * keys_per_group
        ks = list(range(base, base + keys_per_group))
        if random.random() < 0.5:
            k = random.choice(ks)
            return {"f": "txn", "value": [["w", k, next(counter)]]}
        return {"f": "txn", "value": [["r", k, None] for k in ks]}

    return gen.FnGen(one)


class LongForkChecker(Checker):
    def check(self, test, history: History, opts=None):
        # collect ok read-only txns and the write of each (key, value)
        reads: List[Dict[Any, Any]] = []
        for op in history:
            if op.type != OK or not isinstance(op.value, (list, tuple)):
                continue
            mops = op.value
            if all(f in READ_FS for f, _, _ in mops):
                reads.append({k: v for f, k, v in mops})

        forks = []
        for i, r1 in enumerate(reads):
            for r2 in reads[i + 1:]:
                shared = [k for k in r1 if k in r2]
                # find keys x,y where r1 has x but not y, r2 has y but not x
                for x in shared:
                    for y in shared:
                        if x == y:
                            continue
                        if (r1[x] is not None and r1[y] is None and
                                r2[x] is None and r2[y] is not None):
                            forks.append({"r1": r1, "r2": r2,
                                          "keys": [x, y]})
                if len(forks) > 8:
                    break
            if len(forks) > 8:
                break
        if not reads:
            return {"valid": UNKNOWN, "error": "no read transactions"}
        return {"valid": not forks, "reads": len(reads),
                "forks": forks[:8]}


def workload(group_size: int = 2) -> Dict[str, Any]:
    return {"generator": generator(group_size),
            "checker": LongForkChecker()}
