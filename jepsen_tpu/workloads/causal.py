"""Causal-consistency workloads: causal register and causal-reverse.

Parity:
- jepsen.tests.causal (jepsen/src/jepsen/tests/causal.clj): a causal
  register model — ops carry [k, v] where a read's expected value encodes
  its causal predecessor; the checker walks the history asserting each op's
  causal preconditions.
- jepsen.tests.causal-reverse (causal_reverse.clj:21-114): strict
  serializability's write-precedence — if w1 completes before w2 begins in
  real time, no read may observe w2's effect while missing w1's.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from jepsen_tpu.checker.core import Checker, UNKNOWN
from jepsen_tpu.history import History, INVOKE, OK, Op
from jepsen_tpu.models.base import Model, inconsistent


@dataclass(frozen=True)
class CausalRegister(Model):
    """A register where each write's value must be exactly one greater than
    the last value this session observed — reads carry the causally-expected
    value (causal.clj:13-27's CausalRegister)."""

    value: int = 0

    def step(self, op: Op):
        if op.f == "write":
            if op.value == self.value + 1:
                return CausalRegister(op.value)
            return inconsistent(
                f"write {op.value} out of causal order after {self.value}")
        if op.f in ("read", "read-init"):
            if op.value is None or op.value == self.value:
                return self
            return inconsistent(
                f"read {op.value}, causally expected {self.value}")
        return inconsistent(f"unknown f {op.f!r}")


class CausalChecker(Checker):
    """Sequentially step the model through completed client ops in history
    order (the causal checker pattern of causal.clj)."""

    def __init__(self, model: Optional[Model] = None):
        self.model = model or CausalRegister()

    def check(self, test, history: History, opts=None):
        from jepsen_tpu.models.base import Inconsistent
        m = self.model
        for op in history.complete():
            if op.type != INVOKE or op.process == "nemesis":
                continue
            m2 = m.step(op)
            if isinstance(m2, Inconsistent):
                return {"valid": False, "error": m2.msg, "op": op.to_dict()}
            m = m2
        return {"valid": True, "final": repr(m)}


class CausalReverseChecker(Checker):
    """Write-precedence for strict serializability (causal_reverse.clj):
    writes of unique values to one key; reads return the list of values in
    write order.  If w(a) completed before w(b) was invoked, then any read
    containing b must also contain a (and before it)."""

    def check(self, test, history: History, opts=None):
        pairs = history.pair_index()
        w_done: Dict[Any, int] = {}     # value -> completion index
        w_begin: Dict[Any, int] = {}    # value -> invocation index
        for i, op in enumerate(history):
            if op.f == "w" or op.f == "write":
                if op.type == INVOKE:
                    w_begin[op.value] = i
                elif op.type == OK:
                    j = pairs[i]
                    v = history[j].value if j >= 0 else op.value
                    w_done[v] = i
        errors = []
        for op in history:
            if op.f not in ("read", "r") or op.type != OK or \
                    not isinstance(op.value, (list, tuple)):
                continue
            seen = list(op.value)
            pos = {v: i for i, v in enumerate(seen)}
            for b in seen:
                for a, done_i in w_done.items():
                    if a == b:
                        continue
                    begin_b = w_begin.get(b)
                    if begin_b is not None and done_i < begin_b:
                        # a strictly precedes b in real time
                        if a not in pos:
                            errors.append({"missing": a, "saw": b,
                                           "read": op.to_dict()})
                        elif pos[a] > pos[b]:
                            errors.append({"reversed": [a, b],
                                           "read": op.to_dict()})
        return {"valid": not errors, "errors": errors[:8]}
