"""Set workload: unique adds, final (or repeated) reads.

Parity: the set workloads used across the reference's suites, checked by
checker/set and checker/set-full (jepsen/src/jepsen/checker.clj:240,294).
"""

from __future__ import annotations

import itertools
from typing import Any, Dict

from jepsen_tpu import generator as gen
from jepsen_tpu.checker.core import SetChecker, SetFullChecker


def adds():
    counter = itertools.count()
    return gen.FnGen(lambda: {"f": "add", "value": next(counter)})


def final_read():
    return gen.once({"f": "read"})


def workload(full: bool = False, read_interval: float = 1.0) -> Dict[str, Any]:
    if full:
        # interleave reads throughout (set-full analysis needs them)
        g = gen.mix([adds(), gen.stagger(read_interval,
                                         gen.repeat({"f": "read"}))])
        return {"generator": g, "checker": SetFullChecker()}
    return {"generator": adds(), "final_generator": final_read(),
            "checker": SetChecker()}
