"""Linearizable register workload — per-key CAS registers, device-checked.

Parity: jepsen.tests.linearizable-register
(jepsen/src/jepsen/tests/linearizable_register.clj:18-53): r/w/cas ops
lifted over keys via independent, each key's sub-history checked for
linearizability.  TPU-first: the per-key checker is the device engine, and
all keys check as one vmapped batch (independent.IndependentChecker).
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Dict, Optional

from jepsen_tpu import generator as gen
from jepsen_tpu import independent
from jepsen_tpu.checker.linearizable import linearizable
from jepsen_tpu.models import get_model


def r():
    return {"f": "read"}


def w(values: int = 5):
    return lambda: {"f": "write", "value": random.randrange(values)}


def cas(values: int = 5):
    return lambda: {"f": "cas",
                    "value": [random.randrange(values),
                              random.randrange(values)]}


def key_gen(k, values: int = 5, ops_per_key: int = 100,
            unique_writes: bool = False):
    if unique_writes:
        # Every written value is distinct (per-key monotonic counter), so a
        # stale read is *unambiguously* stale: with a small reused domain a
        # frozen replica's answer often coincides with some legal current
        # value and linearizes anyway — the reason probabilistic
        # stale-read refutation tests flake.  CAS guesses a recent value as
        # ``old`` so it still sometimes succeeds.
        cnt = itertools.count()

        def w_():
            return {"f": "write", "value": next(cnt)}

        def cas_():
            n = next(cnt)
            return {"f": "cas", "value": [random.randrange(max(1, n)), n]}

        return gen.limit(ops_per_key, gen.mix([gen.FnGen(lambda: r()),
                                               gen.FnGen(w_),
                                               gen.FnGen(cas_)]))
    return gen.limit(ops_per_key, gen.mix([gen.FnGen(lambda: r()),
                                           gen.FnGen(w(values)),
                                           gen.FnGen(cas(values))]))


def workload(keys=None, values: int = 5, ops_per_key: int = 100,
             threads_per_key: int = 2, mesh=None,
             algorithm: Optional[str] = None,
             unique_writes: bool = False, **engine_opts) -> Dict[str, Any]:
    keys = list(keys if keys is not None else range(8))
    model = get_model("cas-register")
    return {
        "generator": independent.concurrent_generator(
            threads_per_key, keys,
            lambda k: key_gen(k, values, ops_per_key, unique_writes)),
        "checker": independent.checker(
            linearizable(model, algorithm, **engine_opts), mesh=mesh),
        "model": model,
    }
