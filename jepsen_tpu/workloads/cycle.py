"""Transactional cycle workloads — generators + Elle-equivalent checkers.

Parity: jepsen.tests.cycle / cycle.append / cycle.wr (the thin adapters at
jepsen/src/jepsen/tests/cycle/append.clj:11-46 and wr.clj:9-25): generators
emit micro-op transactions; checkers run the anomaly inference from
jepsen_tpu.elle.
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Dict, Optional

from jepsen_tpu import generator as gen
from jepsen_tpu.checker.core import Checker
from jepsen_tpu.elle import list_append, rw_register
from jepsen_tpu.elle.render import write_artifacts
from jepsen_tpu.history import History


def append_gen(keys: int = 8, min_len: int = 1, max_len: int = 4,
               read_p: float = 0.5):
    """Random list-append transactions with per-key unique values."""
    counters = [itertools.count(1) for _ in range(keys)]

    def one():
        txn = []
        for _ in range(random.randint(min_len, max_len)):
            k = random.randrange(keys)
            if random.random() < read_p:
                txn.append(["r", k, None])
            else:
                txn.append(["append", k, next(counters[k])])
        return {"f": "txn", "value": txn}

    return gen.FnGen(one)


def wr_gen(keys: int = 8, min_len: int = 1, max_len: int = 4,
           read_p: float = 0.5):
    counters = [itertools.count(1) for _ in range(keys)]

    def one():
        txn = []
        for _ in range(random.randint(min_len, max_len)):
            k = random.randrange(keys)
            if random.random() < read_p:
                txn.append(["r", k, None])
            else:
                txn.append(["w", k, next(counters[k])])
        return {"f": "txn", "value": txn}

    return gen.FnGen(one)


class AppendChecker(Checker):
    """``consistency_models`` mirrors append.clj:15-21: validity is judged
    against the requested models (e.g. ``("snapshot-isolation",)`` passes
    write-skew); the elle-style ``not``/``also-not`` boundary is reported
    either way."""

    def __init__(self, realtime: bool = False, consistency_models=None):
        self.realtime = realtime
        self.consistency_models = consistency_models

    def check(self, test, history: History, opts=None):
        res = list_append.check(
            history, realtime=self.realtime,
            consistency_models=self.consistency_models)
        write_artifacts(test, res, opts)
        return res


class WrChecker(Checker):
    def __init__(self, realtime: bool = False,
                 consistency_models=None,
                 sequential_keys: bool = False,
                 linearizable_keys: bool = False):
        self.realtime = realtime
        self.consistency_models = consistency_models
        self.sequential_keys = sequential_keys
        self.linearizable_keys = linearizable_keys

    def check(self, test, history: History, opts=None):
        res = rw_register.check(history, realtime=self.realtime,
                                consistency_models=self.consistency_models,
                                sequential_keys=self.sequential_keys,
                                linearizable_keys=self.linearizable_keys)
        write_artifacts(test, res, opts)
        return res


def append_workload(keys: int = 8, consistency_models=None,
                    **kw) -> Dict[str, Any]:
    return {"generator": append_gen(keys, **kw),
            "checker": AppendChecker(consistency_models=consistency_models)}


def wr_workload(keys: int = 8, consistency_models=None,
                **kw) -> Dict[str, Any]:
    return {"generator": wr_gen(keys, **kw),
            "checker": WrChecker(consistency_models=consistency_models)}
