"""Synthetic history generation — golden corpora for checker tests and bench.

The reference tests its checkers on hand-written histories
(test/jepsen/checker_test.clj, test/jepsen/perf_test.clj:11-60); at 10k ops
that needs a generator.  ``cas_register_history`` simulates an actual
concurrent execution against a sequential register — invocations, effects,
and completions interleave freely, processes can crash mid-op — so the
result is linearizable *by construction*.  ``corrupt_reads`` then flips
observed read values to produce refutable histories with a known culprit.
"""

from __future__ import annotations

import random
from typing import List, Optional

from jepsen_tpu.history import FAIL, History, INFO, INVOKE, OK, Op


def cas_register_history(n_ops: int,
                         concurrency: int = 5,
                         values: int = 5,
                         crash_p: float = 0.003,
                         seed: int = 0,
                         read_p: float = 0.5,
                         write_p: float = 0.25) -> History:
    """Simulate ``n_ops`` reads/writes/cas against one register.

    Returns a linearizable history (invoke/ok/fail/info entries, values
    filled, nanosecond-ish times).  Crashed ops (probability ``crash_p``)
    become ``info``; half of crashed mutations still take effect later —
    exercising the forever-pending window path.
    """
    rng = random.Random(seed)
    state: Optional[int] = None
    history: List[Op] = []
    free = list(range(concurrency))
    # pending: process -> dict(op, effected, result_type, result_value)
    pending = {}
    # crashed-but-will-still-effect ops waiting for their moment
    ghost_effects = []
    t = 0
    invoked = 0

    def effect(p):
        nonlocal state
        d = pending[p]
        op = d["op"]
        if op.f == "read":
            d["result_value"] = state
            d["result_type"] = OK
        elif op.f == "write":
            state = op.value
            d["result_value"] = op.value
            d["result_type"] = OK
        else:  # cas
            old, new = op.value
            if state == old:
                state = new
                d["result_type"] = OK
            else:
                d["result_type"] = FAIL
            d["result_value"] = op.value
        d["effected"] = True

    while invoked < n_ops or pending:
        t += rng.randint(1, 1000)
        # Maybe fire a deferred ghost effect from a crashed op.
        if ghost_effects and rng.random() < 0.3:
            ge = ghost_effects.pop(rng.randrange(len(ghost_effects)))
            if ge["op"].f == "write":
                state = ge["op"].value
            elif ge["op"].f == "cas":
                old, new = ge["op"].value
                if state == old:
                    state = new
        roll = rng.random()
        if free and invoked < n_ops and (roll < 0.45 or not pending):
            p = free.pop(rng.randrange(len(free)))
            r = rng.random()
            if r < read_p:
                op = Op(process=p, type=INVOKE, f="read", value=None, time=t)
            elif r < read_p + write_p:
                op = Op(process=p, type=INVOKE, f="write",
                        value=rng.randrange(values), time=t)
            else:
                op = Op(process=p, type=INVOKE, f="cas",
                        value=[rng.randrange(values), rng.randrange(values)],
                        time=t)
            history.append(op)
            pending[p] = {"op": op, "effected": False,
                          "result_type": None, "result_value": None}
            invoked += 1
        elif pending:
            p = rng.choice(list(pending))
            d = pending[p]
            if rng.random() < crash_p:
                # Crash: process never reports back.
                history.append(Op(process=p, type=INFO, f=d["op"].f,
                                  value=None, time=t, error="crashed"))
                if not d["effected"] and d["op"].f != "read" and rng.random() < 0.5:
                    ghost_effects.append(d)
                del pending[p]
                # Process id is burned (the runtime would spawn a fresh one);
                # reuse here to keep concurrency bounded — window slots in the
                # checker are assigned independently of process ids.
                free.append(p)
            elif not d["effected"]:
                effect(p)
            else:
                history.append(Op(process=p, type=d["result_type"],
                                  f=d["op"].f, value=d["result_value"], time=t))
                del pending[p]
                free.append(p)

    return History(history)


def doomed_cas_padding(n: int, start_process: int = 9000,
                       base_expect: int = 7777) -> List[Op]:
    """``n`` crashed CAS ops whose expected value (``base_expect + i``) lies
    outside any value :func:`cas_register_history` writes (its domain is
    ``range(values)``, small): they hold pending-window slots forever but can
    never be linearized from a reachable state, so they widen the engine's
    window — per-closure-round cost is O(capacity * window) — without
    multiplying the configuration set.  Interleave with a workload history
    (reindex=True) to build wide-window-yet-decidable benchmark tiers."""
    return ([Op(process=start_process + i, type=INVOKE, f="cas",
                value=[base_expect + i, 1]) for i in range(n)]
            + [Op(process=start_process + i, type=INFO, f="cas", value=None)
               for i in range(n)])


def ghost_write_burst(k: int, start_process: int = 2000,
                      base_value: int = 100) -> List[Op]:
    """``k`` crashed writes of distinct values: each one stays pending
    forever and may or may not have taken effect, so each roughly doubles
    the reachable configuration set (masks) and multiplies states — the
    capacity driver for escalation/ceiling tests and bench tiers."""
    out = []
    for i in range(k):
        out.append(Op(process=start_process + i, type=INVOKE, f="write",
                      value=base_value + i))
        out.append(Op(process=start_process + i, type=INFO, f="write",
                      value=None))
    return out


def bitset_ceiling_history(k: int, n_clean: int = 200,
                           concurrency: int = 4,
                           domain_off: int = 32) -> History:
    """``k`` crashed ``add`` ops on a grow-only bitset + a clean stream.

    A register's state only remembers the LAST linearized value, so ghost
    subset-subsumption collapses any crashed-write pileup to an O(k)
    antichain — a register history cannot exercise a capacity ceiling
    once the engine's dedup is doing its job.  A bitset's state IS the
    linearized subset: ``k`` crashed adds of distinct elements give 2^k
    genuinely distinct (mask, state) configurations that neither class
    canonicalization nor subset-subsumption can merge (every state
    differs).  The clean tail (adds/reads of elements outside the ghost
    range, overlapped ``concurrency`` wide) forces closures that
    materialize the subsets until any capacity ladder overflows."""
    ops: List[Op] = [Op(process=3000 + i, type=INVOKE, f="add", value=i)
                     for i in range(k)]
    ops += [Op(process=3000 + i, type=INFO, f="add", value=None)
            for i in range(k)]
    pend: List[Op] = []
    for j in range(n_clean):
        p = j % concurrency
        if len(pend) == concurrency:
            for q in pend:
                ops.append(Op(process=q.process, type=OK, f=q.f,
                              value=q.value))
            pend = []
        if j % 3 == 2:
            op = Op(process=p, type=INVOKE, f="read",
                    value=(domain_off + j - 2, 1))
        else:
            op = Op(process=p, type=INVOKE, f="add", value=domain_off + j)
        ops.append(op)
        pend.append(op)
    for q in pend:
        ops.append(Op(process=q.process, type=OK, f=q.f, value=q.value))
    return History(ops, reindex=True)


def multi_register_history(n_ops: int,
                           keys: int = 3,
                           concurrency: int = 5,
                           values: int = 5,
                           crash_p: float = 0.003,
                           seed: int = 0,
                           read_p: float = 0.5) -> History:
    """Simulate ``n_ops`` multi-key reads/writes against a key->value map
    (the multi_key_acid.clj / BASELINE configs #4-#5 shape): writes upsert a
    random key subset atomically; reads invoke with ``[[k, None], ...]``
    placeholders and OK-complete with the observed values (None for unset
    keys — nil reads are always legal).  Linearizable by construction."""
    rng = random.Random(seed)
    state: dict = {}
    history: List[Op] = []
    free = list(range(concurrency))
    pending = {}
    ghost_effects = []
    t = 0
    invoked = 0

    def subset():
        ks = rng.sample(range(keys), rng.randint(1, keys))
        return sorted(ks)

    def effect(p):
        d = pending[p]
        op = d["op"]
        if op.f == "read":
            d["result_value"] = [[k, state.get(k)] for k, _ in op.value]
        else:
            state.update({k: v for k, v in op.value})
            d["result_value"] = op.value
        d["result_type"] = OK
        d["effected"] = True

    while invoked < n_ops or pending:
        t += rng.randint(1, 1000)
        if ghost_effects and rng.random() < 0.3:
            ge = ghost_effects.pop(rng.randrange(len(ghost_effects)))
            state.update({k: v for k, v in ge["op"].value})
        roll = rng.random()
        if free and invoked < n_ops and (roll < 0.45 or not pending):
            p = free.pop(rng.randrange(len(free)))
            if rng.random() < read_p:
                op = Op(process=p, type=INVOKE, f="read",
                        value=[[k, None] for k in subset()], time=t)
            else:
                op = Op(process=p, type=INVOKE, f="write",
                        value=[[k, rng.randrange(values)] for k in subset()],
                        time=t)
            history.append(op)
            pending[p] = {"op": op, "effected": False,
                          "result_type": None, "result_value": None}
            invoked += 1
        elif pending:
            p = rng.choice(list(pending))
            d = pending[p]
            if rng.random() < crash_p:
                history.append(Op(process=p, type=INFO, f=d["op"].f,
                                  value=d["op"].value if d["op"].f != "read"
                                  else None,
                                  time=t, error="crashed"))
                if (not d["effected"] and d["op"].f != "read"
                        and rng.random() < 0.5):
                    ghost_effects.append(d)
                del pending[p]
                free.append(p)
            elif not d["effected"]:
                effect(p)
            else:
                history.append(Op(process=p, type=d["result_type"],
                                  f=d["op"].f, value=d["result_value"],
                                  time=t))
                del pending[p]
                free.append(p)

    return History(history)


def corrupt_multi_reads(history: History, n: int = 1, seed: int = 0,
                        values: int = 5) -> History:
    """Multi-register analog of :func:`corrupt_reads`: flip one observed key
    of ``n`` ok-reads to an out-of-domain value."""
    rng = random.Random(seed)
    ops = [o.with_() for o in history]
    read_oks = [i for i, o in enumerate(ops)
                if o.type == OK and o.f == "read" and o.value]
    if not read_oks:
        raise ValueError("no ok reads to corrupt")
    for i in rng.sample(read_oks, min(n, len(read_oks))):
        pairs = [list(kv) for kv in ops[i].value]
        j = rng.randrange(len(pairs))
        pairs[j][1] = values + 1 + rng.randrange(values)
        ops[i] = ops[i].with_(value=pairs)
    return History(ops, reindex=True)


def list_append_history(n_txns: int = 100,
                        keys: int = 3,
                        concurrency: int = 5,
                        max_txn_len: int = 4,
                        read_p: float = 0.5,
                        fail_p: float = 0.05,
                        anomaly_p: float = 0.0,
                        seed: int = 0) -> History:
    """Simulate ``n_txns`` list-append transactions against an atomic
    per-key list store (elle's append.clj workload shape): each txn is a
    list of ``["append", k, v]`` / ``["r", k, [vs...]]`` mops, appended
    values unique per key, and every txn takes effect atomically at its
    completion — so the history is strict-serializable *by construction*.
    ``fail_p`` txns abort (their appends never apply — G1a bait for the
    corruptor).  ``anomaly_p > 0`` then corrupts that fraction of ok
    reads via :func:`corrupt_list_append`, producing histories with known
    anomaly families for checker fuzzing."""
    rng = random.Random(seed)
    state = {k: [] for k in range(keys)}
    counters = {k: 0 for k in range(keys)}
    history: List[Op] = []
    free = list(range(concurrency))
    pending = {}
    t = 0
    invoked = 0
    while invoked < n_txns or pending:
        t += rng.randint(1, 1000)
        if free and invoked < n_txns and (rng.random() < 0.55 or not pending):
            p = free.pop(rng.randrange(len(free)))
            txn = []
            for _ in range(rng.randint(1, max_txn_len)):
                k = rng.randrange(keys)
                if rng.random() < read_p:
                    txn.append(["r", k, None])
                else:
                    counters[k] += 1
                    txn.append(["append", k, counters[k]])
            history.append(Op(process=p, type=INVOKE, f="txn",
                              value=txn, time=t))
            pending[p] = (txn, rng.random() < fail_p)
            invoked += 1
        elif pending:
            p = rng.choice(list(pending))
            txn, will_fail = pending.pop(p)
            if will_fail:
                history.append(Op(process=p, type=FAIL, f="txn",
                                  value=txn, time=t))
            else:
                filled = []
                for f_, k, v in txn:
                    if f_ == "append":
                        state[k] = state[k] + [v]
                        filled.append(["append", k, v])
                    else:
                        filled.append(["r", k, list(state[k])])
                history.append(Op(process=p, type=OK, f="txn",
                                  value=filled, time=t))
            free.append(p)
    h = History(history, reindex=True)
    if anomaly_p > 0:
        h = corrupt_list_append(h, anomaly_p=anomaly_p, seed=seed)
    return h


def corrupt_list_append(history: History, anomaly_p: float = 0.1,
                        seed: int = 0) -> History:
    """Corrupt ok list-reads to inject elle-detectable anomalies: swap
    the last two observed elements (incompatible-order and order cycles),
    truncate the last element (a stale read — rw inversions), or splice
    in a value appended by a *failed* txn (G1a)."""
    rng = random.Random(seed + 1)
    failed_by_key = {}
    for op in history:
        if op.type == FAIL and isinstance(op.value, (list, tuple)):
            for f_, k, v in op.value:
                if f_ == "append":
                    failed_by_key.setdefault(k, []).append(v)
    ops = [o.with_() for o in history]
    for i, op in enumerate(ops):
        if op.type != OK or not isinstance(op.value, (list, tuple)):
            continue
        txn = [list(m) for m in op.value]
        changed = False
        for m in txn:
            if m[0] != "r" or not m[2] or rng.random() >= anomaly_p:
                continue
            lst = list(m[2])
            roll = rng.random()
            if roll < 0.4 and len(lst) >= 2:
                lst[-1], lst[-2] = lst[-2], lst[-1]
            elif roll < 0.7:
                lst = lst[:-1]
            elif failed_by_key.get(m[1]):
                lst = lst + [rng.choice(failed_by_key[m[1]])]
            else:
                lst = lst[:-1]
            m[2] = lst
            changed = True
        if changed:
            ops[i] = op.with_(value=txn)
    return History(ops, reindex=True)


def rw_register_history(n_txns: int = 100,
                        keys: int = 3,
                        concurrency: int = 5,
                        max_txn_len: int = 4,
                        read_p: float = 0.5,
                        fail_p: float = 0.05,
                        anomaly_p: float = 0.0,
                        seed: int = 0) -> History:
    """Simulate ``n_txns`` read/write-register transactions (elle's
    wr.clj workload shape): ``["w", k, v]`` with v unique per key,
    ``["r", k, v]`` observing the current value, txns atomic at
    completion — strict-serializable by construction.  ``anomaly_p``
    corrupts ok reads via :func:`corrupt_rw_register`."""
    rng = random.Random(seed)
    state = {}
    counters = {k: 0 for k in range(keys)}
    history: List[Op] = []
    free = list(range(concurrency))
    pending = {}
    t = 0
    invoked = 0
    while invoked < n_txns or pending:
        t += rng.randint(1, 1000)
        if free and invoked < n_txns and (rng.random() < 0.55 or not pending):
            p = free.pop(rng.randrange(len(free)))
            txn = []
            for _ in range(rng.randint(1, max_txn_len)):
                k = rng.randrange(keys)
                if rng.random() < read_p:
                    txn.append(["r", k, None])
                else:
                    counters[k] += 1
                    txn.append(["w", k, counters[k]])
            history.append(Op(process=p, type=INVOKE, f="txn",
                              value=txn, time=t))
            pending[p] = (txn, rng.random() < fail_p)
            invoked += 1
        elif pending:
            p = rng.choice(list(pending))
            txn, will_fail = pending.pop(p)
            if will_fail:
                history.append(Op(process=p, type=FAIL, f="txn",
                                  value=txn, time=t))
            else:
                filled = []
                for f_, k, v in txn:
                    if f_ == "w":
                        state[k] = v
                        filled.append(["w", k, v])
                    else:
                        filled.append(["r", k, state.get(k)])
                history.append(Op(process=p, type=OK, f="txn",
                                  value=filled, time=t))
            free.append(p)
    h = History(history, reindex=True)
    if anomaly_p > 0:
        h = corrupt_rw_register(h, anomaly_p=anomaly_p, seed=seed)
    return h


def corrupt_rw_register(history: History, anomaly_p: float = 0.1,
                        seed: int = 0) -> History:
    """Corrupt ok register-reads: rewind to an older committed value of
    the key (stale reads — wr/ww/rw inversions once version orders are
    recovered) or observe a *failed* write's value (G1a)."""
    rng = random.Random(seed + 1)
    committed = {}
    failed_by_key = {}
    for op in history:
        if not isinstance(op.value, (list, tuple)):
            continue
        for f_, k, v in op.value:
            if f_ == "w" and op.type == OK:
                committed.setdefault(k, []).append(v)
            elif f_ == "w" and op.type == FAIL:
                failed_by_key.setdefault(k, []).append(v)
    ops = [o.with_() for o in history]
    for i, op in enumerate(ops):
        if op.type != OK or not isinstance(op.value, (list, tuple)):
            continue
        txn = [list(m) for m in op.value]
        changed = False
        for m in txn:
            if m[0] != "r" or m[2] is None or rng.random() >= anomaly_p:
                continue
            k = m[1]
            older = [v for v in committed.get(k, []) if v != m[2]]
            if rng.random() < 0.7 and older:
                m[2] = rng.choice(older)
            elif failed_by_key.get(k):
                m[2] = rng.choice(failed_by_key[k])
            elif older:
                m[2] = rng.choice(older)
            else:
                continue
            changed = True
        if changed:
            ops[i] = op.with_(value=txn)
    return History(ops, reindex=True)


def corrupt_reads(history: History, n: int = 1, seed: int = 0,
                  values: int = 5,
                  within: float | None = None) -> History:
    """Flip the observed value of ``n`` ok-reads to a value that was never
    current at any point during the read — producing (with overwhelming
    likelihood) a non-linearizable history.  ``within`` restricts the
    corrupted reads to the first fraction of the history (benchmarks use
    it to assert the checker's early exit touches a bounded prefix)."""
    rng = random.Random(seed)
    ops = [o.with_() for o in history]
    cut = len(ops) if within is None else max(1, int(len(ops) * within))
    read_oks = [i for i, o in enumerate(ops[:cut])
                if o.type == OK and o.f == "read"]
    if not read_oks:
        raise ValueError("no ok reads to corrupt")
    for i in rng.sample(read_oks, min(n, len(read_oks))):
        bad = values + 1000 + rng.randrange(100)  # outside the value domain
        ops[i] = ops[i].with_(value=bad)
    return History(ops, reindex=True)


# -- queue workload (the fifo-queue / unordered-queue engine plugins) --------

def queue_history(n_ops: int = 100,
                  concurrency: int = 5,
                  enqueue_p: float = 0.55,
                  crash_p: float = 0.003,
                  seed: int = 0) -> History:
    """Simulate ``n_ops`` enqueues/dequeues against a real FIFO queue:
    enqueued values are unique ints, dequeues invoke with ``None`` and
    OK-complete with the popped head (FAIL on empty — a legal no-op),
    processes can crash mid-op leaving ghost enqueues that may or may not
    have taken effect.  FIFO-linearizable by construction (and therefore
    also unordered-queue-linearizable)."""
    rng = random.Random(seed)
    q: List[int] = []
    history: List[Op] = []
    free = list(range(concurrency))
    pending: dict = {}
    ghost_effects: List[dict] = []
    t = 0
    invoked = 0
    next_v = 0

    while invoked < n_ops or pending:
        t += rng.randint(1, 1000)
        if ghost_effects and rng.random() < 0.3:
            ge = ghost_effects.pop(rng.randrange(len(ghost_effects)))
            q.append(ge["op"].value)
        roll = rng.random()
        if free and invoked < n_ops and (roll < 0.45 or not pending):
            p = free.pop(rng.randrange(len(free)))
            if rng.random() < enqueue_p:
                op = Op(process=p, type=INVOKE, f="enqueue",
                        value=next_v, time=t)
                next_v += 1
            else:
                op = Op(process=p, type=INVOKE, f="dequeue",
                        value=None, time=t)
            history.append(op)
            pending[p] = {"op": op, "effected": False,
                          "result_type": None, "result_value": None}
            invoked += 1
        elif pending:
            p = rng.choice(list(pending))
            d = pending[p]
            if rng.random() < crash_p:
                history.append(Op(process=p, type=INFO, f=d["op"].f,
                                  value=d["op"].value
                                  if d["op"].f == "enqueue" else None,
                                  time=t, error="crashed"))
                if (not d["effected"] and d["op"].f == "enqueue"
                        and rng.random() < 0.5):
                    ghost_effects.append(d)
                del pending[p]
                free.append(p)
            elif not d["effected"]:
                op = d["op"]
                if op.f == "enqueue":
                    q.append(op.value)
                    d["result_type"], d["result_value"] = OK, op.value
                elif q:
                    d["result_type"], d["result_value"] = OK, q.pop(0)
                else:
                    d["result_type"], d["result_value"] = FAIL, None
                d["effected"] = True
            else:
                history.append(Op(process=p, type=d["result_type"],
                                  f=d["op"].f, value=d["result_value"],
                                  time=t,
                                  error="empty"
                                  if d["result_type"] == FAIL else None))
                del pending[p]
                free.append(p)

    return History(history)


def corrupt_queue(history: History, mode: str = "lost", n: int = 1,
                  seed: int = 0) -> History:
    """Inject queue anomalies with a known culprit:

    - ``"lost"``: an ok dequeue observes a phantom value that was never
      enqueued (the real element was lost in flight) — refutes FIFO and
      unordered queues alike;
    - ``"duplicated"``: an ok dequeue re-observes a value an earlier
      dequeue already returned (an element delivered twice);
    - ``"reordered"``: two ok dequeues swap their observed values —
      refutes FIFO order but, elements still leaving exactly once, NOT an
      unordered queue (generate with ``concurrency=1`` to guarantee the
      refutation isn't absorbed by overlap).
    """
    rng = random.Random(seed)
    ops = [o.with_() for o in history]
    deq_oks = [i for i, o in enumerate(ops)
               if o.type == OK and o.f == "dequeue" and o.value is not None]
    enq_vals = {o.value for o in ops if o.f == "enqueue"}
    if mode == "lost":
        if not deq_oks:
            raise ValueError("no ok dequeues to corrupt")
        for i in rng.sample(deq_oks, min(n, len(deq_oks))):
            phantom = max(enq_vals, default=0) + 1000 + rng.randrange(100)
            ops[i] = ops[i].with_(value=phantom)
    elif mode == "duplicated":
        if len(deq_oks) < 2:
            raise ValueError("need >= 2 ok dequeues to duplicate")
        for _ in range(n):
            i, j = sorted(rng.sample(deq_oks, 2))
            ops[j] = ops[j].with_(value=ops[i].value)
    elif mode == "reordered":
        if len(deq_oks) < 2:
            raise ValueError("need >= 2 ok dequeues to reorder")
        for _ in range(n):
            i, j = rng.sample(deq_oks, 2)
            vi, vj = ops[i].value, ops[j].value
            ops[i], ops[j] = ops[i].with_(value=vj), ops[j].with_(value=vi)
    else:
        raise ValueError(f"unknown mode {mode!r}")
    return History(ops, reindex=True)


# -- grow-only set workload (the set engine plugin) --------------------------

def set_history(n_ops: int = 80,
                domain: int = 62,
                concurrency: int = 5,
                read_p: float = 0.4,
                crash_p: float = 0.003,
                seed: int = 0) -> History:
    """Simulate adds of unique elements from ``[0, domain)`` interleaved
    with full-set reads (the jepsen set-full workload): reads invoke with
    ``None`` and OK-complete with the sorted membership; crashed adds may
    or may not have taken effect (ghosts).  Linearizable by construction."""
    rng = random.Random(seed)
    s: set = set()
    history: List[Op] = []
    free = list(range(concurrency))
    pending: dict = {}
    ghost_effects: List[dict] = []
    t = 0
    invoked = 0
    unadded = list(range(domain))
    rng.shuffle(unadded)

    while invoked < n_ops or pending:
        t += rng.randint(1, 1000)
        if ghost_effects and rng.random() < 0.3:
            ge = ghost_effects.pop(rng.randrange(len(ghost_effects)))
            s.add(ge["op"].value)
        roll = rng.random()
        if free and invoked < n_ops and (roll < 0.45 or not pending):
            p = free.pop(rng.randrange(len(free)))
            if rng.random() >= read_p and unadded:
                op = Op(process=p, type=INVOKE, f="add",
                        value=unadded.pop(), time=t)
            else:
                op = Op(process=p, type=INVOKE, f="read",
                        value=None, time=t)
            history.append(op)
            pending[p] = {"op": op, "effected": False,
                          "result_type": None, "result_value": None}
            invoked += 1
        elif pending:
            p = rng.choice(list(pending))
            d = pending[p]
            if rng.random() < crash_p:
                history.append(Op(process=p, type=INFO, f=d["op"].f,
                                  value=d["op"].value
                                  if d["op"].f == "add" else None,
                                  time=t, error="crashed"))
                if (not d["effected"] and d["op"].f == "add"
                        and rng.random() < 0.5):
                    ghost_effects.append(d)
                del pending[p]
                free.append(p)
            elif not d["effected"]:
                op = d["op"]
                if op.f == "add":
                    s.add(op.value)
                    d["result_value"] = op.value
                else:
                    d["result_value"] = sorted(s)
                d["result_type"] = OK
                d["effected"] = True
            else:
                history.append(Op(process=p, type=d["result_type"],
                                  f=d["op"].f, value=d["result_value"],
                                  time=t))
                del pending[p]
                free.append(p)

    return History(history)


def corrupt_set(history: History, mode: str = "phantom", n: int = 1,
                seed: int = 0, domain: int = 62) -> History:
    """Inject set anomalies with a known culprit:

    - ``"phantom"``: an ok read observes an element that was never added;
    - ``"lost"``: an ok read drops an element it should have observed
      (corrupts non-empty reads; with concurrent adds in flight the drop
      can be legal, so refutation tests generate with low concurrency).
    """
    rng = random.Random(seed)
    ops = [o.with_() for o in history]
    read_oks = [i for i, o in enumerate(ops)
                if o.type == OK and o.f == "read"]
    added = {o.value for o in ops if o.f == "add"}
    if mode == "phantom":
        if not read_oks:
            raise ValueError("no ok reads to corrupt")
        never = [e for e in range(domain) if e not in added]
        if not never:
            raise ValueError("domain exhausted; no phantom available")
        for i in rng.sample(read_oks, min(n, len(read_oks))):
            ops[i] = ops[i].with_(
                value=sorted(set(ops[i].value) | {rng.choice(never)}))
    elif mode == "lost":
        full = [i for i in read_oks if ops[i].value]
        if not full:
            raise ValueError("no non-empty ok reads to corrupt")
        for i in rng.sample(full, min(n, len(full))):
            v = list(ops[i].value)
            v.remove(rng.choice(v))
            ops[i] = ops[i].with_(value=v)
    else:
        raise ValueError(f"unknown mode {mode!r}")
    return History(ops, reindex=True)


# -- transactional workload (the opacity checker) ----------------------------

def txn_history(n_txns: int = 60,
                keys: int = 3,
                values: int = 16,
                max_txn_len: int = 4,
                concurrency: int = 5,
                abort_p: float = 0.15,
                crash_p: float = 0.003,
                seed: int = 0) -> History:
    """Simulate transactions over a ``keys``-key register: each txn is a
    random mix of ``["r", k, None]`` / ``["w", k, v]`` micro-ops applied
    atomically at effect time (reads fill sequentially, seeing the txn's
    own earlier writes).  With probability ``abort_p`` the txn aborts
    AFTER its reads observed real state — its writes are discarded and it
    FAIL-completes carrying the filled reads, exactly the shape the
    opacity reduction consumes.  Crashes leave indeterminate (info)
    txns.  Opaque by construction."""
    rng = random.Random(seed)
    state: dict = {}
    history: List[Op] = []
    free = list(range(concurrency))
    pending: dict = {}
    ghost_effects: List[dict] = []
    t = 0
    invoked = 0

    def gen_txn():
        mops = []
        for _ in range(rng.randint(1, max_txn_len)):
            k = rng.randrange(keys)
            if rng.random() < 0.5:
                mops.append(["r", k, None])
            else:
                mops.append(["w", k, rng.randrange(values)])
        return mops

    def apply_txn(mops, commit: bool):
        view = dict(state)
        filled = []
        for ftag, k, v in mops:
            if ftag == "r":
                filled.append(["r", k, view.get(k)])
            else:
                view[k] = v
                filled.append(["w", k, v])
        if commit:
            state.clear()
            state.update(view)
        return filled

    while invoked < n_txns or pending:
        t += rng.randint(1, 1000)
        if ghost_effects and rng.random() < 0.3:
            ge = ghost_effects.pop(rng.randrange(len(ghost_effects)))
            apply_txn(ge["op"].value, commit=True)
        roll = rng.random()
        if free and invoked < n_txns and (roll < 0.45 or not pending):
            p = free.pop(rng.randrange(len(free)))
            op = Op(process=p, type=INVOKE, f="txn", value=gen_txn(),
                    time=t)
            history.append(op)
            pending[p] = {"op": op, "effected": False,
                          "result_type": None, "result_value": None}
            invoked += 1
        elif pending:
            p = rng.choice(list(pending))
            d = pending[p]
            if rng.random() < crash_p:
                history.append(Op(process=p, type=INFO, f="txn",
                                  value=d["op"].value, time=t,
                                  error="crashed"))
                if not d["effected"] and rng.random() < 0.5:
                    ghost_effects.append(d)
                del pending[p]
                free.append(p)
            elif not d["effected"]:
                commit = rng.random() >= abort_p
                d["result_value"] = apply_txn(d["op"].value, commit)
                d["result_type"] = OK if commit else FAIL
                d["effected"] = True
            else:
                history.append(Op(process=p, type=d["result_type"],
                                  f="txn", value=d["result_value"],
                                  time=t,
                                  error="aborted"
                                  if d["result_type"] == FAIL else None))
                del pending[p]
                free.append(p)

    return History(history)


def corrupt_txn_reads(history: History, n: int = 1, seed: int = 0,
                      target: str = "fail", values: int = 16) -> History:
    """Flip one constraining (external, non-nil) read of ``n`` completed
    txns to a different in-domain value.  ``target="fail"`` corrupts
    aborted txns — the committed subhistory stays linearizable, so only
    an *opacity* checker refutes (the reduction's distinguishing case);
    ``target="ok"`` corrupts committed txns."""
    rng = random.Random(seed)
    ops = [o.with_() for o in history]
    want = FAIL if target == "fail" else OK

    def external_reads(mops):
        written = set()
        out = []
        for idx, m in enumerate(mops):
            if m[0] == "w":
                written.add(m[1])
            elif m[0] == "r" and m[2] is not None and m[1] not in written:
                out.append(idx)
        return out

    cands = [i for i, o in enumerate(ops)
             if o.type == want and o.f == "txn" and o.value
             and external_reads(o.value)]
    if not cands:
        raise ValueError(f"no {target} txns with constraining reads")
    for i in rng.sample(cands, min(n, len(cands))):
        mops = [list(m) for m in ops[i].value]
        j = rng.choice(external_reads(mops))
        old = mops[j][2]
        mops[j][2] = (old + 1 + rng.randrange(values - 1)) % values
        ops[i] = ops[i].with_(value=mops)
    return History(ops, reindex=True)
