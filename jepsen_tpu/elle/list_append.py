"""List-append anomaly inference.

Parity: elle.list-append as consumed by the reference
(jepsen/src/jepsen/tests/cycle/append.clj:11-46).  The workload: each
transaction is a list of mops ``["append", k, v]`` / ``["r", k, [v...]]``
where appended values are unique per key.  Reads observe the key's whole
list, which *traces the version history exactly* — that's what makes
dependency inference sound:

- version order per key = the longest read list (all reads must agree on
  prefixes; disagreement = :incompatible-order);
- wr edge  W →wr R:  R read a list whose last element was appended by W;
- ww edge  W1 →ww W2: W2 appended the value immediately following W1's in
  the version order;
- rw edge  R →rw W:  R observed the state just before W's append;
- realtime edge T1 → T2: T1's ok preceded T2's invoke (strict mode).

Anomalies: G1a (read of aborted write), G1b (read of intermediate state),
duplicates, incompatible orders, and dependency cycles classified as
G0 (ww only), G1c (ww+wr), G-single (exactly one rw), G2-item (≥1 rw).
"""

from __future__ import annotations

from collections import defaultdict
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from jepsen_tpu.elle.graph import Graph, cycle_edge_kinds, peeled_cycles
from jepsen_tpu.history import FAIL, History, INFO, INVOKE, OK, Op

CYCLE_SEVERITY = ["G0", "G1c", "G-single", "G2-item"]


def classify_cycle(kind_sets: List[Set[str]]) -> str:
    has_rw = sum(1 for ks in kind_sets if ks <= {"rw"})
    any_rw = any("rw" in ks for ks in kind_sets)
    only_ww = all("ww" in ks for ks in kind_sets)
    if only_ww and not any_rw:
        return "G0"
    if not any_rw:
        return "G1c"
    if has_rw == 1 or sum(1 for ks in kind_sets if "rw" in ks) == 1:
        return "G-single"
    return "G2-item"


def check(history: History, consistency_models: Sequence[str] = ("serializable",),
          realtime: bool = False) -> Dict[str, Any]:
    """Analyze a list-append history; returns an elle-shaped result map."""
    oks: List[Tuple[int, Op]] = []
    failed_writes: Set[Tuple[Any, Any]] = set()
    info_writes: Set[Tuple[Any, Any]] = set()
    pairs = history.pair_index()

    for i, op in enumerate(history):
        if not isinstance(op.value, (list, tuple)):
            continue
        if op.type == OK:
            oks.append((i, op))
        elif op.type in (FAIL, INFO):
            j = pairs[i]
            txn = op.value if op.value else (
                history[j].value if j >= 0 else None)
            if txn:
                for f, k, v in txn:
                    if f == "append":
                        (failed_writes if op.type == FAIL
                         else info_writes).add((k, v))

    anomalies: Dict[str, List[Any]] = defaultdict(list)

    # writer index + duplicate detection
    writer: Dict[Tuple[Any, Any], int] = {}
    txn_of: Dict[int, List] = {}
    for tid, (_, op) in enumerate(oks):
        txn_of[tid] = op.value
        for f, k, v in op.value:
            if f == "append":
                if (k, v) in writer:
                    anomalies["duplicate-appends"].append(
                        {"key": k, "value": v})
                writer[(k, v)] = tid

    # per-key longest read + prefix consistency + G1a/G1b
    longest: Dict[Any, List[Any]] = {}
    for tid, (_, op) in enumerate(oks):
        for f, k, v in op.value:
            if f not in ("r", "read") or v is None:
                continue
            lst = list(v)
            # G1a: observed value appended by a failed txn
            for x in lst:
                if (k, x) in failed_writes:
                    anomalies["G1a"].append({"key": k, "value": x,
                                             "reader": op.to_dict()})
            cur = longest.get(k, [])
            short, long_ = (lst, cur) if len(lst) <= len(cur) else (cur, lst)
            if short != long_[:len(short)]:
                anomalies["incompatible-order"].append(
                    {"key": k, "a": cur, "b": lst})
            if len(lst) > len(cur):
                longest[k] = lst

    # G1b: a read that ends inside another txn's append run
    # (observes some but not all of a txn's appends to k, with nothing after)
    appends_by_txn_key: Dict[Tuple[int, Any], List[Any]] = defaultdict(list)
    for tid, (_, op) in enumerate(oks):
        for f, k, v in op.value:
            if f == "append":
                appends_by_txn_key[(tid, k)].append(v)
    for rtid, (_, op) in enumerate(oks):
        for f, k, v in op.value:
            if f not in ("r", "read") or not v:
                continue
            last = v[-1]
            wtid = writer.get((k, last))
            if wtid is None or wtid == rtid:
                continue
            run = appends_by_txn_key[(wtid, k)]
            if run and last != run[-1]:
                anomalies["G1b"].append({"key": k, "value": last,
                                         "reader": op.to_dict()})

    # dependency graph
    g = Graph()
    for tid in range(len(oks)):
        g.add_node(tid)

    for k, order in longest.items():
        # ww edges along the version order
        for a, b in zip(order, order[1:]):
            wa, wb = writer.get((k, a)), writer.get((k, b))
            if wa is not None and wb is not None and wa != wb:
                g.add_edge(wa, wb, "ww")

    for rtid, (_, op) in enumerate(oks):
        for f, k, v in op.value:
            if f not in ("r", "read") or v is None:
                continue
            lst = list(v)
            if lst:
                w = writer.get((k, lst[-1]))
                if w is not None and w != rtid:
                    g.add_edge(w, rtid, "wr")
            # rw: the next value after the observed state
            order = longest.get(k, [])
            nxt = order[len(lst)] if len(lst) < len(order) and \
                order[:len(lst)] == lst else None
            if nxt is not None:
                w = writer.get((k, nxt))
                if w is not None and w != rtid:
                    g.add_edge(rtid, w, "rw")

    if realtime:
        # T1 -> T2 if T1's completion index < T2's invocation index
        for t1, (i1, op1) in enumerate(oks):
            inv1 = pairs[i1]
            for t2, (i2, op2) in enumerate(oks):
                if t1 == t2:
                    continue
                inv2 = pairs[i2]
                if inv2 >= 0 and i1 < inv2:
                    g.add_edge(t1, t2, "realtime")

    # cycles: peel every node-disjoint cycle out of each SCC
    for cyc in peeled_cycles(g):
        kinds = cycle_edge_kinds(g, cyc)
        label = classify_cycle(kinds)
        anomalies[label].append({
            "cycle": [txn_of[t] for t in cyc],
            "edges": [sorted(ks) for ks in kinds]})

    valid = not anomalies
    return {"valid": valid,
            "anomaly-types": sorted(anomalies),
            "anomalies": {k: v[:8] for k, v in anomalies.items()},
            # complete map for artifact rendering; popped by
            # elle.render.write_artifacts so results stay small
            "anomalies-full": dict(anomalies),
            "count": len(oks)}
