"""List-append anomaly inference.

Parity: elle.list-append as consumed by the reference
(jepsen/src/jepsen/tests/cycle/append.clj:11-46).  The workload: each
transaction is a list of mops ``["append", k, v]`` / ``["r", k, [v...]]``
where appended values are unique per key.  Reads observe the key's whole
list, which *traces the version history exactly* — that's what makes
dependency inference sound:

- version order per key = the longest read list (all reads must agree on
  prefixes; disagreement = :incompatible-order);
- wr edge  W →wr R:  R read a list whose last element was appended by W;
- ww edge  W1 →ww W2: W2 appended the value immediately following W1's in
  the version order;
- rw edge  R →rw W:  R observed the state just before W's append;
- realtime edge T1 → T2: T1's ok preceded T2's invoke (strict mode).

Anomalies: G1a (read of aborted write), G1b (read of intermediate state),
duplicates, incompatible orders, and dependency cycles classified as
G0 (ww only), G1c (ww+wr), G-single (exactly one rw), G2-item (≥1 rw).
"""

from __future__ import annotations

from collections import defaultdict
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from jepsen_tpu.elle import consistency
from jepsen_tpu.elle.graph import (Graph, SearchBudget, cycle_edge_kinds,
                                   edge_list, gsingle_cycles,
                                   nonadjacent_rw_cycles, peeled_cycles)
from jepsen_tpu.history import FAIL, History, INFO, INVOKE, OK, Op

CYCLE_SEVERITY = ["G0", "G1c", "G-single", "G-nonadjacent", "G2-item"]

# Same sentinel as checker.core.UNKNOWN — spelled out so elle stays
# importable without the checker package.
UNKNOWN = "unknown"


def classify_cycle(kind_sets: List[Set[str]]) -> str:
    """Label a cycle by the *weakest-model-refuting* reading of its edges:
    an edge offering a non-rw kind is read as non-rw (fewer anti-dependency
    edges refute weaker models), and edges closable only in realtime push
    the label to its ``-realtime`` variant (refutes only the strict tier).

    G0 all-ww < G1c ww+wr < G-single (one forced rw) < G-nonadjacent
    (>= 2 forced rw, none cyclically adjacent — the un-SI-able shape) <
    G2-item (>= 2 forced rw, some adjacent — SI-legal write skew)."""
    rt_needed = any(ks == {"realtime"} for ks in kind_sets)
    core = [ks - {"realtime"} for ks in kind_sets]
    rw_pos = [i for i, ks in enumerate(core) if ks == {"rw"}]
    n = len(core)
    if not rw_pos:
        if all((not ks) or ("ww" in ks) for ks in core):
            label = "G0"
        else:
            label = "G1c"
    elif len(rw_pos) == 1:
        label = "G-single"
    else:
        adjacent = any((j - i) % n == 1
                       for i in rw_pos for j in rw_pos if i != j)
        label = "G2-item" if adjacent else "G-nonadjacent"
    return label + ("-realtime" if rt_needed else "")


def _cycle_sig(cyc: List[int]) -> Tuple[int, ...]:
    """Rotation-normalized signature of a cycle [n0, ..., n0]."""
    body = tuple(cyc[:-1])
    k = body.index(min(body))
    return body[k:] + body[:k]


def collect_cycle_anomalies(g: Graph, txn_of: Dict[int, List],
                            anomalies: Dict[str, List[Any]],
                            budget: Optional[SearchBudget] = None) -> bool:
    """Run the full cycle-search suite and file each distinct cycle under
    its label.  The generic peeled pass alone is not enough below
    serializability: one SCC can hide a G-single or G-nonadjacent witness
    behind a shorter SI-legal cycle, so each anomaly family gets its own
    targeted search (elle searches per anomaly type the same way):

    - ww subgraph          -> G0
    - ww+wr subgraph       -> G1c (its all-ww cycles dedup into G0)
    - one-rw return paths  -> G-single
    - nonadjacent-rw BFS   -> G-nonadjacent
    - full graph, peeled   -> G2-item and anything the above missed

    ``budget`` (one :class:`SearchBudget` shared by all five searches)
    bounds the work; returns True when the suite was truncated — the
    caller must then degrade a clean verdict (see :func:`finish_result`).
    """
    searches = [
        peeled_cycles(g.filter_kinds({"ww", "realtime"}), budget),
        peeled_cycles(g.filter_kinds({"ww", "wr", "realtime"}), budget),
        gsingle_cycles(g, budget=budget),
        nonadjacent_rw_cycles(g, search_budget=budget),
        peeled_cycles(g, budget),
    ]
    seen: Set[Tuple] = set()
    for cycles in searches:
        for cyc in cycles:
            kinds = cycle_edge_kinds(g, cyc)
            label = classify_cycle(kinds)
            key = (label, _cycle_sig(cyc))
            if key in seen:
                continue
            seen.add(key)
            anomalies[label].append({
                "cycle": [txn_of[t] for t in cyc],
                "edges": [sorted(ks) for ks in kinds]})
    return budget is not None and budget.truncated


@dataclass
class Analysis:
    """Everything the linear host pass produces *before* cycle search: the
    dependency graph (ww/wr/rw only — the realtime layer is dense and is
    added on demand via :func:`add_realtime_edges`), per-txn labels, the
    host-detectable anomalies (G1a/G1b/duplicates/incompatible-order), and
    the ok/pair indices the realtime order derives from.  This is the
    shared front half of the CPU checker and the elle_tpu encoder — both
    paths literally analyze the same object, which is what makes their
    anomaly sets identical by construction."""
    graph: Graph
    txn_of: Dict[int, List]
    anomalies: Dict[str, List[Any]] = field(default_factory=dict)
    oks: List[Tuple[int, Op]] = field(default_factory=list)
    pairs: Sequence[int] = ()

    @property
    def count(self) -> int:
        return len(self.oks)


def add_realtime_edges(g: Graph, oks: List[Tuple[int, Op]],
                       pairs: Sequence[int]) -> None:
    """T1 -> T2 iff T1's completion index precedes T2's invocation index
    (strict mode).  O(n^2) and dense — kept out of :func:`analyze` so the
    device engine can compute the same relation as a broadcast compare and
    only materialize these edges for witness recovery."""
    for t1, (i1, _) in enumerate(oks):
        for t2, (i2, _) in enumerate(oks):
            if t1 == t2:
                continue
            inv2 = pairs[i2]
            if inv2 >= 0 and i1 < inv2:
                g.add_edge(t1, t2, "realtime")


def check(history: History,
          consistency_models: Optional[Sequence[str]] = None,
          realtime: bool = False,
          search_budget: Optional[SearchBudget] = None) -> Dict[str, Any]:
    """Analyze a list-append history; returns an elle-shaped result map.

    ``consistency_models`` selects what ``valid`` means (append.clj:15-21
    parity): all anomalies found are always reported, but only those that
    refute a *requested* model make the history invalid — e.g. a G2-item
    write-skew cycle refutes ``("serializable",)`` (the default) yet passes
    ``("snapshot-isolation",)``.  The result carries elle's weakest-model
    boundary under ``not`` / ``also-not``.  Default: serializable, or
    strict-serializable when ``realtime`` ordering is requested.
    ``search_budget`` bounds cycle recovery (see :class:`SearchBudget`)."""
    if consistency_models is None:
        consistency_models = (("strict-serializable",) if realtime
                              else ("serializable",))
    a = analyze(history)
    if realtime:
        add_realtime_edges(a.graph, a.oks, a.pairs)
    truncated = collect_cycle_anomalies(a.graph, a.txn_of, a.anomalies,
                                        budget=search_budget)
    res = finish_result(a.anomalies, consistency_models, a.count,
                        truncated=truncated)
    # complete edge list for artifact rendering; popped by
    # elle.render.write_artifacts alongside anomalies-full
    res["edges-full"] = edge_list(a.graph)
    return res


def analyze(history: History) -> Analysis:
    """The linear host pass: indices, version orders, host anomalies, and
    the ww/wr/rw dependency graph — everything but cycle search and the
    realtime layer."""
    # Client ops only: a nemesis op's value (e.g. the killed node list)
    # is not a txn, and elle likewise analyzes the client subhistory
    # (elle's history preparation removes non-txn ops).
    history = history.client_ops()
    oks: List[Tuple[int, Op]] = []
    failed_writes: Set[Tuple[Any, Any]] = set()
    info_writes: Set[Tuple[Any, Any]] = set()
    pairs = history.pair_index()

    for i, op in enumerate(history):
        if not isinstance(op.value, (list, tuple)):
            continue
        if op.type == OK:
            oks.append((i, op))
        elif op.type in (FAIL, INFO):
            j = pairs[i]
            txn = op.value if op.value else (
                history[j].value if j >= 0 else None)
            if txn:
                for f, k, v in txn:
                    if f == "append":
                        (failed_writes if op.type == FAIL
                         else info_writes).add((k, v))

    anomalies: Dict[str, List[Any]] = defaultdict(list)

    # writer index + duplicate detection
    writer: Dict[Tuple[Any, Any], int] = {}
    txn_of: Dict[int, List] = {}
    for tid, (_, op) in enumerate(oks):
        txn_of[tid] = op.value
        for f, k, v in op.value:
            if f == "append":
                if (k, v) in writer:
                    anomalies["duplicate-appends"].append(
                        {"key": k, "value": v})
                writer[(k, v)] = tid

    # per-key longest read + prefix consistency + G1a/G1b
    longest: Dict[Any, List[Any]] = {}
    for tid, (_, op) in enumerate(oks):
        for f, k, v in op.value:
            if f not in ("r", "read") or v is None:
                continue
            lst = list(v)
            # G1a: observed value appended by a failed txn
            for x in lst:
                if (k, x) in failed_writes:
                    anomalies["G1a"].append({"key": k, "value": x,
                                             "reader": op.to_dict()})
            cur = longest.get(k, [])
            short, long_ = (lst, cur) if len(lst) <= len(cur) else (cur, lst)
            if short != long_[:len(short)]:
                anomalies["incompatible-order"].append(
                    {"key": k, "a": cur, "b": lst})
            if len(lst) > len(cur):
                longest[k] = lst

    # G1b: a read that ends inside another txn's append run
    # (observes some but not all of a txn's appends to k, with nothing after)
    appends_by_txn_key: Dict[Tuple[int, Any], List[Any]] = defaultdict(list)
    for tid, (_, op) in enumerate(oks):
        for f, k, v in op.value:
            if f == "append":
                appends_by_txn_key[(tid, k)].append(v)
    for rtid, (_, op) in enumerate(oks):
        for f, k, v in op.value:
            if f not in ("r", "read") or not v:
                continue
            last = v[-1]
            wtid = writer.get((k, last))
            if wtid is None or wtid == rtid:
                continue
            run = appends_by_txn_key[(wtid, k)]
            if run and last != run[-1]:
                anomalies["G1b"].append({"key": k, "value": last,
                                         "reader": op.to_dict()})

    # dependency graph
    g = Graph()
    for tid in range(len(oks)):
        g.add_node(tid)

    # Values appended but never observed by any read still have a sound
    # place in the (append-only) version order: had such an append preceded
    # the state some read observed, the value would appear in that read, so
    # every unobserved append follows the longest observed list — giving ww
    # edges from the last observed writer and rw edges from every reader
    # (this is what makes pure write skew — two reads of [] and two blind
    # appends — a detectable G2-item cycle).
    by_key: Dict[Any, List[Any]] = defaultdict(list)
    for (k, v) in writer:
        by_key[k].append(v)
    unobserved: Dict[Any, List[Any]] = {}
    for k, vs in by_key.items():
        obs = set(longest.get(k, []))
        unobserved[k] = [v for v in vs if v not in obs]

    for k, order in longest.items():
        # ww edges along the version order
        for a, b in zip(order, order[1:]):
            wa, wb = writer.get((k, a)), writer.get((k, b))
            if wa is not None and wb is not None and wa != wb:
                g.add_edge(wa, wb, "ww")
        if order:
            wa = writer.get((k, order[-1]))
            for v in unobserved.get(k, ()):
                wb = writer.get((k, v))
                if wa is not None and wb is not None and wa != wb:
                    g.add_edge(wa, wb, "ww")

    for rtid, (_, op) in enumerate(oks):
        for f, k, v in op.value:
            if f not in ("r", "read") or v is None:
                continue
            lst = list(v)
            if lst:
                w = writer.get((k, lst[-1]))
                if w is not None and w != rtid:
                    g.add_edge(w, rtid, "wr")
            # rw: the next value after the observed state
            order = longest.get(k, [])
            nxt = order[len(lst)] if len(lst) < len(order) and \
                order[:len(lst)] == lst else None
            if nxt is not None:
                w = writer.get((k, nxt))
                if w is not None and w != rtid:
                    g.add_edge(rtid, w, "rw")
            # rw: every unobserved append to k follows any observed state
            observed = set(lst)
            for v in unobserved.get(k, ()):
                if v in observed:
                    continue
                w = writer.get((k, v))
                if w is not None and w != rtid:
                    g.add_edge(rtid, w, "rw")

    return Analysis(graph=g, txn_of=txn_of, anomalies=anomalies,
                    oks=oks, pairs=pairs)


def finish_result(anomalies: Dict[str, List[Any]],
                  consistency_models: Sequence[str],
                  count: int, truncated: bool = False) -> Dict[str, Any]:
    """Shared result assembly: model-relative validity + boundary report.

    ``truncated`` (cycle search hit its :class:`SearchBudget`) degrades a
    *clean* verdict to unknown — an exhausted search may simply not have
    reached the refuting cycle — while found anomalies still refute.  The
    marker rides as its own ``cycle-search-truncated`` key, never as an
    anomaly type: consistency.refuted_models treats unknown anomaly types
    as refuting everything, which would turn "gave up" into "invalid"."""
    valid = consistency.judge(consistency_models, anomalies)
    if truncated and valid is True:
        valid = UNKNOWN
    res = {"valid": valid,
           "consistency-models": [consistency.canonicalize(m)
                                  for m in consistency_models],
           **consistency.boundary(anomalies),
           "anomaly-types": sorted(anomalies),
           "anomalies": {k: v[:8] for k, v in anomalies.items()},
           # complete map for artifact rendering; popped by
           # elle.render.write_artifacts so results stay small
           "anomalies-full": dict(anomalies),
           "count": count}
    if truncated:
        res["cycle-search-truncated"] = True
    return res
