"""Transactional-anomaly detection — the Elle-equivalent analysis engines.

The reference delegates to the external elle library
(jepsen/src/jepsen/tests/cycle.clj, cycle/append.clj, cycle/wr.clj); this
package provides the same capability natively: dependency-graph construction
from transactional histories, strongly-connected-component cycle search, and
anomaly classification (G0, G1a/b/c, G-single, G2-item) for the list-append
and rw-register workload languages.
"""

from jepsen_tpu.elle.graph import Graph, find_cycle, sccs  # noqa: F401
