"""Consistency-model lattice: which anomalies refute which models.

Parity: elle's ``elle.consistency-model`` as the reference consumes it —
``jepsen/src/jepsen/tests/cycle/append.clj:15-21`` forwards a
``:consistency-models`` option and elle judges validity *relative to those
models*, reporting the weakest models the found anomalies rule out
(``:not`` / ``:also-not``).  The model names and anomaly semantics follow
Adya's portable isolation levels (PL-1 .. PL-3) plus the snapshot-isolation
branch:

- **G0** (write cycle) refutes everything, PL-1 up.
- **G1a/b/c** (aborted read / intermediate read / cyclic information flow)
  refute read-committed (PL-2) up.
- **G-single** (exactly one anti-dependency edge in the cycle) refutes
  consistent-view (PL-2+) and everything above it — including both
  snapshot-isolation and repeatable-read.
- **G-nonadjacent** (>= 2 anti-dependency edges, no two adjacent around the
  cycle) refutes snapshot-isolation: by Fekete's characterization every
  cycle an SI execution admits carries two *consecutive* rw edges, so a
  cycle without such a pair is un-SI-able.  It is also an item-level rw
  cycle, so it refutes repeatable-read.
- **G2-item** (>= 2 rw edges, some adjacent) refutes repeatable-read
  (PL-2.99) and serializability — but NOT snapshot isolation: SI admits
  exactly this shape (write-skew).
- **lost-update** refutes cursor-stability and (via the lattice) SI.
- ``*-realtime`` cycle variants (closable only through a realtime edge)
  refute strict serializability alone.

``boundary`` turns a set of found anomalies into elle's friendly
``{"not", "also-not"}`` report: the weakest refuted models, then every
stronger model they drag down.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Sequence, Set

#: weaker -> directly-stronger edges of the model lattice.
STRONGER: Dict[str, Set[str]] = {
    "read-uncommitted": {"read-committed"},
    "read-committed": {"monotonic-atomic-view", "cursor-stability"},
    "monotonic-atomic-view": {"consistent-view"},
    "cursor-stability": {"repeatable-read", "snapshot-isolation"},
    "consistent-view": {"repeatable-read", "snapshot-isolation"},
    "repeatable-read": {"serializable"},
    "snapshot-isolation": {"serializable"},
    "serializable": {"strict-serializable"},
    "strict-serializable": set(),
}

CANONICAL = sorted(STRONGER)

ALIASES = {
    "ru": "read-uncommitted", "pl-1": "read-uncommitted",
    "rc": "read-committed", "pl-2": "read-committed",
    "mav": "monotonic-atomic-view",
    "pl-2+": "consistent-view",
    "pl-cs": "cursor-stability",
    "rr": "repeatable-read", "pl-2.99": "repeatable-read",
    "si": "snapshot-isolation",
    "ser": "serializable", "serializability": "serializable",
    "pl-3": "serializable", "1sr": "serializable",
    "strict-1sr": "strict-serializable", "pl-ss": "strict-serializable",
    "strong-serializable": "strict-serializable",
    "linearizable": "strict-serializable",
}

#: anomaly type -> the weakest model(s) it directly refutes.  Stronger
#: models fall via the lattice (``implied``).
ANOMALY_REFUTES: Dict[str, Set[str]] = {
    "G0": {"read-uncommitted"},
    "duplicate-appends": {"read-uncommitted"},
    "duplicate-writes": {"read-uncommitted"},
    "cyclic-versions": {"read-uncommitted"},
    "G1a": {"read-committed"},
    "G1b": {"read-committed"},
    "G1c": {"read-committed"},
    "incompatible-order": {"read-committed"},
    "G-single": {"consistent-view"},
    "lost-update": {"cursor-stability"},
    "G-nonadjacent": {"snapshot-isolation", "repeatable-read"},
    "G2-item": {"repeatable-read"},
    "G2": {"serializable"},
    # cycles that need a realtime edge to close refute only the strict tier
    "G0-realtime": {"strict-serializable"},
    "G1c-realtime": {"strict-serializable"},
    "G-single-realtime": {"strict-serializable"},
    "G-nonadjacent-realtime": {"strict-serializable"},
    "G2-item-realtime": {"strict-serializable"},
}


def canonicalize(model: str) -> str:
    m = model.strip().lower()
    m = ALIASES.get(m, m)
    if m not in STRONGER:
        raise ValueError(f"unknown consistency model {model!r}; "
                         f"known: {CANONICAL}")
    return m


def implied(models: Iterable[str]) -> Set[str]:
    """Upward closure: every model at least as strong as one of ``models``
    (a violation of a weak model refutes all stronger ones)."""
    out: Set[str] = set()
    stack = [canonicalize(m) for m in models]
    while stack:
        m = stack.pop()
        if m not in out:
            out.add(m)
            stack.extend(STRONGER[m])
    return out


def refuted_models(anomaly_types: Iterable[str]) -> Set[str]:
    """All models (closure) the given anomaly types rule out.  Unknown
    anomaly types (workload-specific internal checks) refute everything —
    conservative, like elle treating unclassified anomalies as fatal."""
    direct: Set[str] = set()
    for a in anomaly_types:
        direct |= ANOMALY_REFUTES.get(a, {"read-uncommitted"})
    return implied(direct) if direct else set()


def boundary(anomaly_types: Iterable[str]) -> Dict[str, List[str]]:
    """Elle's friendly boundary: ``not`` = the weakest refuted models (no
    refuted model weaker than them), ``also-not`` = the rest of the refuted
    closure."""
    refuted = refuted_models(anomaly_types)
    not_ = {m for m in refuted
            if not any(m in implied([o]) for o in refuted if o != m)}
    return {"not": sorted(not_), "also-not": sorted(refuted - not_)}


def judge(consistency_models: Sequence[str],
          anomaly_types: Iterable[str]) -> bool:
    """True iff none of the requested models is refuted by the anomalies."""
    wanted = {canonicalize(m) for m in consistency_models}
    return not (wanted & refuted_models(anomaly_types))
