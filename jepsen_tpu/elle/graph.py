"""Dependency graphs, SCCs, and cycle extraction.

The backbone of the anomaly checkers: nodes are transaction ids, labelled
edges carry dependency types (ww/wr/rw/realtime/process).  Tarjan SCC
(iterative — histories are long) plus shortest-cycle recovery inside an SCC.
"""

from __future__ import annotations

import time
from collections import defaultdict, deque
from typing import Any, Dict, FrozenSet, Iterable, List, Optional, Set, Tuple


class SearchBudget:
    """Work/time guard for cycle recovery.

    Witness recovery is best-effort by nature (the verdict-deciding pass is
    the closure / SCC scan); on a huge SCC the peel-and-research loop in
    :func:`peeled_cycles` is O(cycles * E) and the per-start BFS of
    :func:`find_cycle` is O(|C| * E) — enough to wedge the budgeted checker
    path (checker/core.py check_safe) on a pathological history.  The budget
    caps both a step counter (coarse-grained: nodes touched per peel / BFS
    expansions) and, optionally, a wall-clock deadline; exhaustion flips
    ``truncated`` and the searches stop yielding.  Callers surface the flag
    as ``cycle-search-truncated`` so a truncated pass can never silently
    certify a history (finish_result degrades a clean verdict to unknown).
    """

    #: default step ceiling — generous (a 10k-txn history's full suite
    #: spends well under 10% of this) but finite, so the CPU fallback path
    #: is bounded even when no explicit budget was configured.
    DEFAULT_MAX_STEPS = 20_000_000
    #: SCCs beyond this many nodes are reported truncated, not searched.
    DEFAULT_MAX_SCC_NODES = 200_000
    #: cap on shortest-cycle BFS starts inside one component (the search
    #: stays correct — any cycle is a witness — just not globally shortest).
    DEFAULT_MAX_CYCLE_STARTS = 2_000

    def __init__(self, deadline_s: Optional[float] = None,
                 max_steps: int = DEFAULT_MAX_STEPS,
                 max_scc_nodes: int = DEFAULT_MAX_SCC_NODES,
                 max_cycle_starts: int = DEFAULT_MAX_CYCLE_STARTS):
        self.deadline = (time.monotonic() + deadline_s
                         if deadline_s is not None else None)
        self.steps = max_steps
        self.max_scc_nodes = max_scc_nodes
        self.max_cycle_starts = max_cycle_starts
        self.truncated = False

    def admit_scc(self, n_nodes: int) -> bool:
        if n_nodes > self.max_scc_nodes:
            self.truncated = True
            return False
        return self.spend(0)

    def spend(self, n: int = 1) -> bool:
        """Charge ``n`` work units; False (and truncated) once exhausted."""
        if self.truncated:
            return False
        self.steps -= n
        if self.steps < 0 or (self.deadline is not None
                              and time.monotonic() > self.deadline):
            self.truncated = True
            return False
        return True


class Graph:
    def __init__(self):
        self.out: Dict[Any, Dict[Any, Set[str]]] = defaultdict(dict)
        self.nodes: Set[Any] = set()

    def add_node(self, n) -> None:
        self.nodes.add(n)

    def add_edge(self, a, b, kind: str) -> None:
        if a == b:
            return
        self.nodes.add(a)
        self.nodes.add(b)
        self.out[a].setdefault(b, set()).add(kind)

    def succs(self, n) -> Iterable[Any]:
        return self.out.get(n, {})

    def edge_kinds(self, a, b) -> Set[str]:
        return self.out.get(a, {}).get(b, set())

    def subgraph(self, nodes: Iterable[Any]) -> "Graph":
        """Node-induced subgraph (edge kinds dropped — cycle *search* never
        reads kinds; report kinds from the full graph)."""
        ns = set(nodes)
        g = Graph()
        g.nodes = ns
        for a in ns:
            for b in self.succs(a):
                if b in ns:
                    g.add_edge(a, b, "")
        return g

    def filter_kinds(self, kinds: Iterable[str]) -> "Graph":
        ks = set(kinds)
        g = Graph()
        g.nodes = set(self.nodes)
        for a, bs in self.out.items():
            for b, ek in bs.items():
                inter = ek & ks
                if inter:
                    for k in inter:
                        g.add_edge(a, b, k)
        return g

    def __len__(self):
        return len(self.nodes)


def peeled_cycles(g: Graph, budget: Optional[SearchBudget] = None):
    """Yield node-disjoint cycles across the whole graph.

    ``find_cycle`` recovers one (shortest) cycle per SCC, but one SCC can
    merge several distinct anomalies (e.g. a ww 2-cycle bridged to a wr
    cycle).  After yielding a cycle, its nodes are peeled off and the
    remainder re-searched, so every node-disjoint cycle in a component is
    reported (the coverage elle's checkers get from per-SCC re-search).

    ``budget`` (:class:`SearchBudget`) bounds the peel loop: each iteration
    re-runs Tarjan over the remainder, so an adversarial SCC could cost
    O(cycles * E) — past the budget the generator just stops (the caller
    reads ``budget.truncated``)."""
    for comp in sccs(g):
        if budget is not None and not budget.admit_scc(len(comp)):
            continue
        remaining = set(comp)
        while len(remaining) >= 2:
            if budget is not None and not budget.spend(len(remaining)):
                return
            sub = g.subgraph(remaining)
            cyc = None
            for c in sccs(sub):
                if len(c) >= 2:
                    cyc = find_cycle(sub, c, budget)
                    if cyc:
                        break
            if not cyc:
                break
            remaining -= set(cyc)
            yield cyc


def sccs(g: Graph) -> List[List[Any]]:
    """Iterative Tarjan; returns nontrivial SCCs (size >= 2)."""
    index: Dict[Any, int] = {}
    low: Dict[Any, int] = {}
    on_stack: Set[Any] = set()
    stack: List[Any] = []
    out: List[List[Any]] = []
    counter = [0]

    for root in g.nodes:
        if root in index:
            continue
        work = [(root, iter(g.succs(root)))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(g.succs(w))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1:
                    out.append(comp)
    return out


def find_cycle(g: Graph, component: List[Any],
               budget: Optional[SearchBudget] = None) -> Optional[List[Any]]:
    """A shortest cycle within an SCC: BFS from each node back to itself
    (bounded — component members only).  With a ``budget``, the number of
    BFS starts is capped (any recovered cycle is a valid witness; only
    global shortestness is sacrificed) and each start charges the
    component size."""
    comp = set(component)
    best: Optional[List[Any]] = None
    starts = component if budget is None \
        else component[:budget.max_cycle_starts]
    for start in starts:
        if budget is not None and not budget.spend(len(comp)):
            break
        # BFS over comp
        prev: Dict[Any, Any] = {start: None}
        q = deque([start])
        found = None
        while q and found is None:
            n = q.popleft()
            for m in g.succs(n):
                if m == start:
                    found = n
                    break
                if m in comp and m not in prev:
                    prev[m] = n
                    q.append(m)
        if found is not None:
            path = [found]
            while prev[path[-1]] is not None:
                path.append(prev[path[-1]])
            path.reverse()
            path.append(start)  # close: start -> ... -> found -> start
            cyc = [start] + path if path[0] != start else path
            # normalize: cycle as [n0, n1, ..., n0]
            if best is None or len(cyc) < len(best):
                best = cyc
        if best is not None and len(best) == 2:
            break
    return best


def cycle_edge_kinds(g: Graph, cycle: List[Any]) -> List[Set[str]]:
    return [g.edge_kinds(a, b) for a, b in zip(cycle, cycle[1:])]


def edge_list(g: Graph, cap: int = 100_000) -> List[Tuple[Any, Any, List[str]]]:
    """The graph as a flat, JSON-friendly edge list ``(src, dst, kinds)``
    for artifact export.  Capped: a dense realtime layer is O(N^2) edges
    and the artifact is a debugging aid, not the verdict."""
    out: List[Tuple[Any, Any, List[str]]] = []
    for a, bs in g.out.items():
        for b, ks in bs.items():
            out.append((a, b, sorted(ks)))
            if len(out) >= cap:
                return out
    return out


def gsingle_cycles(g: Graph, cap: int = 64,
                   budget: Optional[SearchBudget] = None):
    """Cycles with exactly one anti-dependency (rw) edge: for each rw edge
    a->b, a shortest return path b ->* a through edges that each offer a
    non-rw kind.  This is the targeted G-single search (elle runs one per
    anomaly type) — the generic shortest-cycle pass can surface a different,
    SI-legal cycle from the same SCC and miss these."""
    out = []
    for a in list(g.out):
        for b, ks in g.out[a].items():
            if "rw" not in ks:
                continue
            if budget is not None and not budget.spend(len(g)):
                return out
            path = _bfs_path(g, b, a, lambda kinds: bool(kinds - {"rw"}))
            if path is not None:
                out.append([a] + path)
                if len(out) >= cap:
                    return out
    return out


def nonadjacent_rw_cycles(g: Graph, cap: int = 64,
                          budget: int = 20000,
                          search_budget: Optional[SearchBudget] = None):
    """Cycles with >= 2 rw edges and no two adjacent around the cycle —
    the shape snapshot isolation cannot admit (every cycle in an SI
    execution carries two *consecutive* anti-dependency edges; Fekete).

    For each rw edge a->b, DFS over (node, last-edge-was-rw,
    used-a-second-rw) from (b, True, False) to an arrival at ``a`` with a
    non-rw last edge and a second (necessarily nonadjacent) rw on the
    path.  The search tracks per-path visited NODES, so every emitted
    witness is a simple cycle — a state-keyed BFS could revisit a node
    under a different flag state and file a closed *walk* as the anomaly
    (the verdict stayed sound, but the witness edges in the artifact could
    be wrong).  ``budget`` caps expansions per rw edge (simple-path search
    is worst-case exponential); on exhaustion the edge just yields no
    witness — other searches still guard the verdict."""
    out = []
    for a in list(g.out):
        for b, ks in g.out[a].items():
            if "rw" not in ks:
                continue
            if search_budget is not None and not search_budget.spend(0):
                return out
            path = _simple_nonadjacent_path(g, a, b, budget,
                                            search_budget)
            if path is None:
                continue
            out.append([a] + path)
            if len(out) >= cap:
                return out
    return out


def _simple_nonadjacent_path(
        g: Graph, a, b, budget: int,
        search_budget: Optional[SearchBudget] = None) -> Optional[List[Any]]:
    """Simple path [b, ..., a] whose first hop is non-rw-preceded (the
    caller's a->b edge was rw), containing >= 1 further rw edge, no two
    rw edges adjacent, and a non-rw arrival at ``a``."""
    stack = [(b, True, False, (b,))]
    seen_budget = budget
    while stack:
        n, last_rw, extra, path = stack.pop()
        seen_budget -= 1
        if seen_budget <= 0:
            return None
        if search_budget is not None and not search_budget.spend():
            return None
        on_path = set(path)
        for m, mks in g.out.get(n, {}).items():
            steps = []
            if mks - {"rw"}:
                steps.append((m, False, extra))
            if "rw" in mks and not last_rw:
                steps.append((m, True, True))
            for mm, lr, ex in steps:
                if mm == a:
                    if not lr and ex:
                        return list(path) + [a]
                    continue
                if mm in on_path:
                    continue
                stack.append((mm, lr, ex, path + (mm,)))
    return None


def _bfs_path(g: Graph, src, dst, edge_ok) -> Optional[List[Any]]:
    """Shortest path src ->* dst using edges where ``edge_ok(kinds)``;
    returns [src, ..., dst] (src == dst gives a self-returning path only via
    an actual cycle, never the empty path)."""
    prev: Dict[Any, Any] = {src: None}
    q = deque([src])
    while q:
        n = q.popleft()
        for m, ks in g.out.get(n, {}).items():
            if not edge_ok(ks):
                continue
            if m == dst:
                path = [m, n]
                while prev[path[-1]] is not None:
                    path.append(prev[path[-1]])
                path.reverse()
                return path
            if m not in prev:
                prev[m] = n
                q.append(m)
    return None
