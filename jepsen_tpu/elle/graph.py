"""Dependency graphs, SCCs, and cycle extraction.

The backbone of the anomaly checkers: nodes are transaction ids, labelled
edges carry dependency types (ww/wr/rw/realtime/process).  Tarjan SCC
(iterative — histories are long) plus shortest-cycle recovery inside an SCC.
"""

from __future__ import annotations

from collections import defaultdict, deque
from typing import Any, Dict, FrozenSet, Iterable, List, Optional, Set, Tuple


class Graph:
    def __init__(self):
        self.out: Dict[Any, Dict[Any, Set[str]]] = defaultdict(dict)
        self.nodes: Set[Any] = set()

    def add_node(self, n) -> None:
        self.nodes.add(n)

    def add_edge(self, a, b, kind: str) -> None:
        if a == b:
            return
        self.nodes.add(a)
        self.nodes.add(b)
        self.out[a].setdefault(b, set()).add(kind)

    def succs(self, n) -> Iterable[Any]:
        return self.out.get(n, {})

    def edge_kinds(self, a, b) -> Set[str]:
        return self.out.get(a, {}).get(b, set())

    def subgraph(self, nodes: Iterable[Any]) -> "Graph":
        """Node-induced subgraph (edge kinds dropped — cycle *search* never
        reads kinds; report kinds from the full graph)."""
        ns = set(nodes)
        g = Graph()
        g.nodes = ns
        for a in ns:
            for b in self.succs(a):
                if b in ns:
                    g.add_edge(a, b, "")
        return g

    def filter_kinds(self, kinds: Iterable[str]) -> "Graph":
        ks = set(kinds)
        g = Graph()
        g.nodes = set(self.nodes)
        for a, bs in self.out.items():
            for b, ek in bs.items():
                inter = ek & ks
                if inter:
                    for k in inter:
                        g.add_edge(a, b, k)
        return g

    def __len__(self):
        return len(self.nodes)


def peeled_cycles(g: Graph):
    """Yield node-disjoint cycles across the whole graph.

    ``find_cycle`` recovers one (shortest) cycle per SCC, but one SCC can
    merge several distinct anomalies (e.g. a ww 2-cycle bridged to a wr
    cycle).  After yielding a cycle, its nodes are peeled off and the
    remainder re-searched, so every node-disjoint cycle in a component is
    reported (the coverage elle's checkers get from per-SCC re-search)."""
    for comp in sccs(g):
        remaining = set(comp)
        while len(remaining) >= 2:
            sub = g.subgraph(remaining)
            cyc = None
            for c in sccs(sub):
                if len(c) >= 2:
                    cyc = find_cycle(sub, c)
                    if cyc:
                        break
            if not cyc:
                break
            remaining -= set(cyc)
            yield cyc


def sccs(g: Graph) -> List[List[Any]]:
    """Iterative Tarjan; returns nontrivial SCCs (size >= 2)."""
    index: Dict[Any, int] = {}
    low: Dict[Any, int] = {}
    on_stack: Set[Any] = set()
    stack: List[Any] = []
    out: List[List[Any]] = []
    counter = [0]

    for root in g.nodes:
        if root in index:
            continue
        work = [(root, iter(g.succs(root)))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(g.succs(w))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1:
                    out.append(comp)
    return out


def find_cycle(g: Graph, component: List[Any]) -> Optional[List[Any]]:
    """A shortest cycle within an SCC: BFS from each node back to itself
    (bounded — component members only)."""
    comp = set(component)
    best: Optional[List[Any]] = None
    for start in component:
        # BFS over comp
        prev: Dict[Any, Any] = {start: None}
        q = deque([start])
        found = None
        while q and found is None:
            n = q.popleft()
            for m in g.succs(n):
                if m == start:
                    found = n
                    break
                if m in comp and m not in prev:
                    prev[m] = n
                    q.append(m)
        if found is not None:
            path = [found]
            while prev[path[-1]] is not None:
                path.append(prev[path[-1]])
            path.reverse()
            path.append(start)  # close: start -> ... -> found -> start
            cyc = [start] + path if path[0] != start else path
            # normalize: cycle as [n0, n1, ..., n0]
            if best is None or len(cyc) < len(best):
                best = cyc
        if best is not None and len(best) == 2:
            break
    return best


def cycle_edge_kinds(g: Graph, cycle: List[Any]) -> List[Set[str]]:
    return [g.edge_kinds(a, b) for a, b in zip(cycle, cycle[1:])]
