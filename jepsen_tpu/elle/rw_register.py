"""Read/write-register anomaly inference.

Parity: elle.rw-register as consumed by the reference
(jepsen/src/jepsen/tests/cycle/wr.clj:9-25).  Transactions carry
``["w", k, v]`` (v unique per key) and ``["r", k, v]`` mops.  Unlike
list-append, reads don't trace version history, so a per-key *version
order* must be recovered first, from several sources (each an explicit
"must precede" constraint on versions of one key):

- ``initial``  — the initial state ``None`` precedes every written value;
- ``wfr``      — a txn that read v and then wrote v' orders v < v';
- ``ww-txn``   — a txn that wrote v then v' to the same key orders v < v'
  (v is then an *intermediate* version: reads of it by others are G1b);
- ``sequential`` (opt-in ``sequential_keys``) — consecutive writes to a key
  by one process order their values (per-key sequential consistency
  assumption, elle's :sequential-keys?);
- ``linearizable`` (opt-in ``linearizable_keys``) — a write completed
  before another write's invocation orders their values (per-key
  linearizability assumption, elle's :linearizable-keys?).

A cycle in a key's version graph is itself reported (``cyclic-versions``).
The transaction dependency graph then gets:

- wr edges (exact): the unique writer of an observed value → the reader;
- ww edges: writer of v → writer of v' for each version edge v < v';
- rw edges: reader of v → writer of v' for each version edge v < v'
  (sound for serialization cycles: a reader of v must precede the
  installer of any later version);
- realtime edges in strict mode.

Plus G1a (reads of failed writes), G1b (reads of intermediate writes) and
duplicate-write detection.
"""

from __future__ import annotations

import bisect
from collections import defaultdict
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from jepsen_tpu.elle.graph import Graph, SearchBudget, edge_list
from jepsen_tpu.elle.list_append import (Analysis, add_realtime_edges,
                                         collect_cycle_anomalies,
                                         finish_result)
from jepsen_tpu.history import FAIL, History, INFO, OK, Op
from jepsen_tpu.txn import READ_FS, WRITE_FS


def check(history: History, realtime: bool = False,
          consistency_models: Optional[Sequence[str]] = None,
          sequential_keys: bool = False,
          linearizable_keys: bool = False,
          search_budget: Optional[SearchBudget] = None) -> Dict[str, Any]:
    """Analyze an rw-register history; ``consistency_models`` selects what
    ``valid`` means (wr.clj:9-25 consumes elle the same way) — see
    :func:`jepsen_tpu.elle.list_append.check`."""
    if consistency_models is None:
        consistency_models = (("strict-serializable",) if realtime
                              else ("serializable",))
    a = analyze(history, sequential_keys=sequential_keys,
                linearizable_keys=linearizable_keys)
    if realtime:
        add_realtime_edges(a.graph, a.oks, a.pairs)
    truncated = collect_cycle_anomalies(a.graph, a.txn_of, a.anomalies,
                                        budget=search_budget)
    res = finish_result(a.anomalies, consistency_models, a.count,
                        truncated=truncated)
    res["edges-full"] = edge_list(a.graph)
    return res


def analyze(history: History, sequential_keys: bool = False,
            linearizable_keys: bool = False) -> Analysis:
    """The linear host pass: version-graph recovery, host anomalies, and
    the ww/wr/rw dependency graph — everything but cycle search and the
    realtime layer (see :class:`jepsen_tpu.elle.list_append.Analysis`)."""
    # Client ops only (see list_append.check: nemesis values are not txns).
    history = history.client_ops()
    pairs = history.pair_index()
    oks: List[Tuple[int, Op]] = []
    failed_writes: Set[Tuple[Any, Any]] = set()
    for i, op in enumerate(history):
        if not isinstance(op.value, (list, tuple)):
            continue
        if op.type == OK:
            oks.append((i, op))
        elif op.type == FAIL:
            j = pairs[i]
            txn = op.value or (history[j].value if j >= 0 else None)
            if txn:
                for f, k, v in txn:
                    if f in WRITE_FS:
                        failed_writes.add((k, v))

    anomalies: Dict[str, List[Any]] = defaultdict(list)
    writer: Dict[Tuple[Any, Any], int] = {}
    txn_of: Dict[int, List] = {}
    # intermediate versions: (k, v) overwritten within its own txn (G1b bait)
    intermediate: Dict[Tuple[Any, Any], int] = {}
    for tid, (_, op) in enumerate(oks):
        txn_of[tid] = op.value
        last_w: Dict[Any, Any] = {}
        for f, k, v in op.value:
            if f in WRITE_FS:
                if (k, v) in writer:
                    anomalies["duplicate-writes"].append({"key": k,
                                                          "value": v})
                writer[(k, v)] = tid
                if k in last_w:
                    intermediate[(k, last_w[k])] = tid
                last_w[k] = v

    # ----- per-key version graphs -----------------------------------------
    # vg[k] : value -> set of successor values (direct "precedes" edges)
    vg: Dict[Any, Dict[Any, Set[Any]]] = defaultdict(lambda: defaultdict(set))
    written_values: Dict[Any, Set[Any]] = defaultdict(set)
    for (k, v) in writer:
        written_values[k].add(v)

    for tid, (_, op) in enumerate(oks):
        reads: Dict[Any, Any] = {}
        last_w: Dict[Any, Any] = {}
        for f, k, v in op.value:
            if f in READ_FS:
                reads[k] = v
            elif f in WRITE_FS:
                if k in last_w:            # ww-txn source
                    vg[k][last_w[k]].add(v)
                elif k in reads:           # wfr source
                    if reads[k] != v:
                        vg[k][reads[k]].add(v)
                last_w[k] = v

    for k, vs in written_values.items():   # initial source
        for v in vs:
            if v is not None:              # a written None is not the initial
                vg[k][None].add(v)         # version; avoid a None self-loop

    if sequential_keys or linearizable_keys:
        _order_writes(oks, pairs, vg, sequential_keys, linearizable_keys)

    for k, adj in vg.items():
        cyc = _version_cycle(adj)
        if cyc:
            anomalies["cyclic-versions"].append({"key": k, "versions": cyc})

    # ----- transaction dependency graph -----------------------------------
    g = Graph()
    for tid in range(len(oks)):
        g.add_node(tid)

    # readers[(k, v)] -> tids that externally observed v for k
    readers: Dict[Tuple[Any, Any], List[int]] = defaultdict(list)
    for tid, (_, op) in enumerate(oks):
        seen_w: Set[Any] = set()
        for f, k, v in op.value:
            if f in READ_FS and k not in seen_w:
                readers[(k, v)].append(tid)
                if (k, v) in failed_writes:
                    anomalies["G1a"].append({"key": k, "value": v,
                                             "reader": op.to_dict()})
                iw = intermediate.get((k, v))
                if iw is not None and iw != tid:
                    anomalies["G1b"].append({"key": k, "value": v,
                                             "reader": op.to_dict()})
                if v is not None:
                    w = writer.get((k, v))
                    if w is not None and w != tid:
                        g.add_edge(w, tid, "wr")
            elif f in WRITE_FS:
                seen_w.add(k)

    for k, adj in vg.items():
        for v, nexts in adj.items():
            w1 = writer.get((k, v))
            for v2 in nexts:
                w2 = writer.get((k, v2))
                if w2 is None:
                    continue
                if w1 is not None and w1 != w2:
                    g.add_edge(w1, w2, "ww")
                for r in readers.get((k, v), ()):
                    if r != w2:
                        g.add_edge(r, w2, "rw")

    return Analysis(graph=g, txn_of=txn_of, anomalies=anomalies,
                    oks=oks, pairs=pairs)


def _order_writes(oks, pairs, vg, sequential_keys, linearizable_keys) -> None:
    """Add per-key version edges from per-process (sequential) and realtime
    (linearizable) order of the writing transactions."""
    # (k -> [(invoke_idx, complete_idx, process, last value written)])
    writes: Dict[Any, List[Tuple[int, int, Any, Any]]] = defaultdict(list)
    for tid, (i, op) in enumerate(oks):
        inv = pairs[i] if pairs[i] >= 0 else i
        last_w: Dict[Any, Any] = {}
        for f, k, v in op.value:
            if f in WRITE_FS:
                last_w[k] = v
        for k, v in last_w.items():
            writes[k].append((min(i, inv), max(i, inv), op.process, v))
    for k, ws in writes.items():
        if sequential_keys:
            by_proc: Dict[Any, List] = defaultdict(list)
            for w in ws:
                by_proc[w[2]].append(w)
            for plist in by_proc.values():
                plist.sort(key=lambda w: w[0])
                for a, b in zip(plist, plist[1:]):
                    if a[3] != b[3]:
                        vg[k][a[3]].add(b[3])
        if linearizable_keys:
            # Realtime order is an interval order; emit a sparse edge set
            # whose transitive closure equals it (full all-pairs would be
            # O(n^2) edges): link a only to successors invoked no later
            # than the earliest completion among a's successors — every
            # other pair is implied through that earliest-completing write.
            ws_sorted = sorted(ws, key=lambda w: w[0])
            n = len(ws_sorted)
            # suffix-min of completion index over ws_sorted[i:]
            suf_min = [0] * (n + 1)
            suf_min[n] = float("inf")
            for i in range(n - 1, -1, -1):
                suf_min[i] = min(ws_sorted[i][1], suf_min[i + 1])
            invokes = [w[0] for w in ws_sorted]
            for a in ws_sorted:
                j = bisect.bisect_right(invokes, a[1])
                if j >= n:
                    continue
                cutoff = suf_min[j]
                for b in ws_sorted[j:]:
                    if b[0] > cutoff:
                        break
                    if a[3] != b[3]:
                        vg[k][a[3]].add(b[3])


def _version_cycle(adj: Dict[Any, Set[Any]]) -> Optional[List[Any]]:
    """Iterative DFS cycle detection over one key's version graph
    (version chains can be as long as the history)."""
    WHITE, GREY, BLACK = 0, 1, 2
    color: Dict[Any, int] = defaultdict(int)
    for root in list(adj):
        if color[root] != WHITE:
            continue
        # stack of (node, iterator over successors); path mirrors the greys
        path: List[Any] = []
        stack = [(root, iter(adj.get(root, ())))]
        color[root] = GREY
        path.append(root)
        while stack:
            v, it = stack[-1]
            advanced = False
            for u in it:
                if color[u] == GREY:
                    return path[path.index(u):] + [u]
                if color[u] == WHITE:
                    color[u] = GREY
                    path.append(u)
                    stack.append((u, iter(adj.get(u, ()))))
                    advanced = True
                    break
            if not advanced:
                color[v] = BLACK
                path.pop()
                stack.pop()
    return None
