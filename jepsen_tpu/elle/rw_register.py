"""Read/write-register anomaly inference.

Parity: elle.rw-register as consumed by the reference
(jepsen/src/jepsen/tests/cycle/wr.clj:9-25).  Transactions carry
``["w", k, v]`` (v unique per key) and ``["r", k, v]`` mops.  Unlike
list-append, reads don't trace version history, so the dependency graph is
inferred from:

- wr edges (exact): the unique writer of an observed value → the reader;
- ww edges (partial): per-key version order inferred from each transaction's
  own read-then-write (a txn that read v and wrote v' orders v < v'), plus
  the initial state (nil before any observed value);
- rw edges: reader of v → writer of any v' with v <ww v' immediately after;
- realtime edges in strict mode.

Plus G1a (reads of failed writes) and duplicate-write detection.  Full
Elle-grade version-order recovery (inferred from recoverability and
traceability assumptions) goes deeper; this covers its core and reports
what it can prove.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from jepsen_tpu.elle.graph import Graph, cycle_edge_kinds, find_cycle, sccs
from jepsen_tpu.elle.list_append import classify_cycle
from jepsen_tpu.history import FAIL, History, INFO, OK, Op
from jepsen_tpu.txn import READ_FS, WRITE_FS


def check(history: History, realtime: bool = False) -> Dict[str, Any]:
    pairs = history.pair_index()
    oks: List[Tuple[int, Op]] = []
    failed_writes: Set[Tuple[Any, Any]] = set()
    for i, op in enumerate(history):
        if not isinstance(op.value, (list, tuple)):
            continue
        if op.type == OK:
            oks.append((i, op))
        elif op.type == FAIL:
            j = pairs[i]
            txn = op.value or (history[j].value if j >= 0 else None)
            if txn:
                for f, k, v in txn:
                    if f in WRITE_FS:
                        failed_writes.add((k, v))

    anomalies: Dict[str, List[Any]] = defaultdict(list)
    writer: Dict[Tuple[Any, Any], int] = {}
    txn_of: Dict[int, List] = {}
    for tid, (_, op) in enumerate(oks):
        txn_of[tid] = op.value
        for f, k, v in op.value:
            if f in WRITE_FS:
                if (k, v) in writer:
                    anomalies["duplicate-writes"].append({"key": k,
                                                          "value": v})
                writer[(k, v)] = tid

    g = Graph()
    for tid in range(len(oks)):
        g.add_node(tid)

    # per-key successor order v -> v' from read-then-write within one txn
    succ: Dict[Tuple[Any, Any], Set[Any]] = defaultdict(set)
    for tid, (_, op) in enumerate(oks):
        reads: Dict[Any, Any] = {}
        for f, k, v in op.value:
            if f in READ_FS:
                reads[k] = v
            elif f in WRITE_FS:
                if k in reads:
                    succ[(k, reads[k])].add(v)

    for tid, (_, op) in enumerate(oks):
        for f, k, v in op.value:
            if f in READ_FS:
                if (k, v) in failed_writes:
                    anomalies["G1a"].append({"key": k, "value": v,
                                             "reader": op.to_dict()})
                if v is not None:
                    w = writer.get((k, v))
                    if w is not None and w != tid:
                        g.add_edge(w, tid, "wr")
                # rw: observed v, some txn wrote a direct successor of v
                for v2 in succ.get((k, v), ()):
                    w2 = writer.get((k, v2))
                    if w2 is not None and w2 != tid:
                        g.add_edge(tid, w2, "rw")

    # ww edges from the same successor relation
    for (k, v), nexts in succ.items():
        w1 = writer.get((k, v))
        for v2 in nexts:
            w2 = writer.get((k, v2))
            if w1 is not None and w2 is not None and w1 != w2:
                g.add_edge(w1, w2, "ww")

    if realtime:
        for t1, (i1, _) in enumerate(oks):
            for t2, (i2, _) in enumerate(oks):
                if t1 != t2:
                    inv2 = pairs[i2]
                    if inv2 >= 0 and i1 < inv2:
                        g.add_edge(t1, t2, "realtime")

    for comp in sccs(g):
        cyc = find_cycle(g, comp)
        if not cyc:
            continue
        kinds = cycle_edge_kinds(g, cyc)
        anomalies[classify_cycle(kinds)].append({
            "cycle": [txn_of[t] for t in cyc],
            "edges": [sorted(ks) for ks in kinds]})

    return {"valid": not anomalies,
            "anomaly-types": sorted(anomalies),
            "anomalies": {k: v[:8] for k, v in anomalies.items()},
            "count": len(oks)}
