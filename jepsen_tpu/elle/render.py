"""Anomaly-graph artifacts: an ``elle/`` directory humans can inspect.

Parity: the reference's cycle checkers write an ``elle/`` directory of
anomaly files and graphviz cycle plots into the store dir
(jepsen/src/jepsen/tests/cycle.clj:9-16, cycle/append.clj:15-21 — elle's
``:directory`` option).  Here each cycle anomaly gets:

- ``<type>.txt``     — cycles listed step by step with their edge kinds
                       (elle's explained-cycle text format);
- ``<type>-<i>.svg`` — a self-contained circular-layout digraph (no
                       graphviz dependency; same spirit as checker/render);
- ``anomalies.json`` — the complete untruncated anomaly map;
- ``edges.jsonl``    — the dependency graph as one ``{src, dst, kinds}``
                       object per line (from the checker's ``edges-full``),
                       so a refuted run's graph can be re-searched offline.

All files land via atomic_io.atomic_write: a run killed mid-render must
never leave a torn artifact shadowing a good one (same discipline as the
store's staged saves).  Rendering is best-effort and must never mask a
verdict (the callers wrap it like Linearizable._render does for
linear.svg).
"""

from __future__ import annotations

import json
import math
import os
from typing import Any, Dict, List, Optional

from jepsen_tpu.atomic_io import atomic_write

# anomaly entries carrying these keys are dependency cycles
_CYCLE_KEYS = ("cycle", "edges")

# SVGs rendered per anomaly type.  The text listing and anomalies.json stay
# complete (that's the point of the directory); only the per-cycle plots are
# capped so a pathological run can't spray thousands of files.
MAX_SVGS_PER_TYPE = 64


def write_artifacts(test, res: Dict[str, Any], opts) -> None:
    """On an invalid analysis, write the ``elle/`` anomaly-graph directory
    into the store dir (tests/cycle.clj:9-16 elle :directory parity).

    The artifacts are written from ``res["anomalies-full"]`` when present —
    the whole point of the directory is to preserve what the in-memory
    result truncates — and that key is popped afterwards so results.json
    stays small.  Best-effort: artifact trouble must never mask the
    verdict."""
    full = res.pop("anomalies-full", None)
    edges = res.pop("edges-full", None)
    if res.get("valid") is True or not (full or res.get("anomalies")):
        return
    d = (opts or {}).get("store_dir") or (test or {}).get("store_dir")
    if not d:
        return
    try:
        path = write_anomaly_dir(
            d, {**res, "anomalies": full or res.get("anomalies")},
            edges=edges)
        if path:
            res["anomaly-dir"] = path
    except Exception as e:  # noqa: BLE001
        res["anomaly-dir-error"] = str(e)


def write_anomaly_dir(store_dir: str, analysis: Dict[str, Any],
                      subdir: str = "elle",
                      edges: Optional[List[Any]] = None) -> Optional[str]:
    """Write the ``elle/`` artifact directory for a checker analysis.
    Returns the directory path, or None when there is nothing to write."""
    anomalies = analysis.get("anomalies") or {}
    if not anomalies:
        return None
    d = os.path.join(store_dir, subdir)
    os.makedirs(d, exist_ok=True)
    atomic_write(os.path.join(d, "anomalies.json"),
                 lambda f: json.dump(anomalies, f, indent=2, default=repr))
    if edges:
        atomic_write(os.path.join(d, "edges.jsonl"),
                     lambda f: _dump_edges(f, edges))
    for typ, entries in anomalies.items():
        cycles = [e for e in entries if isinstance(e, dict)
                  and all(k in e for k in _CYCLE_KEYS)]
        if not cycles:
            continue

        def dump_txt(f, typ=typ, cycles=cycles):
            f.write(f"{len(cycles)} {typ} cycle(s)\n\n")
            for i, c in enumerate(cycles):
                f.write(f"--- cycle {i} ---\n")
                f.write(_explain_cycle(c))
                f.write("\n")

        atomic_write(os.path.join(d, f"{typ}.txt"), dump_txt)
        for i, c in enumerate(cycles[:MAX_SVGS_PER_TYPE]):
            svg = cycle_svg(c, title=f"{typ} #{i}")
            atomic_write(os.path.join(d, f"{typ}-{i}.svg"),
                         lambda f, svg=svg: f.write(svg))
    return d


def _dump_edges(f, edges: List[Any]) -> None:
    """One {src, dst, kinds} object per line (txn ids are the checker's
    dense 0..N-1 labels, matching the cycle witnesses' order)."""
    for e in edges:
        src, dst, kinds = e
        f.write(json.dumps({"src": src, "dst": dst,
                            "kinds": list(kinds)}, default=str))
        f.write("\n")


def _node_label(n: Any, limit: int = 48) -> str:
    if isinstance(n, dict):  # _txn_brief-shaped
        core = n.get("value", n)
        s = f"p{n.get('process', '?')} {core}"
    else:
        s = str(n)
    return s if len(s) <= limit else s[:limit - 1] + "…"


def _explain_cycle(c: Dict[str, Any]) -> str:
    """elle-style step listing: T1 -[ww]-> T2 -[wr]-> ... -> T1."""
    nodes: List[Any] = list(c["cycle"])
    edges: List[Any] = list(c["edges"])
    out = []
    for i, e in enumerate(edges):
        a = _node_label(nodes[i])
        b = _node_label(nodes[(i + 1) % len(nodes)])
        kinds = ",".join(e) if isinstance(e, (list, tuple, set)) else str(e)
        out.append(f"  {a}\n    -[{kinds}]->\n  {b}\n")
    return "".join(out)


def cycle_svg(c: Dict[str, Any], title: str = "cycle",
              size: int = 480) -> str:
    """Self-contained SVG of one dependency cycle, nodes on a circle."""
    nodes: List[Any] = list(c["cycle"])
    # drop a duplicated closing node ([T0, T1, T0] -> [T0, T1])
    if len(nodes) > 1 and nodes[0] == nodes[-1]:
        nodes = nodes[:-1]
    edges: List[Any] = list(c["edges"])
    n = max(1, len(nodes))
    cx = cy = size / 2
    r = size / 2 - 70
    pos = []
    for i in range(n):
        a = 2 * math.pi * i / n - math.pi / 2
        pos.append((cx + r * math.cos(a), cy + r * math.sin(a)))

    def esc(s: str) -> str:
        return (s.replace("&", "&amp;").replace("<", "&lt;")
                .replace(">", "&gt;").replace('"', "&quot;"))

    parts = [
        f'<svg xmlns="http://www.w3.org/2000/svg" width="{size}" '
        f'height="{size}" font-family="monospace" font-size="11">',
        '<defs><marker id="arr" viewBox="0 0 10 10" refX="9" refY="5" '
        'markerWidth="7" markerHeight="7" orient="auto-start-reverse">'
        '<path d="M 0 0 L 10 5 L 0 10 z" fill="#c0392b"/></marker></defs>',
        f'<text x="{cx}" y="18" text-anchor="middle" font-size="14" '
        f'fill="#333">{esc(title)}</text>',
    ]
    box_w, box_h = 120, 28
    for i in range(n):
        x1, y1 = pos[i]
        x2, y2 = pos[(i + 1) % n]
        # retract ends to the node boxes
        dx, dy = x2 - x1, y2 - y1
        L = math.hypot(dx, dy) or 1.0
        pad = box_h * 1.2
        sx, sy = x1 + dx / L * pad, y1 + dy / L * pad
        ex, ey = x2 - dx / L * pad, y2 - dy / L * pad
        kinds = edges[i] if i < len(edges) else []
        kl = ",".join(kinds) if isinstance(kinds, (list, tuple, set)) \
            else str(kinds)
        parts.append(
            f'<line x1="{sx:.1f}" y1="{sy:.1f}" x2="{ex:.1f}" y2="{ey:.1f}" '
            'stroke="#c0392b" stroke-width="1.5" marker-end="url(#arr)"/>')
        mx, my = (sx + ex) / 2, (sy + ey) / 2
        parts.append(f'<text x="{mx:.1f}" y="{my - 4:.1f}" '
                     f'text-anchor="middle" fill="#c0392b">{esc(kl)}</text>')
    for i, (x, y) in enumerate(pos):
        label = _node_label(nodes[i], limit=20)
        parts.append(
            f'<rect x="{x - box_w / 2:.1f}" y="{y - box_h / 2:.1f}" '
            f'width="{box_w}" height="{box_h}" rx="6" fill="#ecf0f1" '
            'stroke="#7f8c8d"/>')
        parts.append(f'<text x="{x:.1f}" y="{y + 4:.1f}" '
                     f'text-anchor="middle" fill="#2c3e50">'
                     f'{esc(label)}</text>')
    parts.append("</svg>")
    return "\n".join(parts)
