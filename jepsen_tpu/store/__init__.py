"""Persistent test store — results on disk, durable in stages.

Parity: jepsen.store (jepsen/src/jepsen/store.clj): every run owns
``store/<test-name>/<timestamp>/`` with ``latest`` symlinks
(store.clj:33-66,350), and durability is staged exactly like the reference's
three-phase save (store.clj:413-457):

  save_0 — the test map, before anything runs;
  save_1 — the history, immediately after the run (pre-analysis): a crashed
           analysis can always be re-run from disk;
  save_2 — the analysis results.

Formats: JSON for the test map and results; the history as JSONL
(line-per-op — append-friendly and streamable, serving the role of the
reference's custom append-only block format) plus an optional packed
struct-of-arrays .npz for zero-parse reload into the device engine.
Per-run logging mirrors store.clj:462-496 (a jepsen.log per run).
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any, Dict, List, Optional

from jepsen_tpu.atomic_io import atomic_path, atomic_write
from jepsen_tpu.history import History, Op

BASE = "store"

_NONSERIALIZABLE = {"client", "nemesis", "generator", "checker", "db", "os",
                    "remote", "sessions", "barrier", "store_dir"}
# (store.clj:94-100 nonserializable-keys)


def test_dir(test: Dict[str, Any], base: Optional[str] = None) -> str:
    name = test.get("name", "noname")
    start = test.get("start_time") or time.strftime("%Y%m%dT%H%M%S")
    return os.path.join(base or test.get("store_base", BASE), name, start)


def make_run_dir(test: Dict[str, Any], base: Optional[str] = None) -> str:
    d = test_dir(test, base)
    # Two runs of one suite in the same wall-clock second (a concurrent
    # campaign sharing a checking service, or a fast test_count loop) must
    # never share a run dir: claim the path atomically, bumping a numeric
    # suffix on collision and keeping start_time in sync with the dir name.
    base_d, i = d, 1
    while True:
        try:
            os.makedirs(d)
            break
        except FileExistsError:
            i += 1
            d = f"{base_d}-{i}"
    if d != base_d:
        test["start_time"] = os.path.basename(d)
    _update_symlink(os.path.join(os.path.dirname(d), "latest"), d)
    _update_symlink(os.path.join(os.path.dirname(os.path.dirname(d)),
                                 "latest"), d)
    test["store_dir"] = d
    return d


def _update_symlink(link: str, target: str) -> None:
    try:
        if os.path.islink(link):
            os.unlink(link)
        os.symlink(os.path.abspath(target), link)
    except OSError:
        pass


def serializable_test(test: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    for k, v in test.items():
        if k in _NONSERIALIZABLE:
            continue
        try:
            json.dumps(v)
            out[k] = v
        except (TypeError, ValueError):
            out[k] = repr(v)
    return out


def save_0(test: Dict[str, Any]) -> str:
    """Phase 0: persist the test map before the run (store.clj:413).
    Atomic (atomic_io): a crash mid-dump can't leave a torn test.json."""
    d = test.get("store_dir") or make_run_dir(test)
    atomic_write(os.path.join(d, "test.json"),
                 lambda f: json.dump(serializable_test(test), f,
                                     indent=2, default=str))
    return d


def save_1(test: Dict[str, Any], history: History) -> None:
    """Phase 1: persist the history right after the run (store.clj:422),
    in both JSONL (greppable) and the CRC32 block format (crash-safe,
    lazily readable — store/format.py)."""
    d = test["store_dir"]
    history.to_jsonl(os.path.join(d, "history.jsonl"))  # atomic internally
    try:
        from jepsen_tpu.store import format as _fmt
        with atomic_path(os.path.join(d, "history.jtsf")) as tmp:
            _fmt.write_history(tmp, history)
    except Exception:  # noqa: BLE001 - the JSONL copy is authoritative
        pass
    try:
        import numpy as np
        cols: Dict[str, Any] = {
            "index": [o.index for o in history],
            "type": [o.type for o in history],
            "process": [str(o.process) for o in history],
            "f": [str(o.f) for o in history],
            "time": [o.time or 0 for o in history],
        }
        arrs = {k: np.asarray(v) for k, v in cols.items()}
        atomic_write(os.path.join(d, "history.npz"),
                     lambda f: np.savez_compressed(f, **arrs), mode="wb")
    except Exception:  # noqa: BLE001 - the npz is a convenience copy
        pass


def save_2(test: Dict[str, Any], results: Dict[str, Any]) -> None:
    """Phase 2: persist analysis results (store.clj:439): the full
    results.json plus a block-indexed results.jtsf whose tiny ``valid``
    block and per-key blocks can be read lazily (the reference's
    BlockRef/PartialMap lazy-results design, store/format.clj:97-120) —
    browsing a thousand runs' verdicts never loads a thousand big maps."""
    d = test["store_dir"]
    atomic_write(os.path.join(d, "results.json"),
                 lambda f: json.dump(results, f, indent=2, default=str))
    try:
        from jepsen_tpu.store import format as _fmt
        with atomic_path(os.path.join(d, "results.jtsf")) as tmp:
            with _fmt.Writer(tmp) as w:
                w.append_named_json("valid", {"valid": results.get("valid"),
                                              "keys": sorted(results)})
                for k, v in results.items():
                    w.append_named_json(f"results/{k}", v)
                # Elle anomaly artifacts (edge list, anomaly listings —
                # elle/render.py) ride along as named blocks, so the
                # verdict file is self-contained for refuted runs.
                _fmt.index_artifact_dir(w, d, "elle")
    except Exception:  # noqa: BLE001 - results.json is authoritative
        pass


def load_test(path: str) -> Dict[str, Any]:
    """Reload a run for re-analysis (store.clj:122/265's load/test)."""
    if os.path.islink(path) or os.path.isdir(path):
        d = os.path.realpath(path)
    else:
        d = path
    with open(os.path.join(d, "test.json")) as f:
        test = json.load(f)
    test["store_dir"] = d
    return test


def load_history(path: str) -> History:
    d = os.path.realpath(path) if os.path.isdir(path) else os.path.dirname(path)
    return History.from_jsonl(os.path.join(d, "history.jsonl"))


def load_results(path: str) -> Dict[str, Any]:
    d = os.path.realpath(path)
    with open(os.path.join(d, "results.json")) as f:
        return json.load(f)


class LazyResults:
    """Mapping-shaped lazy view over a run's results.jtsf: the verdict and
    key list load eagerly (one tiny block); each sub-result loads on first
    access with a single seek (PartialMap role, store/format.clj:113-120)."""

    def __init__(self, path: str):
        from jepsen_tpu.store import format as _fmt
        self._store = _fmt.LazyStore(path)
        head = self._store.read_json("valid")
        self.valid = head.get("valid")
        self._keys = head.get("keys") or []
        self._cache: Dict[str, Any] = {}

    def keys(self):
        return list(self._keys)

    def __contains__(self, k):
        return k in self._keys

    def __iter__(self):
        return iter(self._keys)

    def __getitem__(self, k):
        if k not in self._cache:
            self._cache[k] = self._store.read_json(f"results/{k}")
        return self._cache[k]

    def get(self, k, default=None):
        return self[k] if k in self._keys else default


def load_results_lazy(path: str) -> "LazyResults | Dict[str, Any]":
    """Lazy results view when the run has a results.jtsf; falls back to the
    eager JSON load for older runs."""
    d = os.path.realpath(path)
    p = os.path.join(d, "results.jtsf")
    if os.path.exists(p):
        try:
            return LazyResults(p)
        except Exception:  # noqa: BLE001 - fall back to the JSON blob
            pass
    return load_results(path)


def runs(base: str = BASE) -> List[Dict[str, Any]]:
    """All stored runs with verdicts, newest first (for CLI/web browsing)."""
    out = []
    if not os.path.isdir(base):
        return out
    for name in sorted(os.listdir(base)):
        nd = os.path.join(base, name)
        if not os.path.isdir(nd) or name == "latest":
            continue
        for stamp in sorted(os.listdir(nd), reverse=True):
            d = os.path.join(nd, stamp)
            if stamp == "latest" or not os.path.isdir(d):
                continue
            entry = {"name": name, "time": stamp, "dir": d, "valid": None}
            lp = os.path.join(d, "results.jtsf")
            rp = os.path.join(d, "results.json")
            read_ok = False
            if os.path.exists(lp):
                # One tiny block read per run instead of parsing the whole
                # results blob (which can hold per-key maps for 10^3 keys).
                try:
                    from jepsen_tpu.store import format as _fmt
                    entry["valid"] = _fmt.LazyStore(lp).read_json(
                        "valid").get("valid")
                    read_ok = True  # a None verdict is a real verdict
                except Exception:  # noqa: BLE001
                    pass
            if not read_ok and os.path.exists(rp):
                try:
                    with open(rp) as f:
                        entry["valid"] = json.load(f).get("valid")
                except (OSError, json.JSONDecodeError):
                    pass
            out.append(entry)
    return out


class JsonLineFormatter(logging.Formatter):
    """One JSON object per log line (cli.clj:98 --logging-json parity):
    machine-ingestable run logs for fleet/CI pipelines."""

    def format(self, record: logging.LogRecord) -> str:
        entry = {"ts": self.formatTime(record),
                 "level": record.levelname,
                 "thread": record.threadName,
                 "logger": record.name,
                 "message": record.getMessage()}
        if record.exc_info:
            entry["exception"] = self.formatException(record.exc_info)
        return json.dumps(entry, default=str)


def start_logging(test: Dict[str, Any]) -> logging.Handler:
    """Per-run log file (store.clj:474 start-logging!).  With
    ``test["logging_json"]`` the file is JSON-lines (cli.clj:98)."""
    d = test.get("store_dir") or make_run_dir(test)
    h = logging.FileHandler(os.path.join(d, "jepsen.log"))
    if test.get("logging_json"):
        h.setFormatter(JsonLineFormatter())
    else:
        h.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s [%(threadName)s] %(name)s: "
            "%(message)s"))
    root = logging.getLogger()
    root.addHandler(h)
    if root.level > logging.INFO:
        root.setLevel(logging.INFO)
    return h


def stop_logging(handler: logging.Handler) -> None:
    logging.getLogger().removeHandler(handler)
    handler.close()
