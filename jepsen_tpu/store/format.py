"""Binary block store — append-only, CRC32-checked, lazily readable.

Parity: the reference's custom .jepsen file format
(jepsen/src/jepsen/store/format.clj:36-120: magic + checksummed blocks,
append-only so a crash never corrupts earlier data, lazy reads for
larger-than-memory histories) and its positioned Java write stream
(store/FileOffsetOutputStream.java).

Two interchangeable engines writing the identical format:
- the C++ shared library (native/storefmt.cpp), compiled on demand with g++
  and loaded via ctypes — the fast path;
- a pure-Python fallback.

Format:  "JTSF0001" then blocks of [len:u32le][crc:u32le][tag:u8][payload],
crc = crc32(tag || payload).
"""

from __future__ import annotations

import ctypes
import json
import os
import struct
import subprocess
import tempfile
import zlib
from typing import Any, Iterator, List, Optional, Tuple

MAGIC = b"JTSF0001"

TAG_JSON = 1
TAG_BYTES = 2
TAG_OPS = 3    # one JSONL chunk of ops
TAG_INDEX = 4  # JSON {name: block-header offset} — BlockRef indirection

_LIB: Optional[ctypes.CDLL] = None
_LIB_TRIED = False


def _native_lib() -> Optional[ctypes.CDLL]:
    """Compile+load the C++ engine (cached .so); None if unavailable."""
    global _LIB, _LIB_TRIED
    if _LIB_TRIED:
        return _LIB
    _LIB_TRIED = True
    src = os.path.join(os.path.dirname(__file__), "..", "native",
                       "storefmt.cpp")
    cache_dir = os.path.join(tempfile.gettempdir(), "jepsen-tpu-native")
    os.makedirs(cache_dir, exist_ok=True)
    so = os.path.join(cache_dir, "libstorefmt.so")
    try:
        if not os.path.exists(so) or \
                os.path.getmtime(so) < os.path.getmtime(src):
            subprocess.run(["g++", "-O2", "-shared", "-fPIC", "-o", so, src],
                           check=True, capture_output=True)
        lib = ctypes.CDLL(so)
        lib.jtsf_open.restype = ctypes.c_void_p
        lib.jtsf_open.argtypes = [ctypes.c_char_p]
        lib.jtsf_append.restype = ctypes.c_int
        lib.jtsf_append.argtypes = [ctypes.c_void_p, ctypes.c_uint8,
                                    ctypes.c_char_p, ctypes.c_uint32]
        lib.jtsf_flush.argtypes = [ctypes.c_void_p]
        lib.jtsf_close.argtypes = [ctypes.c_void_p]
        lib.jtsf_verify.restype = ctypes.c_long
        lib.jtsf_verify.argtypes = [ctypes.c_char_p]
        _LIB = lib
    except (subprocess.CalledProcessError, OSError):
        _LIB = None
    return _LIB


class Writer:
    """Append blocks to a store file (native engine when available).

    Blocks may be *named* via :meth:`append_named`; on close, a TAG_INDEX
    block mapping name -> block-header byte offset is appended.  Readers can
    then seek straight to a named block without touching anything else — the
    role of the reference's BlockRef indirection (store/format.clj:97-110).
    Append-only: re-opening and appending writes a fresh index whose entries
    shadow the previous one (last index wins), so earlier data is never
    rewritten."""

    def __init__(self, path: str, native: Optional[bool] = None):
        self.path = path
        try:
            sz = os.path.getsize(path)
        except OSError:
            sz = 0
        # Byte offset of the next block header (magic occupies [0, 8)).
        self._off = sz if sz > 0 else len(MAGIC)
        # Reopening preserves reachability of earlier named blocks: the
        # closing index must be a superset of the previous one, so preload
        # it (new names then shadow old ones).
        self._index: dict = {}
        self._index_dirty = False
        if sz > len(MAGIC):
            try:
                self._index = read_index(path)
            except (OSError, ValueError, CorruptBlock):
                pass
        lib = _native_lib() if native in (None, True) else None
        if native is True and lib is None:
            raise RuntimeError("native store engine unavailable")
        self._lib = lib
        if lib is not None:
            self._h = lib.jtsf_open(path.encode())
            if not self._h:
                raise OSError(f"can't open {path}")
            self._f = None
        else:
            self._f = open(path, "ab")
            if self._f.tell() == 0:
                self._f.write(MAGIC)
            self._h = None

    @property
    def engine(self) -> str:
        return "native" if self._lib is not None else "python"

    def append(self, payload: bytes, tag: int = TAG_BYTES) -> int:
        """Append one block; returns its header byte offset."""
        off = self._off
        if self._lib is not None:
            rc = self._lib.jtsf_append(self._h, tag, payload, len(payload))
            if rc != 0:
                raise OSError("append failed")
        else:
            crc = zlib.crc32(bytes([tag]) + payload) & 0xFFFFFFFF
            self._f.write(struct.pack("<II", len(payload), crc))
            self._f.write(bytes([tag]))
            self._f.write(payload)
        self._off += 9 + len(payload)
        return off

    def append_json(self, value: Any) -> int:
        return self.append(json.dumps(value, default=str).encode(), TAG_JSON)

    def append_named(self, name: str, payload: bytes,
                     tag: int = TAG_BYTES) -> int:
        """Append a block reachable by name via the closing index."""
        off = self.append(payload, tag)
        self._index[name] = off
        self._index_dirty = True
        return off

    def append_named_json(self, name: str, value: Any) -> int:
        return self.append_named(
            name, json.dumps(value, default=str).encode(), TAG_JSON)

    def flush(self) -> None:
        if self._lib is not None:
            self._lib.jtsf_flush(self._h)
        else:
            self._f.flush()

    def close(self) -> None:
        if self._index_dirty:
            self.append(json.dumps(self._index).encode(), TAG_INDEX)
            self._index = {}
            self._index_dirty = False
        if self._lib is not None:
            if self._h:
                self._lib.jtsf_close(self._h)
                self._h = None
        elif self._f:
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class CorruptBlock(Exception):
    def __init__(self, index: int):
        super().__init__(f"corrupt block #{index}")
        self.index = index


def read_blocks(path: str) -> Iterator[Tuple[int, bytes]]:
    """Lazily yield (tag, payload), verifying CRCs as we go."""
    with open(path, "rb") as f:
        if f.read(8) != MAGIC:
            raise CorruptBlock(-1)
        i = 0
        while True:
            hdr = f.read(9)
            if not hdr:
                return
            if len(hdr) != 9:
                raise CorruptBlock(i)
            length, crc = struct.unpack("<II", hdr[:8])
            tag = hdr[8]
            payload = f.read(length)
            if len(payload) != length or \
                    (zlib.crc32(bytes([tag]) + payload) & 0xFFFFFFFF) != crc:
                raise CorruptBlock(i)
            yield tag, payload
            i += 1


def _scan_headers(path: str) -> Iterator[Tuple[int, int, int]]:
    """Yield (offset, tag, length) for every block, reading headers only —
    payloads are skipped with seeks, so this is cheap even for huge files."""
    with open(path, "rb") as f:
        if f.read(8) != MAGIC:
            raise CorruptBlock(-1)
        i = 0
        off = 8
        while True:
            hdr = f.read(9)
            if not hdr:
                return
            if len(hdr) != 9:
                raise CorruptBlock(i)
            length = struct.unpack("<I", hdr[:4])[0]
            yield off, hdr[8], length
            f.seek(length, 1)
            off += 9 + length
            i += 1


def read_block_at(path: str, offset: int) -> Tuple[int, bytes]:
    """Read (and CRC-check) the single block whose header starts at
    ``offset`` — the BlockRef dereference: no other payload is touched."""
    with open(path, "rb") as f:
        f.seek(offset)
        hdr = f.read(9)
        if len(hdr) != 9:
            raise CorruptBlock(-1)
        length, crc = struct.unpack("<II", hdr[:8])
        tag = hdr[8]
        payload = f.read(length)
    if len(payload) != length or \
            (zlib.crc32(bytes([tag]) + payload) & 0xFFFFFFFF) != crc:
        raise CorruptBlock(-1)
    return tag, payload


def read_index(path: str) -> dict:
    """Name -> offset map from the *last* TAG_INDEX block (later appends
    shadow earlier indices).  Header-skip scan: payloads are not read."""
    last = None
    for off, tag, _length in _scan_headers(path):
        if tag == TAG_INDEX:
            last = off
    if last is None:
        return {}
    _tag, payload = read_block_at(path, last)
    return json.loads(payload.decode())


class LazyStore:
    """Named-block view over a store file: ``names()`` is cheap, each
    ``read(name)`` seeks to exactly one block.  The PartialMap role from
    the reference (store/format.clj:113-120): consumers pull the small
    blocks (a verdict) without paying for the big ones (per-key results,
    plots, histories)."""

    def __init__(self, path: str):
        self.path = path
        self._index = read_index(path)

    def names(self):
        return sorted(self._index)

    def __contains__(self, name: str) -> bool:
        return name in self._index

    def read(self, name: str) -> bytes:
        return read_block_at(self.path, self._index[name])[1]

    def read_json(self, name: str) -> Any:
        return json.loads(self.read(name).decode())


def verify(path: str) -> int:
    """Number of valid blocks; raises CorruptBlock on damage.  Uses the
    native verifier when available."""
    lib = _native_lib()
    if lib is not None:
        n = lib.jtsf_verify(path.encode())
        if n < 0:
            raise CorruptBlock(-1 - n)
        return int(n)
    return sum(1 for _ in read_blocks(path))


# -- artifact indexing -------------------------------------------------------

#: per-file embed ceiling for index_artifact_dir: anomaly listings and
#: edge lists fit; nothing pathological can balloon results.jtsf.
MAX_ARTIFACT_BYTES = 4 << 20


def index_artifact_dir(writer: Writer, store_dir: str,
                       subdir: str = "elle") -> int:
    """Index a run's artifact directory (e.g. the elle/ anomaly dir) into
    a block store: each file becomes a named block
    ``artifacts/<subdir>/<name>`` (its bytes, up to MAX_ARTIFACT_BYTES),
    and a manifest block ``artifacts/<subdir>`` lists every file with its
    size and whether it was embedded.  Readers then pull one anomaly
    listing or the edge list with a single seek — without the store dir
    even present (results.jtsf travels alone).  Returns the number of
    files indexed (0 when the directory doesn't exist)."""
    d = os.path.join(store_dir, subdir)
    if not os.path.isdir(d):
        return 0
    manifest = []
    for name in sorted(os.listdir(d)):
        path = os.path.join(d, name)
        if not os.path.isfile(path):
            continue
        try:
            size = os.path.getsize(path)
        except OSError:
            continue
        entry = {"name": name, "bytes": size,
                 "embedded": size <= MAX_ARTIFACT_BYTES}
        if entry["embedded"]:
            with open(path, "rb") as f:
                writer.append_named(f"artifacts/{subdir}/{name}", f.read())
        manifest.append(entry)
    if manifest:
        writer.append_named_json(f"artifacts/{subdir}", manifest)
    return len(manifest)


# -- history-specific layer --------------------------------------------------

OPS_PER_BLOCK = 1024


def write_history(path: str, history, chunk: int = OPS_PER_BLOCK) -> None:
    """History as a sequence of op-chunk blocks (lazy, append-only)."""
    with Writer(path) as w:
        buf: List[str] = []
        for op in history:
            buf.append(json.dumps(op.to_dict(), default=str))
            if len(buf) >= chunk:
                w.append("\n".join(buf).encode(), TAG_OPS)
                buf = []
        if buf:
            w.append("\n".join(buf).encode(), TAG_OPS)


def iter_history(path: str):
    """Lazily yield op dicts from a history store file."""
    for tag, payload in read_blocks(path):
        if tag != TAG_OPS:
            continue
        for line in payload.decode().splitlines():
            if line.strip():
                yield json.loads(line)


def read_history(path: str):
    from jepsen_tpu.history import History
    return History(list(iter_history(path)))
