"""jepsen_tpu — a TPU-native distributed-systems testing framework.

A brand-new framework with the capability surface of Jepsen (reference:
/root/reference): a control plane that installs databases on cluster nodes,
drives concurrent client operations from a pure-functional generator, injects
faults through a nemesis, records a complete invocation/completion history,
and then decides the system's consistency claims by analysing that history.

The defining difference from the reference is the analysis engine:
linearizability checking (the reference delegates to the external `knossos`
library, jepsen/src/jepsen/checker.clj:185-216) is implemented here as a
JAX/XLA search — model step functions are pure jax.numpy transitions,
candidate linearization frontiers are fixed-shape device buffers expanded by
vmapped steps and deduplicated with sort kernels, and frontiers shard across
a TPU mesh via shard_map.
"""

__version__ = "0.1.0"

from jepsen_tpu.history import Op, History  # noqa: F401
