"""Lane-group packing: stack per-history encodings into padded batches.

One vmapped dispatch wants rectangular arrays, so a group of encoded
histories is padded to shared shapes:

- ``n_pad`` — txn count, rounded up to a multiple of 32 (min 32): the
  adjacency matrices are ``[n_pad, n_pad]`` and matmul tiles like round
  shapes; sharing one ``n_pad`` across *all* groups of a batch keeps one
  compiled kernel per (n_pad, realtime) rather than one per group.
- ``e_pad`` — edges per kind, rounded up to a multiple of 64 (min 64),
  ``-1``-padded (a ``-1`` endpoint one-hots to zero: padding contributes
  no edge).
- ``b_pad`` — lanes, padded with empty histories (all ``-1`` edges,
  ``invoke = -1``, ``complete = COMPLETE_PAD``) so a mesh-sharded batch
  divides evenly over the lane axis; padded lanes compute all-False
  flags and are dropped by the caller.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

from jepsen_tpu.elle_tpu.encode import COMPLETE_PAD, KINDS, EncodedHistory
# Word rounding comes off the shared engine ladder (one derivation for
# the elle adjacency pad, the serve elle bucket, and the engine-side
# n_pad_floor) instead of a private copy here.
from jepsen_tpu.engine.ladder import pad_words


def padded_n(encs: Sequence[EncodedHistory]) -> int:
    """The shared adjacency dimension for a batch of encodings."""
    return max(32, pad_words(max((e.n for e in encs), default=1) or 1, 32))


def pack_group(encs: Sequence[EncodedHistory],
               n_pad: Optional[int] = None,
               b_pad: Optional[int] = None) -> Dict[str, np.ndarray]:
    """Stack a lane group into one padded batch of device inputs."""
    if n_pad is None:
        n_pad = padded_n(encs)
    b = len(encs)
    if b_pad is None:
        b_pad = b
    e_pad = max(64, pad_words(max(e.src.shape[1] for e in encs), 64))
    src = np.full((b_pad, len(KINDS), e_pad), -1, np.int32)
    dst = np.full((b_pad, len(KINDS), e_pad), -1, np.int32)
    invoke = np.full((b_pad, n_pad), -1, np.int32)
    complete = np.full((b_pad, n_pad), COMPLETE_PAD, np.int32)
    for i, enc in enumerate(encs):
        ew = enc.src.shape[1]
        src[i, :, :ew] = enc.src
        dst[i, :, :ew] = enc.dst
        nn = enc.invoke.shape[0]
        invoke[i, :nn] = enc.invoke
        complete[i, :nn] = enc.complete
    return {"src": src, "dst": dst, "invoke": invoke, "complete": complete}
