"""The elle_tpu engine: grouping, sharding, budgets, degradation chain.

``check_batch`` fans a set of histories out as lanes of the vmapped
closure kernel:

- lanes are dispatched in bounded groups — at most
  ``parallel.batch.MAX_LANES_PER_GROUP`` (the vmap-width cap that
  module's bool-scatter repro established; the one-hot-matmul kernel
  avoids the scatter, but staying under the proven-safe width costs
  nothing) and at most ``LANE_CELLS_PER_GROUP / n_pad^2`` lanes so one
  dispatch's adjacency residency stays bounded as histories grow;
- with a ``mesh``, each group is padded to the lane axis and sharded
  with ``NamedSharding(mesh, P(axis, ...))`` like parallel/batch.py —
  pure SPMD fan-out, no collectives;
- ``budget_s`` bounds the *whole call's* witness recovery: every lane's
  CPU search gets a SearchBudget deadline at the call's remaining time
  (the device pass itself is a handful of bounded matmuls — it's the
  host-side cycle search that can wedge, see elle.graph.SearchBudget);
- a device failure downgrades the affected group to the CPU path with a
  ``fallback``/``fallback-chain`` annotation, mirroring
  checker.linearizable's TPU->CPU chain: a device error says nothing
  about the history and must never decide a verdict.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from jepsen_tpu.elle_tpu.anomalies import finish_lane
from jepsen_tpu.elle_tpu.encode import EncodedHistory, encode
from jepsen_tpu.elle_tpu.graphs import pack_group, padded_n
from jepsen_tpu.engine.budget import Deadline
from jepsen_tpu.engine.fallback import (
    annotate_fallback, chain_entry, warn_fallback,
)
from jepsen_tpu.engine.groups import (
    MAX_LANES_PER_GROUP, bounded_group_cap,
)
from jepsen_tpu.history import History

log = logging.getLogger(__name__)

#: cap on (lanes x n_pad^2) adjacency cells resident per dispatch: three
#: closure masks plus temporaries per lane, so ~16M cells keeps a group
#: under a few hundred MB of f32 at any history size.
LANE_CELLS_PER_GROUP = 1 << 24

ENGINES = ("auto", "tpu", "cpu")


def available() -> bool:
    """True when a JAX backend with at least one device is importable —
    the engine itself is backend-agnostic (the kernel is plain jnp)."""
    try:
        import jax
        return len(jax.devices()) > 0
    except Exception:  # noqa: BLE001 — any init failure means "no"
        return False


def group_cap(n_pad: int) -> int:
    return bounded_group_cap(LANE_CELLS_PER_GROUP, n_pad * n_pad)


def check(history: History, **kw) -> Dict[str, Any]:
    """Single-history convenience wrapper over :func:`check_batch`."""
    return check_batch([history], **kw)[0]


def check_batch(histories: Sequence[History],
                workload: str = "list-append",
                realtime: bool = False,
                consistency_models: Optional[Sequence[str]] = None,
                engine: str = "auto",
                mesh=None,
                axis: str = "data",
                budget_s: Optional[float] = None,
                n_pad_floor: int = 0,
                **workload_kw) -> List[Dict[str, Any]]:
    """Check many histories at once; one elle-shaped result per history.

    ``engine``: ``"auto"``/``"tpu"`` run the device pass (falling back to
    CPU per group on device errors), ``"cpu"`` skips the device and runs
    the full CPU search per lane (still through this code path, so budget
    and artifacts behave identically).  ``n_pad_floor`` pads the shared
    adjacency dimension up to a caller-chosen bucket so successive batches
    of similar histories reuse one compiled closure kernel (the serve
    scheduler's shape-bucketing lever; 0 = tightest)."""
    if engine not in ENGINES:
        raise ValueError(f"unknown engine {engine!r}; known: {ENGINES}")
    if not histories:
        return []
    if consistency_models is None:
        consistency_models = (("strict-serializable",) if realtime
                              else ("serializable",))
    deadline = Deadline.after(budget_s)
    encs = [encode(h, workload, **workload_kw) for h in histories]
    # Floor padding shares the ladder's word rounding with padded_n —
    # one derivation, so the serve elle bucket and a floorless call land
    # on identical rungs.
    from jepsen_tpu.engine.ladder import pad_words
    n_pad = max(padded_n(encs), pad_words(n_pad_floor))
    cap = group_cap(n_pad)
    use_device = engine != "cpu" and available()
    if engine == "tpu" and not use_device:
        raise RuntimeError("elle_tpu device engine requested but no JAX "
                           "device is available")

    groups = [encs[i:i + cap] for i in range(0, len(encs), cap)]
    gflags: List[Optional[np.ndarray]] = [None] * len(groups)
    gchain: List[Optional[List[Dict[str, Any]]]] = [None] * len(groups)
    if use_device:
        _device_flags_pipelined(groups, n_pad, realtime, mesh, axis,
                                gflags, gchain)

    out: List[Dict[str, Any]] = []
    for gi, group in enumerate(groups):
        flags = gflags[gi]
        chain = gchain[gi]
        for j, enc in enumerate(group):
            budget = deadline.search_budget()
            res = finish_lane(enc, flags[j] if flags is not None else None,
                              realtime, consistency_models, budget=budget)
            if chain is not None:
                annotate_fallback(res, "elle-tpu", "elle-cpu", chain[0],
                                  chain)
                res["analyzer"] = "elle-cpu"
            elif flags is None:
                res["analyzer"] = "elle-cpu"
            out.append(res)
    return out


def _device_flags_pipelined(groups, n_pad: int, realtime: bool, mesh,
                            axis: str, gflags, gchain) -> None:
    """Dispatch every lane group asynchronously with a bounded in-flight
    window and a fused per-group readback.

    Group i+1's ``device_put`` (host→device upload of the packed edge
    tensors) overlaps group i's closure matmuls via JAX async dispatch —
    the host never blocks between dispatches.  Each group's readback is
    ONE fused scalar (the flag sum, computed device-side); the per-lane
    ``[b, 4]`` flag array transfers only for groups where it is nonzero.
    A zero sum means the device proved every lane anomaly-free, so the
    all-False flags are synthesized host-side — same verdicts, O(1)
    device→host traffic on the (dominant) clean path.  All groups share
    the one compiled ``lane_flags_fn(n_pad, realtime)`` executable.

    Failures stay per-group: an exception during dispatch or readback
    degrades that group to the CPU path via ``gchain`` (device trouble
    says nothing about the histories), exactly like the old synchronous
    loop."""
    from collections import deque

    from jepsen_tpu.parallel.megabatch import staging_depth_default

    depth = staging_depth_default()
    inflight: deque = deque()

    def _fail(gi, n, e):
        warn_fallback("elle-tpu", "elle-cpu", e, n_lanes=n)
        gchain[gi] = [chain_entry("elle-tpu", e)]

    def _drain():
        gi, b, flags_dev, summ_dev = inflight.popleft()
        try:
            if int(np.asarray(summ_dev)) == 0:
                gflags[gi] = np.zeros((b, 4), bool)
            else:
                gflags[gi] = np.asarray(flags_dev)[:b]
        except Exception as e:  # noqa: BLE001 — runtime device trouble
            _fail(gi, b, e)

    for gi, group in enumerate(groups):
        try:
            flags_dev, summ_dev = _device_flags_async(
                group, n_pad, realtime, mesh, axis)
            inflight.append((gi, len(group), flags_dev, summ_dev))
        except Exception as e:  # noqa: BLE001 — dispatch-time trouble
            _fail(gi, len(group), e)
        while len(inflight) > depth:
            _drain()
    while inflight:
        _drain()


def _device_flags_async(group: Sequence[EncodedHistory], n_pad: int,
                        realtime: bool, mesh, axis: str):
    """Enqueue one vmapped dispatch over a lane group; returns the
    un-read device ``[b_pad, 4]`` flag array plus its fused scalar sum —
    no host sync happens here (JAX async dispatch)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from jepsen_tpu.elle_tpu.closure import lane_flags_fn

    b = len(group)
    b_pad = b
    if mesh is not None:
        n_sh = mesh.shape[axis]
        b_pad = ((b + n_sh - 1) // n_sh) * n_sh
    packed = pack_group(group, n_pad=n_pad, b_pad=b_pad)
    arrays = {k: jnp.asarray(v) for k, v in packed.items()}
    if mesh is not None:
        arrays = {k: jax.device_put(
            v, NamedSharding(mesh, P(axis, *([None] * (v.ndim - 1)))))
            for k, v in arrays.items()}
    fn = lane_flags_fn(n_pad, realtime)
    flags = fn(arrays["src"], arrays["dst"],
               arrays["invoke"], arrays["complete"])
    return flags, jnp.sum(flags)
