"""The device kernel: boolean-matmul transitive closure, vmapped flags.

Per lane the kernel rebuilds the dependency graph as stacked ``[N, N]``
float32 0/1 adjacency layers and answers four booleans:

- ``cyclic``    — any cycle in ww ∪ wr ∪ rw ∪ rt (the full graph);
- ``g0``        — any cycle in ww ∪ rt (a pure write cycle);
- ``g1c``       — any cycle in ww ∪ wr ∪ rt (information-flow cycle);
- ``g-single``  — some rw edge a->b with a return path b ->* a through
  non-rw layers: exactly one anti-dependency in the cycle (the same
  predicate elle.graph.gsingle_cycles searches per rw edge).

Construction notes:

- Adjacency layers come from one-hot matmuls (``one_hot(src).T @
  one_hot(dst)``), never scatters: a vmapped scatter into bool arrays
  miscompiles at >= 1024 lanes (parallel/batch.py MAX_LANES_PER_GROUP
  documents the minimized repro), and an int/float matmul is the shape
  TPUs like anyway.  ``-1`` padding one-hots to a zero row and vanishes.
- The realtime layer is a broadcast comparison, not an edge list:
  ``rt[i, j] = (invoke[j] >= 0) & (complete[i] < invoke[j])`` — the CPU
  checker's O(N^2) Python loop (elle.list_append.add_realtime_edges) as
  one fused device op.  Compiled out entirely when ``realtime=False``.
- Closure by repeated squaring: ``R <- min(R + R@R, 1)`` doubles the
  reachable path length per iteration, so ``ceil(log2(N))`` iterations
  close paths of any length <= N.  ``Graph.add_edge`` never stores
  self-edges, so a nonzero closure diagonal is a genuine cycle.
- float32 0/1 instead of bool: bool matmul lowers poorly and the min()
  re-clamp keeps values exact (0.0/1.0) — no epsilon drift.
"""

from __future__ import annotations

import math
from functools import lru_cache

import jax
import jax.numpy as jnp

#: order of the per-lane flag vector returned by the kernel.
FLAG_NAMES = ("cyclic", "g0", "g1c", "g-single")


def transitive_closure(adj: jnp.ndarray, n_iters: int) -> jnp.ndarray:
    """Close a 0/1 float adjacency matrix over paths of length >= 1."""
    def body(_, r):
        return jnp.minimum(r + r @ r, 1.0)
    return jax.lax.fori_loop(0, n_iters, body, adj)


def _layer(src: jnp.ndarray, dst: jnp.ndarray, n: int) -> jnp.ndarray:
    """[E]-indexed edge endpoints -> [N, N] 0/1 adjacency, by matmul."""
    oh_s = jax.nn.one_hot(src, n, dtype=jnp.float32)   # [E, N]; -1 -> 0s
    oh_d = jax.nn.one_hot(dst, n, dtype=jnp.float32)
    return jnp.minimum(oh_s.T @ oh_d, 1.0)


@lru_cache(maxsize=None)
def lane_flags_fn(n_pad: int, realtime: bool):
    """The jitted vmapped kernel for one (n_pad, realtime) shape class.

    Takes ``src/dst [B, 3, E]`` and ``invoke/complete [B, N]``; returns
    ``[B, len(FLAG_NAMES)]`` bools.  Edge-count ``E`` may vary between
    calls (jit retraces per shape; e_pad is quantized to multiples of 64
    by graphs.pack_group to bound the variant count)."""
    n_iters = max(1, math.ceil(math.log2(n_pad)))

    def lane(src, dst, invoke, complete):
        ww = _layer(src[0], dst[0], n_pad)
        wr = _layer(src[1], dst[1], n_pad)
        rw = _layer(src[2], dst[2], n_pad)
        if realtime:
            rt = ((complete[:, None] < invoke[None, :])
                  & (invoke[None, :] >= 0)).astype(jnp.float32)
        else:
            rt = jnp.zeros((n_pad, n_pad), jnp.float32)
        nonrw = jnp.minimum(ww + wr + rt, 1.0)
        full = jnp.minimum(nonrw + rw, 1.0)
        g0_adj = jnp.minimum(ww + rt, 1.0)
        cl_full = transitive_closure(full, n_iters)
        cl_nonrw = transitive_closure(nonrw, n_iters)
        cl_g0 = transitive_closure(g0_adj, n_iters)
        cyclic = jnp.trace(cl_full) > 0
        g0 = jnp.trace(cl_g0) > 0
        g1c = jnp.trace(cl_nonrw) > 0
        # rw edge a->b plus a nonrw path b ->* a: cl_nonrw[b, a] read
        # through the transpose aligns with rw[a, b].
        g_single = jnp.sum(rw * cl_nonrw.T) > 0
        return jnp.stack([cyclic, g0, g1c, g_single])

    return jax.jit(jax.vmap(lane))
