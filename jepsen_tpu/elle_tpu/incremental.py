"""Incremental elle closure: extend the previous fixpoint, don't restart.

The epoch monitor's elle side re-checks a growing prefix every epoch.
The cold kernel (:mod:`jepsen_tpu.elle_tpu.closure`) closes each epoch's
adjacency from scratch — ``ceil(log2 N)`` boolean squarings of an
``[N, N]`` matrix — so per-epoch cost grows with history length.  But
the closure is monotone under edge appends: for ``S ⊇ A``,

    closure(S) = closure(closure(A) ∨ S)

so seeding the squaring loop with the *previous epoch's closed matrix*
OR'd over the current layers converges in however many doublings the
NEW paths need (typically one or two), not ``log2 N``.  The three
closed matrices (full / nonrw / g0) stay resident on device between
epochs; per-anomaly flags are read off the extended matrices exactly as
the cold lane computes them, and the result dict is assembled by the
same ``finish_lane`` the cold engine uses — identical anomaly sets by
construction.

When warm seeding is *not* provably sound, the engine resets cold and
says so in its counters.  The guards, checked per epoch against the
stored state:

- node-ordinal stability — ``encode``'s node order is the OK-txn
  enumeration of the client subhistory, append-only for an append-only
  op stream, and cut ``info`` txns are never graph nodes; the stored
  ``invoke``/``complete`` prefixes must match exactly;
- edge-implication — the soundness condition is per-lane closure
  containment, ``cl(A) ⊆ cl(S)``, and the direct edge sets do NOT grow
  monotonically: a new read refines a key's version order, replacing an
  adjacent-pair ww edge ``A→C`` with ``A→B, B→C`` (and re-targeting rw
  antidependencies).  So every previous direct edge must either survive
  or be *implied by a same-lane path* in today's graph: a lost ww edge
  needs a ww path (it sits in all three lanes, g0 included), a lost wr
  edge a ww∪wr path (the nonrw lane), a lost rw edge a ww∪wr∪rw path
  (rw edges only ever enter the full lane — the rw matrix itself is
  rebuilt fresh each epoch, never carried).  Closure is monotone and
  idempotent, so implied-per-lane direct edges give
  ``cl_lane(A) ⊆ cl_lane(cl_lane(S)) = cl_lane(S)`` exactly.  A lost
  edge with no implying path (a genuinely reordered version graph,
  e.g. an incompatible-order anomaly) fails the guard and resets cold.

The host analysis + encode still run over the full prefix each epoch
(an O(prefix) host residual — the device closure is what this module
makes incremental); ``JTPU_STREAM_ORACLE=1`` additionally runs the cold
device kernel every epoch and prefers its flags on any mismatch (the
parity oracle the fuzz tests and the smoke job use).
"""

from __future__ import annotations

import math
import os
from functools import lru_cache
from typing import Any, Dict, List, Optional, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from jepsen_tpu.elle_tpu.closure import _layer, lane_flags_fn
from jepsen_tpu.elle_tpu.encode import KINDS, EncodedHistory, encode
from jepsen_tpu.engine.budget import Deadline
from jepsen_tpu.engine.ladder import pad_words
from jepsen_tpu.monitor.epochs import ElleEpochEngine


def oracle_enabled() -> bool:
    return os.environ.get("JTPU_STREAM_ORACLE", "") not in ("", "0",
                                                            "false", "off")


@lru_cache(maxsize=None)
def _seed_fn(n_pad: int, realtime: bool):
    """Jitted seeding: rebuild today's adjacency layers and OR the
    previous epoch's closed matrices on top.  One trace per
    (n_pad, realtime); the edge axis retraces per 64-quantized e_pad."""

    def seed(src, dst, invoke, complete, prev_full, prev_nonrw, prev_g0):
        ww = _layer(src[0], dst[0], n_pad)
        wr = _layer(src[1], dst[1], n_pad)
        rw = _layer(src[2], dst[2], n_pad)
        if realtime:
            rt = ((complete[:, None] < invoke[None, :])
                  & (invoke[None, :] >= 0)).astype(jnp.float32)
        else:
            rt = jnp.zeros((n_pad, n_pad), jnp.float32)
        nonrw = jnp.minimum(ww + wr + rt, 1.0)
        full = jnp.minimum(nonrw + rw, 1.0)
        g0 = jnp.minimum(ww + rt, 1.0)
        return (jnp.minimum(full + prev_full, 1.0),
                jnp.minimum(nonrw + prev_nonrw, 1.0),
                jnp.minimum(g0 + prev_g0, 1.0),
                rw)

    return jax.jit(seed)


@lru_cache(maxsize=None)
def _square_fn(n_pad: int):
    """Two path-doubling rounds over the three matrices plus their sums
    (the host's convergence probe: a closed 0/1 matrix is a fixpoint of
    ``min(R + R@R, 1)`` iff its sum stops growing — monotone, exact)."""

    def sq(a, b, c):
        for _ in range(2):
            a = jnp.minimum(a + a @ a, 1.0)
            b = jnp.minimum(b + b @ b, 1.0)
            c = jnp.minimum(c + c @ c, 1.0)
        return a, b, c, jnp.stack([a.sum(), b.sum(), c.sum()])

    return jax.jit(sq)


@lru_cache(maxsize=None)
def _flags_fn(n_pad: int):
    def flags(cl_full, cl_nonrw, cl_g0, rw):
        return jnp.stack([jnp.trace(cl_full) > 0,
                          jnp.trace(cl_g0) > 0,
                          jnp.trace(cl_nonrw) > 0,
                          jnp.sum(rw * cl_nonrw.T) > 0])

    return jax.jit(flags)


class _ClosureState:
    """The previous epoch's device-resident fixpoint plus the host-side
    facts that prove it is still extendable."""

    __slots__ = ("n", "n_pad", "edges", "invoke", "complete",
                 "cl_full", "cl_nonrw", "cl_g0")

    def __init__(self, n, n_pad, edges, invoke, complete,
                 cl_full, cl_nonrw, cl_g0):
        self.n = n
        self.n_pad = n_pad
        self.edges = edges
        self.invoke = invoke
        self.complete = complete
        self.cl_full = cl_full
        self.cl_nonrw = cl_nonrw
        self.cl_g0 = cl_g0


def _edge_set(enc: EncodedHistory) -> Set[Tuple[int, int, int]]:
    out = set()
    for i in range(len(KINDS)):
        for s, d in zip(enc.src[i], enc.dst[i]):
            if s >= 0:
                out.add((i, int(s), int(d)))
    return out


#: per-kind edge universes an implying path may use (KINDS order is
#: ww, wr, rw): a lost ww edge is in every lane including g0, so only a
#: ww path implies it everywhere; wr sits in nonrw and full; rw only in
#: the full lane.
_IMPLY_KINDS = {0: (0,), 1: (0, 1), 2: (0, 1, 2)}


def _lost_edges_implied(lost: Set[Tuple[int, int, int]],
                        edges: Set[Tuple[int, int, int]]) -> bool:
    """True when every lost previous direct edge is implied by a
    same-lane path in today's direct graph — the refinement case
    (version orders gaining intermediate writes), not a reorder."""
    adj: Dict[int, Dict[int, List[int]]] = {k: {} for k in _IMPLY_KINDS}
    for k, s, d in edges:
        adj[k].setdefault(s, []).append(d)
    for k, s, d in lost:
        lanes = _IMPLY_KINDS[k]
        seen = {s}
        stack = [s]
        found = False
        while stack and not found:
            u = stack.pop()
            for kk in lanes:
                for v in adj[kk].get(u, ()):
                    if v == d:
                        found = True
                        break
                    if v not in seen:
                        seen.add(v)
                        stack.append(v)
                if found:
                    break
        if not found:
            return False
    return True


def _pad_edges(enc: EncodedHistory) -> Tuple[np.ndarray, np.ndarray]:
    e_pad = pad_words(max(1, enc.src.shape[1]), 64)
    src = np.full((len(KINDS), e_pad), -1, np.int32)
    dst = np.full((len(KINDS), e_pad), -1, np.int32)
    src[:, :enc.src.shape[1]] = enc.src
    dst[:, :enc.dst.shape[1]] = enc.dst
    return src, dst


def _grow(mat, n_pad: int):
    """Re-pad a closed [m, m] matrix top-left into an [n_pad, n_pad]
    zero matrix when the stream climbs an n rung."""
    m = mat.shape[0]
    if m == n_pad:
        return mat
    return jnp.zeros((n_pad, n_pad), jnp.float32).at[:m, :m].set(mat)


class IncrementalElleEngine(ElleEpochEngine):
    """ElleEpochEngine whose device closure extends across epochs."""

    def __init__(self, workload: str = "list-append",
                 realtime: bool = False, service=None,
                 budget_s: Optional[float] = None):
        super().__init__(workload=workload, realtime=realtime,
                         service=service, budget_s=budget_s)
        self._state: Optional[_ClosureState] = None
        self.resets = 0              # cold restarts (guards tripped)
        self.warm_extends = 0        # epochs that reused the fixpoint
        self.squarings = 0           # device squaring dispatches, total
        self.oracle_mismatches = 0

    def _check(self, h) -> Dict[str, Any]:
        try:
            return self._incremental_check(h)
        except Exception:  # noqa: BLE001 — device trouble: cold path
            self._state = None
            self.resets += 1
            return super()._check(h)

    def _warm(self, enc: EncodedHistory, edges, n_pad: int) -> bool:
        st = self._state
        if st is None or st.n_pad > n_pad or st.n > enc.n:
            return False
        if not (np.array_equal(st.invoke, enc.invoke[:len(st.invoke)])
                and np.array_equal(st.complete,
                                   enc.complete[:len(st.complete)])):
            return False
        lost = st.edges - edges
        return not lost or _lost_edges_implied(lost, edges)

    def _incremental_check(self, h) -> Dict[str, Any]:
        from jepsen_tpu.elle_tpu.anomalies import finish_lane
        from jepsen_tpu.serve import buckets

        enc = encode(h, self.workload)
        n_pad = buckets.pow2_at_least(max(1, enc.n), buckets.MIN_N_BUCKET)
        edges = _edge_set(enc)
        warm = self._warm(enc, edges, n_pad)
        if warm and self._state is not None:
            prev_full = _grow(self._state.cl_full, n_pad)
            prev_nonrw = _grow(self._state.cl_nonrw, n_pad)
            prev_g0 = _grow(self._state.cl_g0, n_pad)
            self.warm_extends += 1
        else:
            zero = jnp.zeros((n_pad, n_pad), jnp.float32)
            prev_full = prev_nonrw = prev_g0 = zero
            if self._state is not None:
                self.resets += 1
            self._state = None

        src, dst = _pad_edges(enc)
        m_full, m_nonrw, m_g0, rw = _seed_fn(n_pad, self.realtime)(
            jnp.asarray(src), jnp.asarray(dst),
            jnp.asarray(enc.invoke), jnp.asarray(enc.complete),
            prev_full, prev_nonrw, prev_g0)

        sq = _square_fn(n_pad)
        sums_prev = None
        for _ in range(max(1, math.ceil(math.log2(n_pad))) + 2):
            m_full, m_nonrw, m_g0, sums = sq(m_full, m_nonrw, m_g0)
            self.squarings += 1
            s = np.asarray(sums)
            if sums_prev is not None and np.array_equal(s, sums_prev):
                break
            sums_prev = s

        flags = np.asarray(_flags_fn(n_pad)(m_full, m_nonrw, m_g0, rw))

        if oracle_enabled():
            cold = np.asarray(lane_flags_fn(n_pad, self.realtime)(
                jnp.asarray(src)[None], jnp.asarray(dst)[None],
                jnp.asarray(enc.invoke[None]),
                jnp.asarray(enc.complete[None])))[0]
            if not np.array_equal(flags.astype(bool), cold.astype(bool)):
                self.oracle_mismatches += 1
                flags = cold    # the cold kernel wins — it IS the oracle

        self._state = _ClosureState(
            n=enc.n, n_pad=n_pad, edges=edges,
            invoke=enc.invoke.copy(), complete=enc.complete.copy(),
            cl_full=m_full, cl_nonrw=m_nonrw, cl_g0=m_g0)

        models = (("strict-serializable",) if self.realtime
                  else ("serializable",))
        deadline = Deadline.after(self.budget_s)
        res = finish_lane(enc, flags, self.realtime, models,
                          budget=deadline.search_budget())
        res["analyzer"] = "elle-stream"
        return res

    def counters(self) -> Dict[str, int]:
        c = super().counters()
        c["elle-resets"] = self.resets
        c["elle-warm-extends"] = self.warm_extends
        c["elle-squarings"] = self.squarings
        if oracle_enabled():
            c["elle-oracle-mismatches"] = self.oracle_mismatches
        return c
