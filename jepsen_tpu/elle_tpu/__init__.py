"""elle_tpu: the device tier of the Elle transactional-anomaly checkers.

The CPU checkers (jepsen_tpu.elle.list_append / rw_register) spend their
time in two places: a linear host pass that infers the dependency graph,
and a cycle-search suite over that graph.  The search is the hot part —
and "is this graph cyclic (under this edge-kind mask)?" is a dense
linear-algebra question: build the boolean adjacency matrix, close it
under repeated squaring (``R <- min(R + R@R, 1)``), and read the trace.
That formulation batches across whole histories with ``vmap`` — the same
decomposition argument as the linearizability batch tier
(P-compositionality, arXiv:1504.00204; decrease-and-conquer monitoring,
arXiv:2410.04581).

Division of labor (this is what makes device results *identical* to the
CPU oracle, not merely close):

- the host pass (``elle.list_append.analyze`` / ``elle.rw_register
  .analyze``) runs unchanged — same graph, same host anomalies;
- the device decides, per lane and per edge-kind mask, only the boolean
  "does a cycle exist" (cyclic / G0 / G1c / G-single flags);
- when a lane is cyclic, witness recovery runs the *same*
  ``collect_cycle_anomalies`` suite on the *same* graph the CPU checker
  would have searched, so the reported anomaly set is the CPU set by
  construction.  Acyclic lanes — the common case — skip CPU search
  entirely.

Module map: ``encode`` (history -> dense tensors), ``graphs`` (lane-group
packing/padding), ``closure`` (the jitted vmapped flag kernel),
``anomalies`` (per-lane verdict assembly + witness recovery), ``engine``
(grouping, sharding, budgets, degradation chain).  See docs/elle_tpu.md.
"""

from jepsen_tpu.elle_tpu.closure import FLAG_NAMES
from jepsen_tpu.elle_tpu.encode import EncodedHistory, encode
from jepsen_tpu.elle_tpu.engine import available, check, check_batch

__all__ = ["EncodedHistory", "FLAG_NAMES", "available", "check",
           "check_batch", "encode"]
