"""Per-lane verdict assembly: device flags -> elle-shaped result map.

The device only answers "is there a cycle (under mask X)?" — everything
human-readable comes from the CPU machinery, run *only when needed*:

- acyclic lane: no cycle search at all.  The host anomalies from
  ``analyze`` (G1a/G1b/duplicates/...) plus empty cycle families are
  exactly what the CPU checker would have produced (its searches find
  nothing in an acyclic graph), so the results agree without the work.
- cyclic lane: materialize the realtime layer (if strict mode) and run
  the same ``collect_cycle_anomalies`` suite over the same graph the CPU
  checker uses — identical witnesses, identical labels.
- flags unavailable (device error / engine="cpu"): recovery runs
  unconditionally; the result is the CPU checker's, reached through the
  engine's degradation chain.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import numpy as np

from jepsen_tpu.elle.graph import SearchBudget, edge_list
from jepsen_tpu.elle.list_append import (add_realtime_edges,
                                         collect_cycle_anomalies,
                                         finish_result)
from jepsen_tpu.elle_tpu.closure import FLAG_NAMES
from jepsen_tpu.elle_tpu.encode import EncodedHistory

ANALYZER = "elle-tpu"


def finish_lane(enc: EncodedHistory,
                flags: Optional[np.ndarray],
                realtime: bool,
                consistency_models: Sequence[str],
                budget: Optional[SearchBudget] = None) -> Dict[str, Any]:
    """One lane's result map from its encoding and device flag vector
    (``flags=None`` means "no device verdict — search unconditionally")."""
    a = enc.analysis
    truncated = False
    if flags is None or bool(flags[0]):
        if realtime:
            add_realtime_edges(a.graph, a.oks, a.pairs)
        truncated = collect_cycle_anomalies(a.graph, a.txn_of, a.anomalies,
                                            budget=budget)
    res = finish_result(a.anomalies, consistency_models, a.count,
                        truncated=truncated)
    res["analyzer"] = ANALYZER
    if flags is not None:
        res["device-flags"] = {name: bool(v)
                               for name, v in zip(FLAG_NAMES, flags)}
    # Complete edge list for artifact rendering (popped by
    # elle.render.write_artifacts).  On an acyclic strict-mode lane the
    # dense realtime layer was never materialized host-side — the list
    # then carries the ww/wr/rw core only.
    res["edges-full"] = edge_list(a.graph)
    return res
