"""History -> dense device tensors for the elle_tpu engine.

The encoder is deliberately thin: it runs the *CPU checker's own* host
pass (``elle.list_append.analyze`` / ``elle.rw_register.analyze``) and
merely re-shapes its dependency graph into fixed-kind edge arrays, plus
the invoke/complete index vectors the device needs to rebuild the
realtime order as a broadcast comparison.  Sharing the host pass is the
parity argument's foundation — both tiers literally analyze the same
``Analysis`` object (see the package docstring).

Encoding:

- ``src/dst [3, E] int32`` — per-kind (ww, wr, rw) edge endpoints, padded
  with ``-1``.  The device reconstructs each adjacency layer as
  ``one_hot(src).T @ one_hot(dst)`` (a ``-1`` one-hots to a zero row, so
  padding vanishes); a matmul-based build sidesteps the vmapped
  bool-scatter miscompile documented at parallel/batch.py (the
  MAX_LANES_PER_GROUP cap) entirely.
- ``invoke/complete [N] int32`` — each txn's invocation/completion index
  in the client subhistory.  ``invoke = -1`` marks an unknown invocation
  (no realtime edges *into* that txn, matching the CPU checker's
  ``inv >= 0`` guard); padding rows get ``complete = COMPLETE_PAD`` (no
  realtime edges *out of* them either).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from jepsen_tpu.elle import list_append, rw_register
from jepsen_tpu.elle.list_append import Analysis
from jepsen_tpu.history import History

#: edge-kind layer order of the ``src``/``dst`` arrays.
KINDS = ("ww", "wr", "rw")

WORKLOADS = ("list-append", "rw-register")

#: completion index for padding txn slots: later than any real invocation,
#: so a padded row emits no realtime edge.
COMPLETE_PAD = np.int32(2**30)


@dataclass
class EncodedHistory:
    """One history's device encoding plus the host ``Analysis`` it came
    from (kept for witness recovery — the device only answers booleans)."""
    analysis: Analysis
    workload: str
    src: np.ndarray        # [len(KINDS), E] int32, -1-padded
    dst: np.ndarray        # [len(KINDS), E] int32, -1-padded
    invoke: np.ndarray     # [N] int32, -1 = unknown invocation
    complete: np.ndarray   # [N] int32

    @property
    def n(self) -> int:
        return self.analysis.count

    @property
    def n_edges(self) -> int:
        return int((self.src >= 0).sum())


def analyze(history: History, workload: str = "list-append",
            **workload_kw) -> Analysis:
    """Dispatch to the workload's host pass."""
    if workload == "list-append":
        return list_append.analyze(history, **workload_kw)
    if workload == "rw-register":
        return rw_register.analyze(history, **workload_kw)
    raise ValueError(f"unknown elle workload {workload!r}; "
                     f"known: {WORKLOADS}")


def encode(history: History, workload: str = "list-append",
           **workload_kw) -> EncodedHistory:
    return encode_analysis(analyze(history, workload, **workload_kw),
                           workload)


def encode_analysis(a: Analysis, workload: str) -> EncodedHistory:
    per = {k: ([], []) for k in KINDS}
    for s, bs in a.graph.out.items():
        for d, ks in bs.items():
            for k in ks:
                if k in per:
                    per[k][0].append(s)
                    per[k][1].append(d)
    e = max(1, max(len(per[k][0]) for k in KINDS))
    src = np.full((len(KINDS), e), -1, np.int32)
    dst = np.full((len(KINDS), e), -1, np.int32)
    for i, k in enumerate(KINDS):
        m = len(per[k][0])
        src[i, :m] = per[k][0]
        dst[i, :m] = per[k][1]
    n = a.count
    invoke = np.full(max(1, n), -1, np.int32)
    complete = np.full(max(1, n), COMPLETE_PAD, np.int32)
    for t, (i, _) in enumerate(a.oks):
        complete[t] = i
        inv = int(a.pairs[i])
        invoke[t] = inv if inv >= 0 else -1
    return EncodedHistory(analysis=a, workload=workload, src=src, dst=dst,
                          invoke=invoke, complete=complete)
