"""Per-process clock lies via LD_PRELOAD.

Parity: jepsen.faketime (jepsen/src/jepsen/faketime.clj:8-60): build
libfaketime on the node and generate wrapper scripts that launch a database
binary under a faked clock with a per-run offset and rate.
"""

from __future__ import annotations

import random
from typing import Any, Dict, Optional

from jepsen_tpu.control import Session, session
from jepsen_tpu.control import util as cu

LIB_PATH = "/usr/local/lib/faketime/libfaketime.so.1"


def install(test, node) -> None:
    """Install libfaketime from the distro package (faketime.clj builds a
    fork; the packaged library covers the rate+offset interface we use)."""
    s = session(test, node).sudo()
    if not cu.exists(s, LIB_PATH) and \
            not cu.exists(s, "/usr/lib/x86_64-linux-gnu/faketime/libfaketime.so.1"):
        s.env(DEBIAN_FRONTEND="noninteractive").exec(
            "apt-get", "install", "-y", "libfaketime")


def script(binary: str, offset_s: float, rate: float) -> str:
    """A wrapper script launching ``binary`` under a faked clock
    (faketime.clj:24-60): offset seconds plus a rate multiplier."""
    spec = f"{'+' if offset_s >= 0 else ''}{offset_s}s x{rate}"
    return ("#!/bin/bash\n"
            f"export LD_PRELOAD=\"{LIB_PATH}\"\n"
            f"export FAKETIME=\"{spec}\"\n"
            "export FAKETIME_DONT_FAKE_MONOTONIC=1\n"
            f"exec {binary} \"$@\"\n")


def wrap_binary(test, node, binary: str, wrapper_path: str,
                offset_s: Optional[float] = None,
                rate: Optional[float] = None) -> str:
    """Install a faketime wrapper for ``binary`` at ``wrapper_path`` with a
    random (or given) skew, returning the chosen spec."""
    offset_s = offset_s if offset_s is not None else \
        random.uniform(-60.0, 60.0)
    rate = rate if rate is not None else random.uniform(0.95, 1.05)
    s = session(test, node).sudo()
    cu.write_file(s, script(binary, offset_s, rate), wrapper_path)
    s.exec("chmod", "+x", wrapper_path)
    return f"{offset_s:+.3f}s x{rate:.4f}"
