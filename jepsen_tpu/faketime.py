"""Per-process clock lies via LD_PRELOAD.

Parity: jepsen.faketime (jepsen/src/jepsen/faketime.clj:8-60): build
libfaketime on the node and generate wrapper scripts that launch a database
binary under a faked clock with a per-run offset and rate.
"""

from __future__ import annotations

import random
from typing import Any, Dict, Optional

from jepsen_tpu.control import Session, session
from jepsen_tpu.control import util as cu

LIB_PATH = "/usr/local/lib/faketime/libfaketime.so.1"

#: The pinned fork + tag the reference builds (faketime.clj:8-23): the
#: last release that worked with jemalloc, patched for
#: CLOCK_MONOTONIC_COARSE / CLOCK_REALTIME_COARSE.
PINNED_REPO = "https://github.com/jepsen-io/libfaketime.git"
PINNED_TAG = "0.9.6-jepsen1"
BUILD_DIR = "/tmp/jepsen/libfaketime-jepsen"


def install(test, node) -> None:
    """Install libfaketime from the distro package — the fast path when
    the packaged library's rate+offset interface suffices.  Databases that
    trip the jemalloc/COARSE-clock incompatibilities the reference's fork
    patches need :func:`install_pinned` instead."""
    s = session(test, node).sudo()
    if not cu.exists(s, LIB_PATH) and \
            not cu.exists(s, "/usr/lib/x86_64-linux-gnu/faketime/libfaketime.so.1"):
        s.env(DEBIAN_FRONTEND="noninteractive").exec(
            "apt-get", "install", "-y", "libfaketime")


def install_pinned(test, node, repo: str = PINNED_REPO,
                   tag: str = PINNED_TAG) -> None:
    """Build the pinned libfaketime fork from source on the node
    (faketime.clj:8-23 install-0.9.6-jepsen1!): clone once, check out the
    pinned tag, make, make install.  Idempotent — an existing checkout is
    reused, only the checkout/build re-run."""
    s = session(test, node).sudo()
    s.exec("mkdir", "-p", "/tmp/jepsen")
    if not cu.exists(s, BUILD_DIR):
        s.exec("git", "clone", repo, BUILD_DIR)
    sb = s.cd(BUILD_DIR)
    sb.exec("git", "checkout", tag)
    sb.exec("make")
    sb.exec("make", "install")


def script(binary: str, offset_s: float, rate: float) -> str:
    """A wrapper script launching ``binary`` under a faked clock
    (faketime.clj:24-60): offset seconds plus a rate multiplier."""
    spec = f"{'+' if offset_s >= 0 else ''}{offset_s}s x{rate}"
    return ("#!/bin/bash\n"
            f"export LD_PRELOAD=\"{LIB_PATH}\"\n"
            f"export FAKETIME=\"{spec}\"\n"
            "export FAKETIME_DONT_FAKE_MONOTONIC=1\n"
            f"exec {binary} \"$@\"\n")


def wrap_binary(test, node, binary: str, wrapper_path: str,
                offset_s: Optional[float] = None,
                rate: Optional[float] = None) -> str:
    """Install a faketime wrapper for ``binary`` at ``wrapper_path`` with a
    random (or given) skew, returning the chosen spec."""
    offset_s = offset_s if offset_s is not None else \
        random.uniform(-60.0, 60.0)
    rate = rate if rate is not None else random.uniform(0.95, 1.05)
    s = session(test, node).sudo()
    cu.write_file(s, script(binary, offset_s, rate), wrapper_path)
    s.exec("chmod", "+x", wrapper_path)
    return f"{offset_s:+.3f}s x{rate:.4f}"
