"""The orchestrator: run a complete test end to end.

Parity: jepsen.core/run! (jepsen/src/jepsen/core.clj:322-401), composed of
the same phases with the same durability guarantees:

  prepare -> store.save_0 -> sessions -> OS setup -> DB setup ->
  client+nemesis setup -> interpreter run (history) -> store.save_1 ->
  analysis (checker) -> store.save_2 -> log snarfing -> teardown

Failures during analysis never lose the history (it hit disk in save_1);
a JVM-shutdown-hook's job (core.clj:143-163) is played by try/finally
blocks around log download and teardown.
"""

from __future__ import annotations

import logging
import time
import traceback
from typing import Any, Dict, Optional

from jepsen_tpu import control, db as jdb, nemesis as jnemesis, store
from jepsen_tpu import os as jos
from jepsen_tpu.checker.core import Checker, UNKNOWN, check_safe
from jepsen_tpu.generator import interpreter
from jepsen_tpu.history import History

logger = logging.getLogger("jepsen.core")


def prepare_test(test: Dict[str, Any]) -> Dict[str, Any]:
    """Fill defaults (core.clj:306-320 prepare-test)."""
    test.setdefault("name", "noname")
    test.setdefault("start_time", time.strftime("%Y%m%dT%H%M%S"))
    test.setdefault("nodes", [])
    concurrency = test.get("concurrency", 5)
    if isinstance(concurrency, str) and concurrency.endswith("n"):
        # "3n" syntax: multiple of node count (cli.clj:150-168)
        concurrency = int(concurrency[:-1] or 1) * max(1, len(test["nodes"]))
    test["concurrency"] = int(concurrency)
    return test


def run(test: Dict[str, Any]) -> Dict[str, Any]:
    """Run the test; returns it with :history and :results attached."""
    prepare_test(test)
    store.make_run_dir(test)
    log_handler = store.start_logging(test)
    logger.info("Running test %s", test["name"])
    try:
        store.save_0(test)
        mon = None
        if test.get("monitor"):
            # Online monitor (jepsen_tpu.monitor): taps the interpreter's
            # op stream via test["_monitor"], checks incrementally during
            # the run, and hands analyze() a resumable frontier.
            from jepsen_tpu.monitor import Monitor
            mon = Monitor.from_test(test)
            if mon is not None:
                test["_monitor"] = mon.start()
        has_cluster = bool(test.get("nodes"))
        if has_cluster:
            control.setup_sessions(test)
        try:
            _setup_os(test)
            _setup_db(test)
            try:
                history = _run_case(test)
            finally:
                # A stalled run (interpreter.StalledRun) still leaves its
                # salvaged partial history on disk — partial beats nothing
                # for post-mortem analysis.
                ph = test.get("partial_history")
                if ph is not None and "history" not in test:
                    try:
                        store.save_1(test, ph)
                    except Exception:  # noqa: BLE001
                        logger.exception("saving partial history")
                # Logs must come off the nodes BEFORE teardown wipes them
                # (core.clj:143-163 with-log-snarfing wraps the db phase).
                _snarf_logs_safe(test)
                _teardown_db(test, final=True)
            test["history"] = history
            store.save_1(test, history)
            if mon is not None:
                # Settle the frontier on the tail ops and persist the
                # checkpoint before analysis resumes from it.
                try:
                    mon.finalize()
                except Exception:  # noqa: BLE001
                    logger.exception("monitor finalize; cold analyze")
            results = analyze(test, history)
            test["results"] = results
            store.save_2(test, results)
            _log_results(results)
            return test
        finally:
            if mon is not None:
                mon.close()
            if has_cluster:
                # Failed OS/DB setup never reaches the in-run snarf site;
                # those logs matter most for diagnosis, so snarf here too
                # (idempotent via the _logs_snarfed flag).
                _snarf_logs_safe(test)
                control.teardown_sessions(test)
            _close_resources(test)
    finally:
        store.stop_logging(log_handler)


def _close_resources(test) -> None:
    """Close test-scoped resources (with-resources parity, core.clj:70):
    anything a suite put in test["resources"] — e.g. the localkv proxy
    router's listener sockets/threads — is closed when the run ends,
    best-effort, never masking the run's own outcome."""
    for r in test.get("resources") or []:
        try:
            r.close()
        except Exception:  # noqa: BLE001
            logger.exception("closing test resource %r", r)


def _setup_os(test) -> None:
    osys = test.get("os")
    if osys is None or not test.get("nodes"):
        return
    logger.info("Setting up OS")
    control.on_nodes(test, osys.setup, phase="setup")


def _setup_db(test) -> None:
    database = test.get("db")
    if database is None or not test.get("nodes"):
        return
    logger.info("Setting up DB")

    def cyc(t, node):
        jdb.cycle_(database, t, node)

    control.on_nodes(test, cyc, phase="setup")
    if isinstance(database, jdb.Primary) and test["nodes"]:
        database.setup_primary(test, test["nodes"][0])


def _teardown_db(test, final: bool = False) -> None:
    database = test.get("db")
    if database is None or not test.get("nodes"):
        return
    if test.get("leave_db_running"):
        logger.info("Leaving DB running for inspection")
        return
    logger.info("Tearing down DB")
    control.on_nodes(test, database.teardown, phase="teardown")


def _run_case(test) -> History:
    """Set up nemesis+clients, run the generator, tear down
    (core.clj:176-214 run-case!)."""
    nem = test.get("nemesis") or jnemesis.NoopNemesis()
    test["nemesis"] = nem.setup(test)
    try:
        # Open one client per node and run its setup! (schema creation
        # etc.) before any worker dispatch, as in core.clj:176-207.
        client_proto = test.get("client")
        if client_proto is not None:
            for node in (test.get("nodes") or [None]):
                c = client_proto.open(test, node)
                try:
                    c.setup(test)
                finally:
                    try:
                        c.close(test)
                    except Exception:  # noqa: BLE001
                        logger.exception("client close after setup")
        logger.info("Running workload")
        return interpreter.run(test)
    finally:
        try:
            test["nemesis"].teardown(test)
        except Exception:  # noqa: BLE001
            logger.exception("nemesis teardown")
        finally:
            # The run-level heal guarantee (nemesis/registry.py): even when
            # the generator phase raised, or the nemesis crashed mid-fault
            # before its own teardown could know about the fault, every
            # registered-but-unresolved undo runs here — no run exits with
            # the cluster still partitioned / skewed / SIGSTOPped.
            _heal_outstanding_faults(test)


def _heal_outstanding_faults(test) -> None:
    reg = test.get("fault_registry")
    if reg is None:
        return
    pending = reg.outstanding()
    if not pending:
        return
    logger.warning("healing %d outstanding fault(s) at teardown: %s",
                   len(pending), ", ".join(pending))
    outcomes = reg.heal_all()
    test["healed_faults"] = {**test.get("healed_faults", {}), **outcomes}
    for key, outcome in outcomes.items():
        if outcome != "healed":
            logger.error("fault %s: %s", key, outcome)


def analyze(test, history: History,
            service: Optional[Any] = None) -> Dict[str, Any]:
    """Run the checker over the history (core.clj:216-232 analyze!).

    ``test["checker"]`` may be a Checker instance or any registry spec
    (a name like "elle-list-append", a ``{"name": ..., **opts}`` dict, a
    mapping, or a list — see checker.core.resolve_checker): workload
    configs can name their analysis declaratively.

    With a ``service`` (the argument, or ``test["service"]`` — a
    serve.CheckService), device-tier checkers route through the shared
    batched checking service instead of running a cold one-shot: N
    concurrent runs share one device and one compiled-engine cache.
    Checkers the service cannot batch fall back to the direct path, and
    a service-side crash degrades to the direct path too — routing is an
    optimization, never a verdict risk."""
    logger.info("Analyzing history (%d ops)", len(history))
    checker = test.get("checker")
    if checker is None:
        return {"valid": True, "note": "no checker configured"}
    if not isinstance(checker, Checker):
        from jepsen_tpu.checker.core import resolve_checker
        checker = resolve_checker(checker)
    opts = {"store_dir": test.get("store_dir")}
    mon = test.get("_monitor")
    if mon is not None:
        # A monitored run resumes the authoritative check from the last
        # monitor epoch (monitor/resume.py): None = soundness doubt, run
        # the cold path below.  A resume crash is likewise just a cold
        # analyze — resumption is an optimization, never a verdict risk.
        from jepsen_tpu.monitor import resume as _mon_resume
        try:
            resumed = _mon_resume.resume_final_check(test, checker, history,
                                                     mon, opts)
        except Exception:  # noqa: BLE001
            logger.exception("monitor resume failed; cold analyze")
            resumed = None
        if resumed is not None:
            logger.info("analysis resumed from monitor epoch %s "
                        "(%s tail op(s) re-checked)",
                        resumed.get("resumed-from-epoch"),
                        resumed.get("tail-ops"))
            if resumed.get("valid") is False:
                _failure_artifacts(test, history)
            return resumed
    service = service if service is not None else test.get("service")
    if service is not None:
        try:
            routed = service.try_route_analyze(test, checker, history, opts)
        except Exception:  # noqa: BLE001
            logger.exception("service routing failed; using direct path")
            routed = None
        if routed is not None:
            if routed.get("valid") is False:
                _failure_artifacts(test, history)
            return routed
    results = check_safe(checker, test, history, opts)
    if results.get("valid") is False:
        _failure_artifacts(test, history)
    return results


def _failure_artifacts(test, history: History) -> None:
    """A failing run always gets human-inspectable artifacts — timeline and
    perf plots — even when the test composed no Timeline/Perf checker
    (checker.clj:207-211 renders on invalid analyses).  Best-effort; never
    masks the verdict."""
    d = test.get("store_dir")
    if not d:
        return
    import os as _os
    try:
        if not _os.path.exists(_os.path.join(d, "timeline.html")):
            from jepsen_tpu.checker.timeline import Timeline
            Timeline().check(test, history, {"store_dir": d})
        if not _os.path.exists(_os.path.join(d, "latency-raw.png")):
            from jepsen_tpu.checker.perf import Perf
            Perf().check(test, history, {"store_dir": d})
    except Exception:  # noqa: BLE001
        logger.exception("failure-artifact rendering")


def _snarf_logs_safe(test) -> None:
    """Snarf at most once per run, never raising (shutdown-hook spirit of
    core.clj:143-163: log download must not mask the real failure)."""
    if test.get("_logs_snarfed"):
        return
    try:
        _snarf_logs(test)
        test["_logs_snarfed"] = True
    except Exception:  # noqa: BLE001
        logger.exception("downloading node logs")


def _snarf_logs(test) -> None:
    """Download db log files into the store dir (core.clj:102-129)."""
    database = test.get("db")
    if not isinstance(database, jdb.LogFiles):
        return
    import os as _os

    def snarf(t, node):
        s = control.session(t, node)
        dest = _os.path.join(t["store_dir"], node)
        _os.makedirs(dest, exist_ok=True)
        for path in database.log_files(t, node):
            try:
                s.download(path, dest)
            except Exception:  # noqa: BLE001
                logger.warning("couldn't download %s from %s", path, node)

    control.on_nodes(test, snarf)


def _log_results(results: Dict[str, Any]) -> None:
    v = results.get("valid")
    if v is True:
        logger.info("Everything looks good! (⌐■_■)")
    elif v == UNKNOWN:
        logger.warning("Errors occurred during analysis; verdict unknown")
        for where, tb in iter_analysis_errors(results):
            logger.warning("analysis error in %s:\n%s", "/".join(where), tb)
    else:
        logger.error("Analysis invalid! (ﾉಥ益ಥ）ﾉ ┻━┻")


def iter_analysis_errors(results: Any, path=()):
    """Yield ``(path, reason)`` for every unknown-with-a-reason anywhere in
    a (possibly nested — compose / independent) result map: crashed
    checkers contribute their traceback, non-crash unknowns (capacity
    ceilings, never-succeeded ops, cancellations) their ``error`` string."""
    if not isinstance(results, dict):
        return
    if results.get("valid") == UNKNOWN:
        if "traceback" in results:
            yield path, results["traceback"]
        elif "error" in results:
            yield path, str(results["error"])
        elif results.get("cancelled"):
            yield path, "cancelled (competition loser)"
    for k, value in results.items():
        if isinstance(value, dict):
            yield from iter_analysis_errors(value, path + (str(k),))


def run_tests(tests, raise_on_failure: bool = False, workers: int = 1,
              service: Optional[Any] = None):
    """Run a sequence of tests, collecting verdicts (cli.clj:433-519
    test-all).

    ``service`` (a serve.CheckService) is injected into every test map so
    each run's analysis phase routes through one shared batched checking
    service; with ``workers > 1`` the campaign's runs execute
    concurrently and their checks batch onto the device together —
    N concurrent runs, one device.  Results keep the input order."""
    tests = list(tests)
    if service is not None:
        for t in tests:
            t.setdefault("service", service)

    def one(t):
        try:
            done = run(t)
            return {"name": done.get("name"),
                    "dir": done.get("store_dir"),
                    "valid": done.get("results", {}).get("valid")}
        except Exception as e:  # noqa: BLE001
            logger.error("test crashed: %s", e)
            return {"name": t.get("name"), "valid": UNKNOWN,
                    "error": traceback.format_exc()}

    if workers > 1 and len(tests) > 1:
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(max_workers=workers,
                                thread_name_prefix="campaign") as ex:
            results = list(ex.map(one, tests))
    else:
        results = [one(t) for t in tests]
    n_bad = sum(1 for r in results if r["valid"] is False)
    n_unknown = sum(1 for r in results if r["valid"] == UNKNOWN)
    summary = {"results": results, "failures": n_bad, "unknown": n_unknown,
               "exit": 2 if n_unknown and not n_bad else (1 if n_bad else 0)}
    if raise_on_failure and summary["exit"]:
        raise RuntimeError(f"{n_bad} failures, {n_unknown} unknown")
    return summary
