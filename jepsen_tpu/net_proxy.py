"""Socket-level partitions: a framework-owned TCP proxy per node pair.

The reference's partitioner rewires iptables on real cluster nodes
(jepsen/src/jepsen/nemesis.clj:158-285, net.clj:176-186).  In environments
with no root/netfilter (one-host real-process suites like localkv), the
same *grudge* semantics — ``{dst: [srcs dst refuses to hear from]}`` — are
enforced one layer up the stack: every inter-node link dials through a
:class:`PairProxy` owned by the harness, and severing a link closes its
live TCP connections (peers see a real RST/EOF mid-flight, exactly what a
dropped link looks like to an application) and refuses new ones.

Usage: build a :class:`ProxyRouter` over the node roster before DB setup,
point each node's peer-address config at ``router.addr(src, dst)``, put
``test["net"] = ProxyNet(router)`` in the test map, and the stock
:class:`~jepsen_tpu.nemesis.partition.Partitioner` (and so the whole
``nemesis/combined.py`` partition package and its grudge algebra —
halves/one/majorities-ring) drives it unchanged.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from jepsen_tpu.clock import mono_now
from jepsen_tpu.net import Net


class PairProxy:
    """One direction of one link: listens on a stable port, forwards byte
    streams to ``target``.  ``sever()`` kills live connections (RST) and
    CLOSES the listener, so new dials get ECONNREFUSED — a *definite*
    failure the client can classify as :fail, like iptables REJECT.  (An
    accept-then-close sever was tried first: it turns every op during a
    partition into an indeterminate :info ghost, which is both a worse
    model of a cut link and an unbounded load on the linearizability
    checker's pending window.)  ``heal()`` re-binds the same port.

    Beyond partitions, the link also shapes and tears traffic for the
    serve-tier self-nemesis (serve/chaos.py): ``delay_s`` stalls every
    forwarded chunk (netem-delay on the wire itself), ``reset_conns()``
    RSTs live connections without touching the listener (a frame in
    flight is torn mid-stream; the very next dial succeeds), and
    ``retarget()`` repoints the upstream address so a respawned worker
    process keeps its slot's stable proxy port."""

    def __init__(self, src: str, dst: str, target: Tuple[str, int]):
        self.src, self.dst = src, dst
        self.target = target
        self.severed = False
        #: per-chunk forwarding stall (seconds); 0 = unshaped
        self.delay_s = 0.0
        self._lock = threading.Lock()
        self._conns: List[socket.socket] = []
        srv = socket.socket()
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", 0))
        self.port = srv.getsockname()[1]
        self._srv: Optional[socket.socket] = None
        self._placeholder: Optional[socket.socket] = None
        with self._lock:
            self._listen(srv)

    def _listen(self, srv: socket.socket) -> None:
        """Start listening on an already-bound socket.  Holds the lock."""
        srv.listen(64)
        self._srv = srv
        threading.Thread(target=self._accept_loop, args=(srv,), daemon=True,
                         name=f"proxy-{self.src}->{self.dst}").start()

    def _bind_reserved(self) -> socket.socket:
        """A socket bound to our port but NOT listening: dials get
        ECONNREFUSED, and nothing else (e.g. an ephemeral outbound socket —
        observed in practice) can claim the port while the link is down."""
        last: Optional[OSError] = None
        for _ in range(200):
            s = socket.socket()
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            try:
                s.bind(("127.0.0.1", self.port))
                return s
            except OSError as e:  # lost the close->rebind race; retry
                last = e
                s.close()
                time.sleep(0.01)
        raise last  # type: ignore[misc]

    # -- control -----------------------------------------------------------

    def sever(self) -> None:
        with self._lock:
            if self.severed:
                return
            self.severed = True
            conns, self._conns = self._conns, []
            srv, self._srv = self._srv, None
        if srv is not None:
            try:
                # shutdown BEFORE close: close() alone does not interrupt a
                # thread blocked in accept(), and the in-flight syscall
                # keeps the kernel socket (and the port, and the accepting
                # loop!) alive — the link would never actually sever under
                # steady traffic.  shutdown wakes the accept with an error.
                srv.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                srv.close()  # new dials now get ECONNREFUSED
            except OSError:
                pass
        ph = self._bind_reserved()
        with self._lock:
            self._placeholder = ph
        for c in conns:
            try:
                # RST rather than FIN: a partitioned peer mid-request sees
                # a hard failure, not a graceful close
                c.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                             b"\x01\x00\x00\x00\x00\x00\x00\x00")
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass

    def heal(self, rebind_timeout_s: float = 5.0) -> None:
        """Reopen the link on the same port.  The fast path listens on
        the placeholder socket reserved at sever time (no unbind window);
        if that socket is gone or the OS refuses it, fall back to
        re-binding the port under bounded exponential backoff — the
        kernel may not have released the old listener yet (close() is
        asynchronous with respect to the port actually freeing), and a
        heal that gives up on the first EADDRINUSE leaves the partition
        permanent.  Raises the last OSError only after
        ``rebind_timeout_s`` of retries."""
        with self._lock:
            if not self.severed:
                return
            self.severed = False
            ph, self._placeholder = self._placeholder, None
        if ph is not None:
            try:
                with self._lock:
                    self._listen(ph)
                return
            except OSError:
                try:
                    ph.close()
                except OSError:
                    pass
        srv = self._rebind_with_backoff(rebind_timeout_s)
        with self._lock:
            if self.severed:
                # a sever raced the heal: the link stays down, and the
                # fresh socket becomes the sever's placeholder
                self._placeholder = srv
                return
            self._listen(srv)

    def _rebind_with_backoff(self, timeout_s: float) -> socket.socket:
        """Bind a fresh socket to our stable port, retrying while the OS
        still holds the old listener; raises the last error at timeout."""
        deadline = mono_now() + max(0.0, timeout_s)
        delay = 0.005
        while True:
            s = socket.socket()
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            try:
                s.bind(("127.0.0.1", self.port))
                return s
            except OSError:
                s.close()
                left = deadline - mono_now()
                if left <= 0:
                    with self._lock:
                        self.severed = True  # heal failed: link stays down
                    raise
                time.sleep(min(delay, left))
                delay = min(0.1, delay * 2)

    def retarget(self, target: Tuple[str, int]) -> None:
        """Repoint the upstream address (each proxied connection reads it
        at dial time): a respawned worker process lands on a new ephemeral
        port, but its slot's proxy port — what the fleet dials — is
        stable across the restart."""
        with self._lock:
            self.target = target

    def reset_conns(self) -> int:
        """Mid-frame cut: RST every live proxied connection, listener
        untouched — a frame in flight is torn mid-stream (both peers see
        a hard reset, not EOF at a frame boundary), while the very next
        dial succeeds.  Returns the number of link connections cut."""
        with self._lock:
            conns, self._conns = self._conns, []
        for c in conns:
            try:
                c.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                             b"\x01\x00\x00\x00\x00\x00\x00\x00")
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        return len(conns) // 2  # client+upstream pair per proxied conn

    def close(self) -> None:
        self.sever()
        with self._lock:
            ph, self._placeholder = self._placeholder, None
        if ph is not None:
            try:
                ph.close()
            except OSError:
                pass

    # -- data path ---------------------------------------------------------

    def _accept_loop(self, srv: socket.socket) -> None:
        while True:
            try:
                client, _ = srv.accept()
            except OSError:
                return  # listener closed (sever or shutdown)
            with self._lock:
                stale = self._srv is not srv
            if stale:
                # a sever raced our accept: this connection crossed a cut
                # link — reset it and stop serving this listener generation
                try:
                    client.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                                      b"\x01\x00\x00\x00\x00\x00\x00\x00")
                    client.close()
                except OSError:
                    pass
                return
            threading.Thread(target=self._pump_pair, args=(client,),
                             daemon=True).start()

    def _pump_pair(self, client: socket.socket) -> None:
        with self._lock:
            target = self.target
        try:
            upstream = socket.create_connection(target, timeout=2)
        except OSError:
            try:
                client.close()
            except OSError:
                pass
            return
        with self._lock:
            if self.severed:
                for s in (client, upstream):
                    try:
                        s.close()
                    except OSError:
                        pass
                return
            self._conns += [client, upstream]
        threading.Thread(target=self._pump, args=(client, upstream),
                         daemon=True).start()
        threading.Thread(target=self._pump, args=(upstream, client),
                         daemon=True).start()

    def _pump(self, a: socket.socket, b: socket.socket) -> None:
        try:
            while True:
                data = a.recv(65536)
                if not data:
                    break
                d = self.delay_s
                if d > 0:
                    time.sleep(d)  # slow-link shaping (chaos slow_link)
                b.sendall(data)
        except OSError:
            pass
        for s in (a, b):
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass


class ProxyRouter:
    """All directed (src, dst) proxies for a node roster.  ``addr(src,
    dst)`` is the address ``src``'s process must dial to reach ``dst``."""

    def __init__(self, nodes: Sequence[str],
                 real_ports: Dict[str, int]):
        self.nodes = list(nodes)
        self.proxies: Dict[Tuple[str, str], PairProxy] = {}
        for src in nodes:
            for dst in nodes:
                if src != dst:
                    self.proxies[(src, dst)] = PairProxy(
                        src, dst, ("127.0.0.1", real_ports[dst]))

    def addr(self, src: str, dst: str) -> Tuple[str, int]:
        p = self.proxies[(src, dst)]
        return ("127.0.0.1", p.port)

    def sever(self, src: str, dst: str) -> None:
        """Cut traffic src -> dst (and dst's replies on that link die with
        the connection)."""
        self.proxies[(src, dst)].sever()

    def heal_all(self) -> None:
        for p in self.proxies.values():
            p.heal()

    def close(self) -> None:
        for p in self.proxies.values():
            p.close()


class ProxyNet(Net):
    """Net implementation over a :class:`ProxyRouter` — same grudge
    semantics as the iptables net (``drop(src, dst)`` = dst stops hearing
    from src), so every stock partition nemesis works against
    real-process single-host suites."""

    def __init__(self, router: ProxyRouter):
        self.router = router

    def drop(self, test, src: str, dst: str) -> None:
        self.router.sever(src, dst)

    def heal(self, test) -> None:
        self.router.heal_all()

    # Packet shaping is not meaningfully emulatable at the stream layer;
    # the tc-netem net covers it on real clusters.
    def slow(self, test, opts=None):
        raise NotImplementedError("proxy net does not shape traffic")

    def flaky(self, test):
        raise NotImplementedError("proxy net does not shape traffic")

    def fast(self, test):
        pass  # nothing shaped, nothing to undo

    def shape(self, test, nodes=None, behavior=None):
        raise NotImplementedError("proxy net does not shape traffic")
