"""The AST tier driver: parse each in-scope module once, run every
applicable rule, honor pragmas.

Rules live in :mod:`jepsen_tpu.lint.rules` (one invariant per module);
this driver only handles file discovery, parsing, and suppression.  A
file that fails to parse yields a ``PARSE`` finding rather than crashing
the analyzer — a syntax error must fail lint, not hide it.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Tuple

from jepsen_tpu.lint.findings import Finding, apply_pragmas
from jepsen_tpu.lint.rules import all_rules, in_scope


def repo_root() -> str:
    """The directory containing the ``jepsen_tpu`` package."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


#: top-level trees the AST tier discovers; a rule's SCOPE then narrows
#: per rule.  suites/ carries real threaded client/runner code (the
#: localkv/chronos/mongodb suites), so its concurrency invariants are
#: audited like the package's own.
_SCAN_TREES = ("jepsen_tpu", "suites")


def _iter_py_files(root: str) -> List[str]:
    out = []
    for tree in _SCAN_TREES:
        pkg = os.path.join(root, tree)
        if not os.path.isdir(pkg):
            continue
        for dirpath, dirnames, filenames in os.walk(pkg):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    rel = os.path.relpath(os.path.join(dirpath, fn), root)
                    out.append(rel.replace(os.sep, "/"))
    return out


def run_ast_tier(root: Optional[str] = None,
                 files: Optional[Dict[str, str]] = None,
                 ) -> Tuple[List[Finding], Dict[str, List[str]]]:
    """Run every AST rule over its scope.

    ``files`` (repo-relative path -> source text) overrides disk
    discovery — the test suite uses it to lint fixture sources under
    paths inside each rule's scope.  Returns (post-pragma findings,
    {path: source lines}).
    """
    root = root or repo_root()
    rules = all_rules()
    if files is None:
        files = {}
        for rel in _iter_py_files(root):
            if any(in_scope(rel, r.SCOPE) for r in rules):
                with open(os.path.join(root, rel)) as f:
                    files[rel] = f.read()

    findings: List[Finding] = []
    sources: Dict[str, List[str]] = {}
    for rel in sorted(files):
        src = files[rel]
        lines = src.splitlines()
        sources[rel] = lines
        try:
            tree = ast.parse(src, filename=rel)
        except SyntaxError as e:
            findings.append(Finding(
                "PARSE", rel, e.lineno or 0,
                f"file does not parse: {e.msg}",
                hint="lint requires parseable sources"))
            continue
        for rule in rules:
            if in_scope(rel, rule.SCOPE):
                findings.extend(rule.check(tree, lines, rel))
    return apply_pragmas(findings, sources), sources
