"""Soundness & device-discipline static analysis for jepsen_tpu.

Three tiers prove at CI time the invariants the rest of the stack merely
promises in docstrings (rule catalog: docs/static_analysis.md):

- the **AST tier** (:mod:`.ast_lint` + :mod:`.rules`) — SOUND01 (verdicts
  never flip valid -> false without a witness), DEV01 (no host syncs or
  data-dependent Python in jit-traced engine code), SHAPE01 (serve/
  engine-entry shapes derive from the bucket ladder), CONC01 (monotonic
  clock, lock-order manifest, no blocking I/O under a lock);
- the **interprocedural tier** (:mod:`.interp_lint` + :mod:`.callgraph`)
  — CONC02 (lock-chain inversions across function boundaries, manifest
  drift), SEC01 (the fleet token never reaches any artifact), DL01
  (deadlines cross processes only as remaining budget);
- the **trace tier** (:mod:`.jaxpr_lint`) — traces the real engines with
  ``jax.make_jaxpr`` and proves no callback/transfer primitives survive
  jit (TRACE01) and the compiled-signature universe equals the bucket
  ladder (TRACE02).

Escape valves: inline ``# lint: disable=RULE(reason)`` pragmas and the
committed ledger ``jepsen_tpu/lint/baseline.json`` (see
:mod:`.findings`).  Entry point: ``scripts/lint.py``.
"""

from __future__ import annotations

from typing import List, Optional

from jepsen_tpu.lint.ast_lint import run_ast_tier
from jepsen_tpu.lint.findings import (Baseline, Finding,  # noqa: F401
                                      apply_pragmas, to_sarif)


def run_all(root: Optional[str] = None, trace: bool = True,
            interp: bool = True,
            baseline: Optional[Baseline] = None) -> List[Finding]:
    """All tiers; findings come back with ``baselined`` marked."""
    findings, _ = run_ast_tier(root)
    if interp:
        from jepsen_tpu.lint.interp_lint import run_interp_tier
        interp_findings, _ = run_interp_tier(root)
        findings.extend(interp_findings)
    if trace:
        from jepsen_tpu.lint.jaxpr_lint import run_trace_tier
        findings.extend(run_trace_tier())
    baseline = baseline if baseline is not None else Baseline.load()
    return baseline.mark(findings)
