"""The trace tier: lint the engines' *jaxprs*, not their source.

The AST tier proves properties of the code we wrote; this tier proves
properties of what XLA will actually compile.  Two checks:

- **TRACE01 — no host round-trips in the compiled body.**  Each device
  engine is traced with :func:`jax.make_jaxpr` over representative
  bucket shapes and the resulting jaxpr (recursively, through
  pjit/scan/cond sub-jaxprs) must contain no callback or infeed/outfeed
  primitive.  A ``pure_callback`` smuggled into an engine by a future
  refactor survives jit — it just makes every dispatch block on the
  host — so source review alone cannot guarantee its absence.

- **TRACE02 — the compiled-signature universe equals the bucket
  ladder.**  For a synthetic spread of workload shapes (events, widths,
  lane counts) the derived engine entry signature (window, capacity,
  chunk, lane pad) must collapse to exactly the bucket ladder's image:
  ``|signatures| <= |buckets|``.  A raw shape leaking into any
  signature component makes the signature set grow with the sample set,
  which is precisely the unbounded-compile-cache failure SHAPE01 guards
  at the call-site level — this check proves it end-to-end through the
  real derivation functions.

Tracing is backend-independent (``make_jaxpr`` never compiles), so the
tier runs fine under ``JAX_PLATFORMS=cpu`` in CI.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, List, Sequence, Tuple

from jepsen_tpu.lint.findings import Finding

RULE_CALLBACK = "TRACE01"
RULE_LADDER = "TRACE02"

#: primitives that force a device<->host transition inside compiled code.
BANNED_PRIMITIVES = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "outside_call", "infeed", "outfeed",
})

#: synthetic workload spread: (n_events, width/concurrency, lanes).
#: Deliberately off-bucket values — the point is that messy real-world
#: shapes collapse onto the ladder.
DEFAULT_SAMPLES: Tuple[Tuple[int, int, int], ...] = (
    (5, 1, 1), (63, 2, 2), (64, 2, 3), (65, 3, 4), (100, 5, 7),
    (128, 8, 8), (129, 9, 17), (300, 11, 64), (511, 16, 100),
    (1000, 24, 200), (4097, 33, 513),
)


# -- jaxpr walking -----------------------------------------------------------

def _sub_jaxprs(value: Any) -> Iterator[Any]:
    """Jaxprs nested inside one eqn-params value (ClosedJaxpr, Jaxpr, or
    lists/tuples of either)."""
    if hasattr(value, "jaxpr"):               # ClosedJaxpr
        value = value.jaxpr
    if hasattr(value, "eqns"):
        yield value
    elif isinstance(value, (list, tuple)):
        for v in value:
            yield from _sub_jaxprs(v)


def iter_eqns(jaxpr: Any) -> Iterator[Any]:
    """Every equation in ``jaxpr``, recursing through sub-jaxprs (pjit
    bodies, scan/while/cond branches)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                yield from iter_eqns(sub)


def check_jaxpr_clean(fn: Callable, args: Sequence[Any], label: str,
                      path: str = "<trace>") -> List[Finding]:
    """Trace ``fn(*args)`` and report every banned primitive in the
    resulting jaxpr.  A trace *failure* is itself a finding: an engine
    that no longer traces cannot ship."""
    import jax
    try:
        closed = jax.make_jaxpr(fn)(*args)
    except Exception as e:  # noqa: BLE001 — any trace error is a finding
        return [Finding(
            RULE_CALLBACK, path, 0,
            f"engine '{label}' failed to trace: {type(e).__name__}: {e}",
            hint="the engine must stay traceable with make_jaxpr; see "
                 "docs/static_analysis.md#trace-tier")]
    out = []
    for eqn in iter_eqns(closed.jaxpr):
        name = eqn.primitive.name
        if name in BANNED_PRIMITIVES:
            out.append(Finding(
                RULE_CALLBACK, path, 0,
                f"banned primitive '{name}' in traced engine '{label}': "
                f"a host round-trip inside compiled code",
                hint="engines must be pure device code; hoist the host "
                     "interaction into the chunk driver"))
    return out


# -- the engines we trace ----------------------------------------------------

def trace_engine_findings() -> List[Finding]:
    """Trace the real device engines over representative bucket shapes."""
    import jax.numpy as jnp

    from jepsen_tpu.checker.wgl_tpu import make_engine
    from jepsen_tpu.elle_tpu.closure import lane_flags_fn
    from jepsen_tpu.models import get_model

    findings: List[Finding] = []
    model = get_model("cas-register")

    for single_round in (False, True):
        carry0, _, run_chunk = make_engine(
            model, window=8, capacity=64, gwords=1,
            single_round_closure=single_round)
        label = ("wgl-batch[single-round]" if single_round
                 else "wgl[multi-round]")
        events = jnp.zeros((64, 10), jnp.int32)
        findings.extend(check_jaxpr_clean(
            run_chunk, (carry0(), events), label,
            path="jepsen_tpu/checker/wgl_tpu.py"))

    for n_pad, realtime in ((32, False), (32, True), (64, False)):
        fn = lane_flags_fn(n_pad, realtime)
        b, e = 2, 64
        args = (jnp.zeros((b, 3, e), jnp.int32),
                jnp.zeros((b, 3, e), jnp.int32),
                jnp.zeros((b, n_pad), jnp.int32),
                jnp.zeros((b, n_pad), jnp.int32))
        findings.extend(check_jaxpr_clean(
            fn, args, f"elle-lane[n={n_pad},rt={realtime}]",
            path="jepsen_tpu/elle_tpu/closure.py"))

    # The engine-plugin kernels (queue/set/txn-register) ride the same
    # make_engine body, but their step/encode closures are new device
    # code: trace each through the engine so a host round-trip in a
    # kernel is caught exactly like one in the engine itself.
    for name, kw in (("fifo-queue", {"slots": 8}), ("set", {}),
                     ("txn-register", {})):
        m = get_model(name, **kw)
        carry0, _, run_chunk = make_engine(m, window=8, capacity=64,
                                           gwords=1)
        events = jnp.zeros((64, 10), jnp.int32)
        findings.extend(check_jaxpr_clean(
            run_chunk, (carry0(), events), f"wgl[{name}]",
            path="jepsen_tpu/models/collections.py"))
    return findings


# -- ladder/signature stability ----------------------------------------------

def signature_stability_findings(
        samples: Iterable[Any],
        derive_signature: Callable[[Any], Tuple],
        derive_bucket: Callable[[Any], Tuple],
        label: str, path: str = "<ladder>") -> List[Finding]:
    """|signatures over samples| must not exceed |buckets over samples|:
    every signature component is a pure function of the bucket, so a
    larger signature set means a raw shape leaked into the derivation."""
    samples = list(samples)
    sigs = {derive_signature(s) for s in samples}
    buckets = {derive_bucket(s) for s in samples}
    if len(sigs) > len(buckets):
        return [Finding(
            RULE_LADDER, path, 0,
            f"{label}: {len(sigs)} distinct compiled signatures from "
            f"{len(buckets)} buckets over {len(samples)} sample shapes "
            f"— a raw shape is leaking into the engine signature",
            hint="every signature component must be derived from the "
                 "bucket (serve/buckets.py), never from the history")]
    return []


def ladder_findings(samples: Sequence[Tuple[int, int, int]] =
                    DEFAULT_SAMPLES) -> List[Finding]:
    """Check the real serve-path derivations against the ladder."""
    from jepsen_tpu.checker.wgl_tpu import _round_window
    from jepsen_tpu.engine.ladder import mega_chunk, state_capacity
    from jepsen_tpu.serve import buckets

    findings = []

    def wgl_bucket(s):
        e, w, l = s
        # the numeric ladder under buckets.events_bucket/width_bucket
        return (buckets.pow2_at_least(e, buckets.MIN_EVENTS_BUCKET),
                buckets.pow2_at_least(w, buckets.MIN_WIDTH_BUCKET),
                buckets.lane_bucket(l))

    def wgl_signature(s):
        eb, wb, lb = wgl_bucket(s)
        # exactly what scheduler._dispatch_wgl hands the batch engine
        # (register family: state width 1, the ladder's base rung)
        return (_round_window(wb), buckets.wgl_start_capacity(eb, wb),
                mega_chunk(lb, eb, 1), lb)

    findings.extend(signature_stability_findings(
        samples, wgl_signature, wgl_bucket, "wgl serve path",
        path="jepsen_tpu/serve/scheduler.py"))

    def elle_bucket(s):
        return (buckets.pow2_at_least(max(1, s[0]), buckets.MIN_N_BUCKET),)

    def elle_signature(s):
        n = s[0]
        # graphs.pack_group pads txn count to max(raw 32-multiple, floor);
        # the bucket floor must dominate or the signature tracks raw n.
        raw = max(32, -(-n // 32) * 32)
        return (max(raw, elle_bucket(s)[0]),)

    findings.extend(signature_stability_findings(
        samples, elle_signature, elle_bucket, "elle serve path",
        path="jepsen_tpu/serve/scheduler.py"))

    # The queue plugin's per-history model sizing is an engine-cache key
    # component (JaxModel.variant): run the REAL derivation over synthetic
    # enqueue streams and require it to collapse onto the pow2 ladder.
    from jepsen_tpu.engine.model_plugin import derive_queue_slots
    from jepsen_tpu.history import History, Op

    def _enq_history(n: int) -> History:
        ops = []
        for i in range(n):
            ops.append(Op(process=0, type="invoke", f="enqueue",
                          value=i, index=2 * i))
            ops.append(Op(process=0, type="ok", f="enqueue",
                          value=i, index=2 * i + 1))
        return History(ops)

    def queue_bucket(s):
        return (buckets.pow2_at_least(max(1, s[0]), 8),)

    def queue_signature(s):
        return (derive_queue_slots(_enq_history(s[0]), {})["slots"],)

    findings.extend(signature_stability_findings(
        samples, queue_signature, queue_bucket, "queue plugin slots",
        path="jepsen_tpu/engine/model_plugin.py"))

    # The megabatch state-width ladder: a plugin model's packed state
    # width (queue ring = 2 + derived slots here — the widest, messiest
    # real derivation) feeds the chunk and start-capacity components of
    # the "megav" engine-cache key.  Run the REAL ladder derivations
    # over the raw widths and require the signature to collapse onto
    # the (events, window, lanes, state-width) bucket tuple — a raw
    # ring width leaking into chunk or capacity recompiles per queue
    # size.
    def _queue_state_width(s) -> int:
        return 2 + derive_queue_slots(_enq_history(max(1, s[1])), {})["slots"]

    def state_bucket(s):
        e, w, l = s
        return (buckets.pow2_at_least(e, buckets.MIN_EVENTS_BUCKET),
                buckets.pow2_at_least(max(8, w), buckets.MIN_WIDTH_BUCKET),
                buckets.mega_lane_bucket(l),
                buckets.state_width_bucket(_queue_state_width(s)))

    def state_signature(s):
        eb, wb, lb, _ = state_bucket(s)
        raw_width = _queue_state_width(s)
        return (mega_chunk(lb, eb, raw_width),
                state_capacity(eb, wb, raw_width),
                buckets.state_width_bucket(raw_width))

    findings.extend(signature_stability_findings(
        samples, state_signature, state_bucket, "megabatch state-width",
        path="jepsen_tpu/parallel/megabatch.py"))

    # The fission sub-dispatch floors (batch window_floor / megabatch
    # ev_floor, plus the lane bucket) are engine-cache key components
    # for every post-split dispatch: run the REAL floor derivation over
    # synthetic sub-problem swarms of messy raw shapes and require the
    # resulting (window, events, lanes) triple to collapse onto the
    # ladder — a raw sub-history shape leaking into a floor recompiles
    # per split.
    from jepsen_tpu.engine.fission import subproblem_floors

    def _sub_history(n_events: int, width: int) -> History:
        w = max(1, width)
        ops = [Op(process=p, type="invoke", f="enqueue", value=p,
                  index=p) for p in range(w)]
        ops += [Op(process=p, type="ok", f="enqueue", value=p,
                   index=w + p) for p in range(w)]
        i = len(ops)
        while len(ops) < n_events:
            ops.append(Op(process=0,
                          type="invoke" if i % 2 == 0 else "ok",
                          f="enqueue", value=i, index=i))
            i += 1
        return History(ops)

    def fission_bucket(s):
        e, w, l = s
        return (buckets.pow2_at_least(max(1, e), buckets.MIN_EVENTS_BUCKET),
                buckets.pow2_at_least(max(1, w), buckets.MIN_WIDTH_BUCKET),
                buckets.mega_lane_bucket(l))

    def fission_signature(s):
        e, w, l = s
        subs = [_sub_history(e, w)] * min(3, max(1, l))
        return subproblem_floors(subs)[::-1] + (buckets.mega_lane_bucket(l),)

    findings.extend(signature_stability_findings(
        samples, fission_signature, fission_bucket, "fission sub-dispatch",
        path="jepsen_tpu/engine/fission.py"))

    # The streaming monitor's epoch dispatch (engine/stream.py): the
    # (window, capacity, epoch-events) rung triple is the shape cut of
    # the "streamv" engine-cache key.  Run the REAL rung derivation over
    # raw (new-op count, concurrency) samples and require it to collapse
    # onto the (width-bucket, epoch-events-bucket) image — a raw
    # per-epoch op count leaking into the chunk shape recompiles every
    # epoch, which is exactly the steady-state-zero-recompiles property
    # the stream smoke asserts end-to-end.
    from jepsen_tpu.engine.stream import stream_engine_rungs

    def stream_bucket(s):
        e, w, _ = s
        return (buckets.pow2_at_least(max(1, w), buckets.MIN_WIDTH_BUCKET),
                buckets.epoch_events_bucket(e))

    def stream_signature(s):
        e, w, _ = s
        return stream_engine_rungs(w, e)

    findings.extend(signature_stability_findings(
        samples, stream_signature, stream_bucket, "stream epoch dispatch",
        path="jepsen_tpu/engine/stream.py"))
    return findings


def run_trace_tier(trace_device: bool = True) -> List[Finding]:
    findings = ladder_findings()
    if trace_device:
        findings.extend(trace_engine_findings())
    return findings
