"""The declared lock-acquisition order for the threaded subsystems.

serve/ and monitor/ are the two places where several threads (submitters,
the scheduler device loop, the monitor flusher, web handlers) share
state.  Deadlock freedom there rests on a total order: a thread holding
lock L may only acquire locks strictly *later* in this manifest.  The
CONC01 rule enforces the order syntactically — any ``with`` acquiring a
declared lock lexically inside a ``with`` holding a later-or-equal one
is a finding — so a PR that introduces an inversion fails CI instead of
deadlocking a service under load.

Each entry is ``(name, [(path_regex, expr_regex), ...])``: a ``with``
item matches the entry when its file path matches ``path_regex`` and the
unparsed context expression matches ``expr_regex``.  Level = position in
the tuple (earlier = outermost-permitted).

The declared order mirrors the call graph today:

    fleet-supervisor -> autoscale -> fleet -> fleet-registry
      -> fleet-slot
      -> fleet-journal-write -> fleet-journal-pending
      -> transport-ready -> transport-state -> transport-send
      -> procworker-state -> procworker-send
      -> service -> scheduler -> request -> metrics -> tenants
    router (leaf: breaker/health state, never wraps another lock)
    monitor-flush -> monitor-registry -> verdict -> tap
    engine-cache (leaf: engine.cache's shared LRU, acquired under anything)
    obs-hist, obs-recorder, obs-telemetry, obs-slo (leaves: the
      histogram set's, flight recorder's, telemetry store's, and SLO
      engine's own locks — observe/record/push is called from under
      scheduler/fleet/metrics code and from wire reader threads, so
      these must never wrap another declared lock)

The journal pair is the FleetJournal's write/pending discipline:
``_flush`` snapshots the pending map *inside* the writer lock
(``fleet-journal-write`` then ``fleet-journal-pending``) so a slow
earlier writer can't clobber a newer snapshot; record/complete take the
pending lock alone and flush after releasing it.

The transport chain follows a respawn end to end: the ProcFleet
supervisor (``_sup_lock`` — the Fleetport's slot-admission/eviction
lock sits at the same level, and holds the registry's membership lock
(``fleet-registry``) beneath it when binding slots), restarts a slot
(``_restart_lock``), whose
new ProcWorkerService builds its wire under ``_ready_lock``; the
WireClient guards connection + pending-table state with its ``_lock``
and serializes frame writes with ``_send_lock``; worker-side, the
WorkerServer's table lock precedes each connection's send lock, and a
ThreadWorker's in-process CheckService sits underneath all of it.
"""

from __future__ import annotations

import re
from typing import List, Optional, Tuple

LOCK_ORDER: Tuple[Tuple[str, List[Tuple[str, str]]], ...] = (
    ("fleet-supervisor",
     [(r"serve/fleet\.py$", r"^self\._sup_lock$"),
      (r"serve/fleetport\.py$", r"^self\._sup_lock$")]),
    # the Governor's policy-state lock (serve/autoscale.py): decisions
    # are made under it, but signal reads and scale actions — which take
    # fleet/scheduler locks — happen outside; it sits above "fleet" so
    # holding it across a fleet call could never invert
    ("autoscale",
     [(r"serve/autoscale\.py$", r"^self\._lock$")]),
    ("fleet",
     [(r"serve/fleet\.py$", r"^self\._(lock|cond)$")]),
    ("fleet-registry",
     [(r"serve/registry\.py$", r"^self\._lock$")]),
    ("fleet-slot",
     [(r"serve/fleet\.py$", r"^self\._restart_lock$"),
      (r"", r"^(w|worker)\._restart_lock$")]),
    ("fleet-journal-write",
     [(r"serve/fleet\.py$", r"^self\._wlock$")]),
    ("fleet-journal-pending",
     [(r"serve/fleet\.py$", r"^self\._jlock$")]),
    ("transport-ready",
     [(r"serve/transport\.py$", r"^self\._ready_lock$")]),
    ("transport-state",
     [(r"serve/transport\.py$", r"^self\._lock$")]),
    ("transport-send",
     [(r"serve/transport\.py$", r"^self\._send_lock$")]),
    ("procworker-state",
     [(r"serve/worker_main\.py$", r"^self\._lock$")]),
    ("procworker-send",
     [(r"serve/worker_main\.py$", r"^(self|c|cs|conn)\._send_lock$")]),
    ("service",
     [(r"serve/service\.py$", r"^self\._lock$")]),
    ("scheduler",
     [(r"serve/scheduler\.py$", r"^self\._(lock|cond)$")]),
    ("request",
     [(r"serve/request\.py$", r"^self\._lock$"),
      (r"", r"^(req|request)\._lock$"),
      (r"", r"^(c|cell)\.request\._lock$")]),
    ("metrics",
     [(r"serve/metrics\.py$", r"^self\._lock$")]),
    # the tenant table's quota condition (serve/tenants.py): submit
    # paths block on it BEFORE touching the scheduler, and exports read
    # counts outside the metrics lock — near-leaf, wraps nothing
    ("tenants",
     [(r"serve/tenants\.py$", r"^self\._cond$")]),
    ("router",
     [(r"serve/router\.py$", r"^self\._lock$")]),
    ("monitor-flush",
     [(r"monitor/__init__\.py$", r"^self\._flush_lock$")]),
    ("monitor-registry",
     [(r"monitor/__init__\.py$", r"^_REG_LOCK$")]),
    ("verdict",
     [(r"monitor/verdict\.py$", r"^self\._lock$")]),
    ("tap",
     [(r"monitor/tap\.py$", r"^self\._lock$")]),
    ("engine-cache",
     [(r"engine/cache\.py$", r"^self\._lock$")]),
    # the fission planes' stats-counter locks (fleet edge and the
    # engine's shrink recursion): _bump/snapshot only — touched from
    # under fleet/scheduler/metrics code, so leaves by construction
    ("fission-plane",
     [(r"serve/fission_plane\.py$", r"^_STATS_LOCK$")]),
    ("shrink",
     [(r"engine/shrink\.py$", r"^_STATS_LOCK$")]),
    ("obs-hist",
     [(r"obs/hist\.py$", r"^self\._lock$"),
      (r"obs/hist\.py$", r"^_MERGE_LOCK$")]),
    ("obs-recorder",
     [(r"obs/recorder\.py$", r"^self\._lock$")]),
    ("obs-telemetry",
     [(r"obs/telemetry\.py$", r"^self\._lock$"),
      (r"obs/telemetry\.py$", r"^_GAUGE_LOCK$")]),
    ("obs-slo",
     [(r"obs/slo\.py$", r"^self\._lock$")]),
)


def lock_level(path: str, expr: str) -> Optional[Tuple[int, str]]:
    """(level, name) of the declared lock a with-item acquires, or None
    when the expression is not a declared lock."""
    for level, (name, patterns) in enumerate(LOCK_ORDER):
        for path_re, expr_re in patterns:
            if re.search(path_re, path) and re.match(expr_re, expr):
                return level, name
    return None
