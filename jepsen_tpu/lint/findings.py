"""Finding/baseline/pragma framework shared by both lint tiers.

A *finding* is one rule violation at one source location, carrying a fix
hint.  Two escape valves keep the analyzer deployable on a living tree
without ever silently losing a finding:

- **pragmas** — ``# lint: disable=RULE(reason)`` on the offending line
  (or the line above) suppresses that rule there, in the source, where
  reviewers see the reason next to the code it excuses;
- **baseline** — ``jepsen_tpu/lint/baseline.json`` is the committed
  ledger of known legacy findings.  CI fails on any finding *not* in the
  baseline, so new debt is impossible while old debt is burned down
  explicitly (``scripts/lint.py --update-baseline`` rewrites it).

Baseline entries match on (rule, path, message) — not line numbers — so
unrelated edits above a legacy finding don't churn the ledger.
"""

from __future__ import annotations

import json
import os
import re
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Tuple

#: ``# lint: disable=RULE`` / ``disable=RULE(reason), OTHER(reason)``
_PRAGMA_RE = re.compile(r"#\s*lint:\s*disable=([A-Za-z0-9_,()\- .:'\"/]+)")
_RULE_IN_PRAGMA_RE = re.compile(r"([A-Z][A-Z0-9]+)(?:\(([^)]*)\))?")


@dataclass
class Finding:
    """One rule violation: location, what broke, and how to fix it."""

    rule: str
    path: str           # repo-relative, forward slashes
    line: int
    message: str
    hint: str = ""
    baselined: bool = False

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.message)

    def to_dict(self) -> Dict[str, Any]:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message, "hint": self.hint,
                "baselined": self.baselined}

    def render(self) -> str:
        tag = " [baselined]" if self.baselined else ""
        out = f"{self.path}:{self.line}: {self.rule}{tag}: {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out


def pragma_rules(src_lines: List[str], line: int) -> Dict[str, str]:
    """Rules disabled at 1-based ``line``: the line itself or the one
    above may carry ``# lint: disable=RULE(reason)``.  Returns
    {rule: reason}."""
    out: Dict[str, str] = {}
    for ln in (line - 1, line - 2):         # 0-based: same line, line above
        if 0 <= ln < len(src_lines):
            m = _PRAGMA_RE.search(src_lines[ln])
            if m:
                for rm in _RULE_IN_PRAGMA_RE.finditer(m.group(1)):
                    out[rm.group(1)] = rm.group(2) or ""
    return out


def apply_pragmas(findings: Iterable[Finding],
                  sources: Dict[str, List[str]]) -> List[Finding]:
    """Drop findings whose location carries a matching disable pragma."""
    out = []
    for f in findings:
        lines = sources.get(f.path)
        if lines is not None and f.rule in pragma_rules(lines, f.line):
            continue
        out.append(f)
    return out


# -- SARIF -------------------------------------------------------------------

_SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                 "master/Schemata/sarif-schema-2.1.0.json")


def to_sarif(findings: Iterable[Finding]) -> Dict[str, Any]:
    """SARIF 2.1.0 document for GitHub code scanning.

    ``partialFingerprints`` carries the same (rule, path, message) key
    the baseline ledger uses, so code-scanning dedup tracks findings
    across unrelated line churn exactly like the ledger does.
    Baselined findings come through as ``note`` so they appear without
    failing the scan; new findings are ``error``.
    """
    rules_meta: Dict[str, Dict[str, Any]] = {}
    results: List[Dict[str, Any]] = []
    for f in findings:
        rules_meta.setdefault(f.rule, {
            "id": f.rule,
            "shortDescription": {"text": f.rule},
            "helpUri": "https://github.com/jepsen-tpu/jepsen-tpu/blob/"
                       "main/docs/static_analysis.md",
        })
        text = f.message if not f.hint else f"{f.message}\nhint: {f.hint}"
        results.append({
            "ruleId": f.rule,
            "level": "note" if f.baselined else "error",
            "message": {"text": text},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path,
                                         "uriBaseId": "SRCROOT"},
                    "region": {"startLine": max(1, f.line)},
                },
            }],
            "partialFingerprints": {
                "jepsenTpuLint/v1": "|".join(f.key()),
            },
        })
    return {
        "version": "2.1.0",
        "$schema": _SARIF_SCHEMA,
        "runs": [{
            "tool": {"driver": {
                "name": "jepsen-tpu-lint",
                "informationUri": "https://github.com/jepsen-tpu/"
                                  "jepsen-tpu/blob/main/docs/"
                                  "static_analysis.md",
                "rules": sorted(rules_meta.values(),
                                key=lambda r: r["id"]),
            }},
            "originalUriBaseIds": {"SRCROOT": {"uri": "file:///"}},
            "results": results,
        }],
    }


# -- baseline ----------------------------------------------------------------

BASELINE_PATH = os.path.join(os.path.dirname(__file__), "baseline.json")


@dataclass
class Baseline:
    """The committed ledger of accepted legacy findings."""

    entries: List[Dict[str, Any]] = field(default_factory=list)

    @classmethod
    def load(cls, path: Optional[str] = None) -> "Baseline":
        path = path or BASELINE_PATH
        if not os.path.exists(path):
            return cls()
        with open(path) as f:
            data = json.load(f)
        return cls(entries=list(data.get("findings", [])))

    def keys(self) -> set:
        return {(e.get("rule"), e.get("path"), e.get("message"))
                for e in self.entries}

    def mark(self, findings: List[Finding]) -> List[Finding]:
        """Set ``baselined`` on findings the ledger already accepts."""
        known = self.keys()
        for f in findings:
            f.baselined = f.key() in known
        return findings

    @staticmethod
    def write(findings: List[Finding], path: Optional[str] = None,
              justification: str = "accepted as legacy debt") -> None:
        path = path or BASELINE_PATH
        data = {
            "version": 1,
            "comment": "Known legacy findings; every entry needs its own "
                       "justification.  New findings fail CI regardless.",
            "findings": [
                {"rule": f.rule, "path": f.path, "message": f.message,
                 "justification": justification}
                for f in sorted(findings, key=lambda f: f.key())
            ],
        }
        with open(path, "w") as f:
            json.dump(data, f, indent=2)
            f.write("\n")
