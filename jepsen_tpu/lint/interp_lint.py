"""The interprocedural tier driver: build the whole-program call graph
once, run every graph-consuming rule over it, honor pragmas.

Unlike the AST tier — where each rule sees one parsed module at a time —
the rules here (:mod:`.rules.conc02`, :mod:`.rules.sec01`,
:mod:`.rules.dl01`) export ``check_program(graph)`` and see the entire
repo through :mod:`.callgraph`.  The graph is built once per run and
shared; at ~270 files it costs about two seconds, which is also why CI
budgets the whole tier under a minute (tests/test_lint.py asserts it).

Suppression composes exactly as in the AST tier: an inline ``# lint:
disable=RULE(reason)`` pragma at the finding's line wins.  The baseline
ledger keys on (rule, path, message), and every interprocedural message
is deliberately line-free (symbol chains only), so unrelated edits don't
churn the ledger — see the satellite contract in docs/static_analysis.md.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

from jepsen_tpu.lint.ast_lint import _iter_py_files, repo_root
from jepsen_tpu.lint.callgraph import CallGraph, build_graph
from jepsen_tpu.lint.findings import Finding, apply_pragmas
from jepsen_tpu.lint.rules import in_scope, interp_rules


def run_interp_tier(root: Optional[str] = None,
                    files: Optional[Dict[str, str]] = None,
                    rules: Optional[Sequence] = None,
                    ) -> Tuple[List[Finding], CallGraph]:
    """Run every interprocedural rule over one shared call graph.

    ``files`` (repo-relative path -> source text) overrides disk
    discovery, mirroring :func:`.ast_lint.run_ast_tier` — the test
    suite uses it to analyze fixture programs.  Returns (post-pragma
    findings, the graph) so callers can archive the graph dump.
    """
    root = root or repo_root()
    if files is None:
        files = {}
        for rel in _iter_py_files(root):
            with open(os.path.join(root, rel)) as f:
                files[rel] = f.read()
    graph = build_graph(files)
    findings: List[Finding] = []
    for rule in (rules if rules is not None else interp_rules()):
        findings.extend(f for f in rule.check_program(graph)
                        if in_scope(f.path, rule.SCOPE))
    sources = {rel: src.splitlines() for rel, src in files.items()}
    return apply_pragmas(findings, sources), graph
