"""SEC01: the fleet token never reaches an artifact — statically.

serve/auth.py promises "the token never travels and is never logged":
only the keyed HMAC digest crosses the wire, inside the frame's ``auth``
envelope field.  Until now that invariant was proven dynamically — the
fleetport smoke greps every artifact and log for the token.  This rule
makes it a whole-program static guarantee.

**Sources.**  The return values of ``serve/auth.py::fleet_token`` and
``serve/auth.py::tenant_tokens`` (per-tenant secrets are credential
material exactly like the fleet secret), plus any direct read of the
``JEPSEN_TPU_FLEET_TOKEN`` / ``JEPSEN_TPU_TENANT_TOKENS`` /
``JEPSEN_TPU_TENANT_TOKEN`` env vars.  Anything HMAC-derived from a
tainted value (``hmac.new(token, ...)`` and string methods on tainted
values) stays tainted: the mac is token *material* and is only ever
allowed in the ``auth`` field.  Tenant *names* are identity, not
credential — ``tenant_names`` launders through ``sorted()`` (a
non-string builtin), which correctly drops taint.

**Propagation.**  Through assignments, f-strings/``%``/``+`` string
building, dict/list/tuple literals, ``self.<attr>`` stores (the attr
taints class-wide, through subclasses), and call arguments into resolved
callees — the call-graph edges — with return-taint flowing back.
Placing a tainted value under the ``auth`` key of a dict (literal or
subscript store) does NOT taint the dict: that is the one sanctioned
envelope.  ``bool()/len()/int()`` and friends untaint (existence checks
like ``auth-enabled`` are legal exports).

**Sinks.**  Logging calls, exception construction (exception text ends
up in logs and typed ERROR frames), metrics/telemetry emission
(``record``/``observe``/``set_gauge``/``push``), frame encoding/sends,
file writes, and tainted returns from snapshot/status-shaped functions.

Finding messages carry the symbol chain from the function that minted
the taint to the sink — no line numbers — so the baseline ledger keys
on (rule, path, symbol-chain).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from jepsen_tpu.lint.callgraph import (CallGraph, map_args_to_params)
from jepsen_tpu.lint.findings import Finding

RULE = "SEC01"

SCOPE = ("jepsen_tpu/", "suites/")

_TOKEN_ENVS = ("FLEET_TOKEN", "TENANT_TOKEN")   # substring match: the
# second also covers JEPSEN_TPU_TENANT_TOKENS (the per-tenant secret map)
_AUTH_KEY = "auth"

_LOG_BASES = {"logging", "logger", "log", "LOG", "_log"}
_LOG_METHODS = {"debug", "info", "warning", "warn", "error", "exception",
                "critical", "log"}
_METRIC_METHODS = {"record", "observe", "set_gauge", "push",
                   "observe_compile"}
_FRAME_NAMES = {"encode_frame", "send_frame", "sendall"}
_WRITE_METHODS = {"write", "writelines"}
_WRITE_EXT = {"json.dump", "os.write"}
_STR_FUNCS = {"str", "repr", "format"}
_UNTAINT = {"bool", "len", "int", "float", "hash", "id", "isinstance",
            "type", "callable"}
_SNAPSHOT_RE = re.compile(
    r"(snapshot|status|healthz|payload|to_dict|to_wire|metrics)", re.I)

_FN = (ast.FunctionDef, ast.AsyncFunctionDef)


def _dotted(node: ast.AST) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else ""
    return ""


class _Sec01:
    """The global fixpoint: token-returning functions and tainted class
    attributes grow monotonically; per-(function, tainted-params)
    analyses are memoized within each iteration."""

    MAX_ITERS = 8

    def __init__(self, graph: CallGraph):
        self.g = graph
        self.token_fns: Set[str] = set()
        self.tainted_attrs: Set[Tuple[str, str]] = set()
        self.memo: Dict[Tuple[str, FrozenSet[str]], bool] = {}
        self.findings: Dict[Tuple, Finding] = {}
        self._grew = False

    # -- entry -------------------------------------------------------------

    def run(self) -> List[Finding]:
        for fn in ("fleet_token", "tenant_tokens"):
            src = self.g.find("serve/auth.py", fn)
            if src is not None:
                self.token_fns.add(src.id)
        for _ in range(self.MAX_ITERS):
            self.memo.clear()
            self.findings.clear()
            self._grew = False
            for fid in sorted(self.g.funcs):
                ret = self._analyze(fid, frozenset(), ())
                if ret and fid not in self.token_fns:
                    self.token_fns.add(fid)
                    self._grew = True
            if not self._grew:
                break
        return sorted(self.findings.values(),
                      key=lambda f: (f.path, f.line, f.message))

    # -- helpers -----------------------------------------------------------

    def _const_key(self, path: str, key: Optional[ast.AST]) -> Optional[str]:
        if key is None:
            return None
        if isinstance(key, ast.Constant) and isinstance(key.value, str):
            return key.value
        if isinstance(key, ast.Name):
            return self.g.module_const(path, key.id)
        return None

    def _emit(self, fam: str, path: str, lineno: int,
              chain: Tuple[str, ...]) -> None:
        chain_s = " -> ".join(chain)
        key = (fam, path, chain_s)
        if key in self.findings:
            return
        self.findings[key] = Finding(
            RULE, path, lineno,
            f"token material (fleet or tenant) may reach a {fam} sink "
            f"via {chain_s}: a token (and anything HMAC-derived from it) "
            f"may only appear in a frame's 'auth' envelope field",
            hint="export at most `auth-enabled: bool(token)`; strip the "
                 "token before the value reaches logs, errors, metrics, "
                 "frames, or files")

    # -- per-function analysis --------------------------------------------

    def _analyze(self, fid: str, params: FrozenSet[str],
                 stack: Tuple[str, ...]) -> bool:
        key = (fid, params)
        if key in self.memo:
            return self.memo[key]
        if fid in stack:
            return False                 # cycle: converges via iterations
        f = self.g.funcs[fid]
        m = self.g.modules.get(f.path)
        if m is None:                    # pragma: no cover - defensive
            return False
        stack = stack + (fid,)
        chain = tuple(self.g.funcs[s].label for s in stack)
        tainted: Set[str] = set(params)
        ret_tainted = False
        edge_at = self.g.edge_at.get(fid, {})

        def is_tainted(e: ast.AST) -> bool:
            if isinstance(e, ast.Name):
                return e.id in tainted
            if isinstance(e, ast.Attribute):
                if isinstance(e.value, ast.Name) and e.value.id == "self" \
                        and f.cls:
                    return self.g.class_attr_taintable(
                        f.cls, e.attr, self.tainted_attrs)
                return is_tainted(e.value)
            if isinstance(e, ast.Call):
                return call_taint(e)
            if isinstance(e, ast.JoinedStr):
                return any(is_tainted(v.value) for v in e.values
                           if isinstance(v, ast.FormattedValue))
            if isinstance(e, ast.FormattedValue):
                return is_tainted(e.value)
            if isinstance(e, ast.BinOp):
                return is_tainted(e.left) or is_tainted(e.right)
            if isinstance(e, ast.BoolOp):
                return any(is_tainted(v) for v in e.values)
            if isinstance(e, ast.IfExp):
                return is_tainted(e.body) or is_tainted(e.orelse)
            if isinstance(e, ast.Dict):
                return any(
                    is_tainted(v) for k, v in zip(e.keys, e.values)
                    if self._const_key(f.path, k) != _AUTH_KEY)
            if isinstance(e, (ast.List, ast.Tuple, ast.Set)):
                return any(is_tainted(v) for v in e.elts)
            if isinstance(e, ast.Subscript):
                return is_tainted(e.value)
            if isinstance(e, ast.Starred):
                return is_tainted(e.value)
            if isinstance(e, ast.NamedExpr):
                return is_tainted(e.value)
            if isinstance(e, ast.Await):
                return is_tainted(e.value)
            return False

        def env_token_read(call: ast.Call) -> bool:
            ext = self.g.external_name(m, _dotted(call.func)) or ""
            if ext not in ("os.environ.get", "os.getenv"):
                return False
            if not call.args:
                return False
            k = call.args[0]
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                return any(t in k.value for t in _TOKEN_ENVS)
            if isinstance(k, ast.Name):
                v = self.g.module_const(f.path, k.id)
                return v is not None and any(t in v for t in _TOKEN_ENVS)
            return False

        def sink_family(call: ast.Call, d: str,
                        ext: Optional[str]) -> Optional[str]:
            parts = d.split(".") if d else []
            last = parts[-1] if parts else ""
            if (ext or "").split(".")[0] == "logging" \
                    or d == "print" or ext in ("print", "warnings.warn") \
                    or (len(parts) >= 2 and parts[0] in _LOG_BASES
                        and last in _LOG_METHODS):
                return "logging"
            if last in _WRITE_METHODS or ext in _WRITE_EXT:
                return "file-write"
            if last in _METRIC_METHODS:
                return "metrics/telemetry"
            if last in _FRAME_NAMES:
                return "frame"
            if re.search(r"(Error|Exception)$", last or ""):
                return "exception"
            return None

        def call_taint(call: ast.Call) -> bool:
            d = _dotted(call.func)
            ext = self.g.external_name(m, d) if d else None
            if env_token_read(call):
                return True
            args = list(call.args) + [kw.value for kw in call.keywords]
            any_taint = any(is_tainted(a) for a in args)
            if any_taint:
                fam = sink_family(call, d, ext)
                if fam is not None:
                    self._emit(fam, f.path, call.lineno, chain)
            edge = edge_at.get((call.lineno, call.col_offset))
            sub_ret = False
            if edge is not None and edge.kind == "call":
                callee = self.g.funcs[edge.callee]
                if any_taint:
                    mapped = map_args_to_params(edge, call, callee)
                    tp = frozenset(p for p, ex in mapped.items()
                                   if is_tainted(ex))
                    if tp:
                        sub_ret = self._analyze(callee.id, tp, stack)
                if edge.callee in self.token_fns:
                    return True
                return sub_ret
            if ext is not None:
                if ext in _UNTAINT:
                    return False
                if ext.startswith("hmac.new") and any_taint:
                    return True
                if ext in _STR_FUNCS and any_taint:
                    return True
            # a method invoked on a tainted object yields token material
            # (.encode/.strip/.hexdigest/.format/...)
            if isinstance(call.func, ast.Attribute) \
                    and is_tainted(call.func.value):
                return True
            return False

        def store(target: ast.AST, value_tainted: bool) -> None:
            if not value_tainted:
                return
            if isinstance(target, ast.Name):
                tainted.add(target.id)
            elif isinstance(target, ast.Attribute) \
                    and isinstance(target.value, ast.Name) \
                    and target.value.id == "self" and f.cls:
                if (f.cls, target.attr) not in self.tainted_attrs:
                    self.tainted_attrs.add((f.cls, target.attr))
                    self._grew = True
            elif isinstance(target, (ast.Tuple, ast.List)):
                for el in target.elts:
                    store(el, value_tainted)

        def visit(node: ast.AST) -> None:
            nonlocal ret_tainted
            if isinstance(node, _FN) or isinstance(node, ast.Lambda):
                return                   # separate graph node
            if isinstance(node, ast.Assign):
                t = is_tainted(node.value)
                for tg in node.targets:
                    if isinstance(tg, ast.Subscript):
                        k = self._const_key(
                            f.path, tg.slice
                            if not isinstance(tg.slice, ast.Tuple)
                            else None)
                        if t and k != _AUTH_KEY:
                            store(tg.value, True)
                    else:
                        store(tg, t)
            elif isinstance(node, ast.AugAssign):
                if is_tainted(node.value):
                    store(node.target, True)
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                store(node.target, is_tainted(node.value))
            elif isinstance(node, ast.Return) and node.value is not None:
                if is_tainted(node.value):
                    ret_tainted = True
                    if _SNAPSHOT_RE.search(f.qual.rsplit(".", 1)[-1]):
                        self._emit("snapshot-payload", f.path,
                                   node.lineno, chain)
            elif isinstance(node, ast.Raise) and node.exc is not None:
                if isinstance(node.exc, ast.Call):
                    args = (list(node.exc.args)
                            + [kw.value for kw in node.exc.keywords])
                    if any(is_tainted(a) for a in args):
                        self._emit("exception", f.path, node.exc.lineno,
                                   chain)
            elif isinstance(node, ast.Call):
                call_taint(node)
            for child in ast.iter_child_nodes(node):
                visit(child)

        # two passes: taint assigned late in a loop body reaches uses
        # earlier in the (next) iteration
        for _ in range(2):
            for stmt in f.node.body:
                visit(stmt)
        self.memo[key] = ret_tainted
        return ret_tainted


def check_program(graph: CallGraph) -> List[Finding]:
    return _Sec01(graph).run()
