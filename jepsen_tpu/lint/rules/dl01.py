"""DL01: no wall clock crosses a process boundary — deadlines travel as
remaining budget.

The transport's deadline discipline (serve/transport.py, serve/fleet.py)
is that every cross-process send — SUBMIT frames, journal records,
telemetry pushes — carries ``deadline-rem-s``: the *remaining* seconds
of the caller's ``engine.budget.Deadline``, re-anchored on the
receiver's own monotonic clock.  Absolute timestamps are meaningless
across hosts (wall clocks disagree; monotonic clocks have per-process
epochs), so a ``time.time()`` value or a bare ``mono_now()`` reading
flowing into a deadline field silently corrupts budget accounting on
the far side.  Until now that was proven only dynamically; this rule
makes it a static check over the call graph.

**Provenance classes** for an expression feeding a deadline field:

- *bad / wall-clock*: ``time.time``/``time.time_ns``, ``datetime.now``
  family, and anything built from them — including differences:
  two hosts' wall clocks disagree, so even ``wall - wall`` is
  untrustworthy budget.
- *bad / absolute-monotonic*: bare ``time.monotonic`` / ``mono_now()``
  readings and ``Deadline.at``-style absolute attributes.  Subtraction
  launders absoluteness here: ``deadline_at - mono_now()`` is a
  relative remainder and is fine — that is exactly how
  ``Deadline.remaining`` is implemented.
- *ok*: ``.remaining()`` / ``.remaining_s()`` calls, constants, and
  anything else — the rule reports positively-detected bad flows only;
  unknown provenance is not a finding.
- *parameter*: the obligation propagates to every caller through the
  call graph's in-edges — a wall-clock argument three frames up still
  produces a finding, with the symbol chain printed.

A second check is structural: any dict literal that is recognizably a
SUBMIT frame (a ``"type"`` key whose value resolves to ``"submit"``)
must carry a deadline key at all — a frame with no budget is as wrong
as one with an absolute one.

Messages are line-free symbol chains, keying the baseline ledger on
(rule, path, symbol-chain).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from jepsen_tpu.lint.callgraph import CallGraph, map_args_to_params
from jepsen_tpu.lint.findings import Finding

RULE = "DL01"

SCOPE = ("jepsen_tpu/", "suites/")

#: frame/journal keys that must carry *remaining* (relative) budget
_DEADLINE_KEYS = {"deadline-rem-s", "deadline_rem_s"}

_WALL = {"time.time", "time.time_ns"}
_WALL_DT_SUFFIX = (".now", ".utcnow", ".today")
_MONO = {"time.monotonic", "time.monotonic_ns", "time.perf_counter"}
_MONO_QUALS = ("mono_now",)
_OK_METHODS = {"remaining", "remaining_s"}
_COMBINE_FUNCS = {"max", "min", "abs", "float", "int", "round"}

_FN = (ast.FunctionDef, ast.AsyncFunctionDef)

# provenance lattice: OK < PARAM < BAD
_OK, _PARAM, _BAD = 0, 1, 2


class _Prov:
    __slots__ = ("rank", "reason", "param")

    def __init__(self, rank: int, reason: str = "",
                 param: Optional[str] = None):
        self.rank = rank
        self.reason = reason
        self.param = param


def _join(a: _Prov, b: _Prov) -> _Prov:
    return a if a.rank >= b.rank else b


def _dotted(node: ast.AST) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else ""
    return ""


class _FnFacts:
    """One function's deadline-relevant facts."""

    def __init__(self) -> None:
        #: name -> provenance of its last assignment
        self.env: Dict[str, _Prov] = {}
        #: direct findings: (lineno, key, reason)
        self.direct: List[Tuple[int, str, str]] = []
        #: param name -> (lineno, key): the param flows into a deadline
        #: field, so callers owe a relative value
        self.param_sinks: Dict[str, Tuple[int, str]] = {}
        #: submit-frame dict literals with no deadline key
        self.missing: List[int] = []
        #: call nodes by position, for arg->param mapping at in-edges
        self.calls: Dict[Tuple[int, int], ast.Call] = {}


class _Dl01:

    def __init__(self, graph: CallGraph):
        self.g = graph
        self.facts: Dict[str, _FnFacts] = {}

    # -- provenance classifier --------------------------------------------

    def classify(self, fid: str, e: ast.AST) -> _Prov:
        g = self.g
        f = g.funcs[fid]
        m = g.modules.get(f.path)
        facts = self.facts[fid]
        if isinstance(e, ast.Call):
            d = _dotted(e.func)
            ext = g.external_name(m, d) if (d and m) else None
            if ext in _WALL or (ext and ext.startswith("datetime.")
                                and ext.endswith(_WALL_DT_SUFFIX)):
                return _Prov(_BAD, f"wall-clock reading `{d}()`")
            if ext in _MONO:
                return _Prov(_BAD,
                             f"absolute monotonic reading `{d}()` "
                             f"(per-process epoch)")
            edge = g.edge_at.get(fid, {}).get((e.lineno, e.col_offset))
            if edge is not None and edge.kind == "call" \
                    and g.funcs[edge.callee].qual.rsplit(
                        ".", 1)[-1] in _MONO_QUALS:
                return _Prov(_BAD,
                             f"absolute monotonic reading `{d}()` "
                             f"(per-process epoch)")
            parts = d.split(".") if d else []
            if parts and parts[-1] in _OK_METHODS:
                return _Prov(_OK)
            if parts and parts[-1] in _COMBINE_FUNCS:
                p = _Prov(_OK)
                for a in list(e.args) + [kw.value for kw in e.keywords]:
                    p = _join(p, self.classify(fid, a))
                return p
            return _Prov(_OK)
        if isinstance(e, ast.BinOp):
            left = self.classify(fid, e.left)
            right = self.classify(fid, e.right)
            if isinstance(e.op, ast.Sub):
                # differences of monotonic readings are relative; wall
                # stays bad (two hosts' wall clocks disagree)
                for p in (left, right):
                    if p.rank == _BAD and "wall-clock" in p.reason:
                        return p
                if _PARAM in (left.rank, right.rank):
                    return left if left.rank == _PARAM else right
                return _Prov(_OK)
            return _join(left, right)
        if isinstance(e, ast.Name):
            if e.id in facts.env:
                return facts.env[e.id]
            if e.id in f.params():
                return _Prov(_PARAM, param=e.id)
            return _Prov(_OK)
        if isinstance(e, ast.Attribute):
            d = _dotted(e)
            if e.attr == "at" and "deadline" in d.lower():
                return _Prov(_BAD, f"absolute deadline attribute `{d}`")
            return _Prov(_OK)
        if isinstance(e, ast.IfExp):
            return _join(self.classify(fid, e.body),
                         self.classify(fid, e.orelse))
        if isinstance(e, ast.BoolOp):
            p = _Prov(_OK)
            for v in e.values:
                p = _join(p, self.classify(fid, v))
            return p
        return _Prov(_OK)

    # -- per-function pass ------------------------------------------------

    def _const_key(self, path: str, k: Optional[ast.AST]) -> Optional[str]:
        if k is None:
            return None
        if isinstance(k, ast.Constant) and isinstance(k.value, str):
            return k.value
        if isinstance(k, ast.Name):
            return self.g.module_const(path, k.id)
        return None

    def _analyze_fn(self, fid: str) -> None:
        f = self.g.funcs[fid]
        facts = _FnFacts()
        self.facts[fid] = facts

        def sink(lineno: int, key: str, value: ast.AST) -> None:
            p = self.classify(fid, value)
            if p.rank == _BAD:
                facts.direct.append((lineno, key, p.reason))
            elif p.rank == _PARAM and p.param is not None:
                facts.param_sinks.setdefault(p.param, (lineno, key))

        def visit(node: ast.AST) -> None:
            if isinstance(node, _FN) or isinstance(node, ast.Lambda):
                return
            if isinstance(node, ast.Call):
                facts.calls[(node.lineno, node.col_offset)] = node
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                if isinstance(tgt, ast.Name):
                    facts.env[tgt.id] = self.classify(fid, node.value)
                elif isinstance(tgt, ast.Subscript):
                    k = self._const_key(f.path, tgt.slice)
                    if k in _DEADLINE_KEYS:
                        sink(node.lineno, k, node.value)
            if isinstance(node, ast.Dict):
                keys = [self._const_key(f.path, k) for k in node.keys]
                for k, v in zip(keys, node.values):
                    if k in _DEADLINE_KEYS:
                        sink(v.lineno, k, v)
                type_val: Optional[str] = None
                for k, v in zip(keys, node.values):
                    if k == "type":
                        if isinstance(v, ast.Constant) \
                                and isinstance(v.value, str):
                            type_val = v.value
                        elif isinstance(v, ast.Name):
                            type_val = self.g.module_const(f.path, v.id)
                if type_val == "submit" \
                        and not (set(keys) & _DEADLINE_KEYS):
                    facts.missing.append(node.lineno)
            for child in ast.iter_child_nodes(node):
                visit(child)

        # two passes so names assigned textually after first use (loop
        # bodies) still classify on the re-walk
        for _ in range(2):
            facts.direct.clear()
            facts.param_sinks.clear()
            facts.missing.clear()
            facts.calls.clear()
            for stmt in f.node.body:
                visit(stmt)

    # -- whole-program ----------------------------------------------------

    def run(self) -> List[Finding]:
        g = self.g
        for fid in g.funcs:
            self._analyze_fn(fid)
        findings: List[Finding] = []
        seen: Set[Tuple[str, str, str]] = set()

        def emit(path: str, lineno: int, key: str, reason: str,
                 chain: Tuple[str, ...]) -> None:
            chain_s = " -> ".join(chain)
            k = (path, chain_s, key)
            if k in seen:
                return
            seen.add(k)
            findings.append(Finding(
                RULE, path, lineno,
                f"non-relative deadline flows into frame field '{key}' "
                f"via {chain_s}: {reason}; cross-process deadlines must "
                f"travel as remaining budget "
                f"(engine.budget.Deadline.remaining)",
                hint="send deadline.remaining() (or deadline_at - "
                     "mono_now()) and re-anchor on the receiver's "
                     "monotonic clock"))

        for fid in sorted(self.facts):
            f = g.funcs[fid]
            facts = self.facts[fid]
            for lineno, key, reason in facts.direct:
                emit(f.path, lineno, key, reason, (f.label,))
            for lineno in facts.missing:
                k = (f.path, f.label, "<missing>")
                if k in seen:
                    continue
                seen.add(k)
                findings.append(Finding(
                    RULE, f.path, lineno,
                    f"submit frame constructed in {f.label} carries no "
                    f"deadline field: every cross-process send must "
                    f"carry remaining budget",
                    hint="add 'deadline-rem-s': deadline.remaining() "
                         "to the frame"))

        # parameter obligations propagate to callers through in-edges
        work: List[Tuple[str, str, Tuple[str, ...],
                         Tuple[int, str]]] = []
        for fid in sorted(self.facts):
            for param, at in sorted(self.facts[fid].param_sinks.items()):
                work.append((fid, param, (g.funcs[fid].label,), at))
        visited: Set[Tuple[str, str]] = set()
        while work:
            fid, param, chain, at = work.pop()
            if (fid, param) in visited:
                continue
            visited.add((fid, param))
            callee = g.funcs[fid]
            for e in g.in_edges(fid):
                if e.kind != "call":
                    continue
                cfacts = self.facts.get(e.caller)
                if cfacts is None:
                    continue
                call = cfacts.calls.get((e.lineno, e.col))
                if call is None:
                    continue
                mapped = map_args_to_params(e, call, callee)
                arg = mapped.get(param)
                if arg is None:
                    continue          # default applies: callee's choice
                caller = g.funcs[e.caller]
                p = self.classify(e.caller, arg)
                if p.rank == _BAD:
                    emit(caller.path, e.lineno, at[1], p.reason,
                         (caller.label,) + chain)
                elif p.rank == _PARAM and p.param is not None:
                    work.append((e.caller, p.param,
                                 (caller.label,) + chain, at))
        return findings


def check_program(graph: CallGraph) -> List[Finding]:
    return _Dl01(graph).run()
