"""SOUND01: a verdict may degrade valid -> unknown, never valid -> false.

Everything in this stack — budget expiry, device failure, deadline
passes, monitor partial state — is allowed to *weaken* a verdict to
``unknown``; only a genuine counterexample may say ``false``.  A
``{"valid": False}`` constructed on a fallback path silently converts
"we could not check this" into "the system is broken", which corrupts
every downstream consumer (merge_valid propagates false over
everything).

The rule therefore audits every literal ``valid: False`` construction
(dict literals and ``result["valid"] = False`` stores) in the verdict-
producing subsystems:

- inside an ``except`` handler: always a finding — an exception path has
  no witness by construction;
- elsewhere: legal only when the site is *witness-bearing* and says so —
  either an inline ``# witness: <why>`` annotation on the construction,
  or an entry in :data:`WHITELIST` keyed by (path, enclosing qualname).

Computed verdicts (``"valid": not errors``) are out of scope: they carry
their evidence in the same expression.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Tuple

from jepsen_tpu.lint.findings import Finding
from jepsen_tpu.lint.rules import (enclosing_handler, qualname_of,
                                   walk_with_parents)

RULE = "SOUND01"

SCOPE = (
    "jepsen_tpu/checker/",
    "jepsen_tpu/serve/",
    "jepsen_tpu/monitor/",
    "jepsen_tpu/parallel/",
    "jepsen_tpu/elle_tpu/",
    "jepsen_tpu/elle/",
    "jepsen_tpu/engine/",
)

#: Registered witness-bearing sites: (path, enclosing qualname) -> one-line
#: justification.  Prefer the inline ``# witness:`` annotation (reviewers
#: see it next to the code); register here only when the site is shared by
#: several constructions in one function.
WHITELIST: Dict[Tuple[str, str], str] = {
    # The CPU oracle refutes only when pruning on a RETURN leaves no
    # surviving configuration; the result carries the refuting op.
    ("jepsen_tpu/checker/wgl_cpu.py", "check"):
        "exhaustive WGL prune: refuting op + final configs attached",
}

_WITNESS_RE = re.compile(r"#\s*witness:\s*\S")


def _is_false(node: ast.AST) -> bool:
    return isinstance(node, ast.Constant) and node.value is False


def _has_witness(src_lines: List[str], *lines: int) -> bool:
    for ln in lines:                        # 1-based
        for cand in (ln, ln - 1):
            if 0 < cand <= len(src_lines) \
                    and _WITNESS_RE.search(src_lines[cand - 1]):
                return True
    return False


def check(tree: ast.Module, src_lines: List[str],
          path: str) -> Iterator[Finding]:
    for node in walk_with_parents(tree):
        site = None                          # (line, description)
        if isinstance(node, ast.Dict):
            for k, v in zip(node.keys, node.values):
                if isinstance(k, ast.Constant) and k.value == "valid" \
                        and _is_false(v):
                    site = (k.lineno, "dict literal {'valid': False}")
        elif isinstance(node, ast.Assign) and _is_false(node.value):
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript) \
                        and isinstance(tgt.slice, ast.Constant) \
                        and tgt.slice.value == "valid":
                    site = (node.lineno, "store result['valid'] = False")
        if site is None:
            continue
        line, desc = site
        qn = qualname_of(node)
        handler = enclosing_handler(node)
        if handler is not None:
            yield Finding(
                RULE, path, line,
                f"{desc} inside an except handler ({qn}): an exception "
                f"path has no witness and must degrade to 'unknown', "
                f"never flip a verdict to false",
                hint="return {'valid': 'unknown', 'error': ...} from "
                     "fallback paths; false requires a counterexample")
            continue
        if _has_witness(src_lines, line, getattr(node, "lineno", line)):
            continue
        if (path, qn) in WHITELIST:
            continue
        yield Finding(
            RULE, path, line,
            f"{desc} in {qn} is not a registered witness-bearing site",
            hint="attach the refuting evidence and annotate the "
                 "construction with '# witness: <what evidence rides "
                 "along>', or register (path, qualname) in "
                 "lint/rules/sound01.py WHITELIST with a justification")
