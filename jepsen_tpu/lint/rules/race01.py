"""RACE01: every shared mutable attribute has a consistent guard.

Eraser's lockset discipline, statically, over the guarded-by inference
in :mod:`jepsen_tpu.lint.guards`: for each attribute of a class under
``serve/``, ``monitor/``, or ``obs/`` whose post-publication accesses
span at least two concurrency roots (a ``threading.Thread`` seam and
"main", or two distinct seams), intersect the locks *guaranteed held*
(lexically + inherited MUST-hold entry sets through the call graph) at
every post-publication site.  An attribute that is written after
publication and whose intersection is empty has **no consistent guard**
— two threads can interleave on it — and the finding prints both
unsynchronized sites with the symbol chain from each site's concurrency
root, so the reviewer sees the two racing stacks, not just a field name.

What does *not* fire:

- attributes written only in ``__init__`` before the first possible
  thread start — safely published, immutable afterwards;
- attributes bound to internally-synchronized types (``queue.Queue``,
  ``threading.Event``, the locks themselves);
- attributes touched from a single thread's call tree only;
- read-only attributes (no post-publication write anywhere).

Deliberately-torn sites (e.g. the gauge sampling in ``serve/metrics.py``,
whose tear contract is documented in that module and in
docs/observability.md) carry ``# lint: disable=RACE01(reason)`` on the
write — the pragma-with-reason idiom, never the baseline.

Messages are line-free symbol chains (baseline/SARIF keys survive line
churn); the finding's *location* is the unguarded write, so the pragma
lands where the tear lives.
"""

from __future__ import annotations

from typing import List

from jepsen_tpu.lint import guards
from jepsen_tpu.lint.callgraph import CallGraph
from jepsen_tpu.lint.findings import Finding

RULE = "RACE01"

SCOPE = ("jepsen_tpu/", "suites/")

#: classes whose attributes are audited (the threaded subsystems)
_CLASS_SCOPE = ("jepsen_tpu/serve/", "jepsen_tpu/monitor/",
                "jepsen_tpu/obs/")


def _fmt_locks(locks) -> str:
    if not locks:
        return "no lock"
    return ", ".join(f"'{name}'" for _lv, name in sorted(locks))


def check_program(graph: CallGraph) -> List[Finding]:
    ga = guards.analyze(graph)
    findings: List[Finding] = []
    for (cid, attr), _sites in sorted(ga.accesses.items()):
        info = graph.classes.get(cid)
        if info is None or not any(info.path.startswith(p)
                                   for p in _CLASS_SCOPE):
            continue
        if ga.threadsafe_attr(cid, attr):
            continue
        sites = ga.post_publication_sites(cid, attr)
        writes = [a for a in sites if a.is_write]
        if not writes or not ga.shared(cid, attr):
            continue
        common = None
        for a in sites:
            h = ga.held_at(a)
            common = h if common is None else (common & h)
            if not common:
                break
        if common:
            continue                        # a consistent guard exists
        # exemplars: the barest write, and the barest conflicting site
        # in a different function (prefer a different concurrency root)
        w = min(writes, key=lambda a: (len(ga.held_at(a)), a.fid,
                                       a.lineno))
        others = [a for a in sites
                  if a.fid != w.fid or (a.lineno, a.col) != (w.lineno,
                                                             w.col)]
        conflict = None
        if others:
            w_roots = ga.origins.get(w.fid, frozenset())
            conflict = min(
                others,
                key=lambda a: (len(ga.held_at(a)),
                               ga.origins.get(a.fid, frozenset())
                               <= w_roots,
                               a.fid, a.lineno))
        cls_label = f"{info.name}.{attr}"
        msg = (f"shared attribute `{cls_label}` has no consistent "
               f"guard: candidate-lock intersection over "
               f"{len(sites)} post-publication site(s) is empty; "
               f"{w.kind} in {graph.funcs[w.fid].label} holds "
               f"{_fmt_locks(ga.held_at(w))} "
               f"[{ga.render_chain(w.fid)}]")
        if conflict is not None:
            msg += (f"; conflicting {conflict.kind} in "
                    f"{graph.funcs[conflict.fid].label} holds "
                    f"{_fmt_locks(ga.held_at(conflict))} "
                    f"[{ga.render_chain(conflict.fid)}]")
        findings.append(Finding(
            RULE, w.fid.split("::")[0], w.lineno, msg,
            hint="guard every post-publication access with one declared "
                 "lock (lint/lock_order.py), make the field "
                 "safely-published (write it in __init__ before any "
                 "thread starts), or add `# lint: disable=RACE01"
                 "(reason)` at the write if the tear is a documented "
                 "contract"))
    return findings
