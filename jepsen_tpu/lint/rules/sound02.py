"""SOUND02: unknown-never-false, proven across fission merge sites.

SOUND01 audits each ``valid: False`` construction lexically — an inline
``# witness:`` annotation or a whitelist entry attests that evidence
rides along.  Fission recombination raised the stakes: a verdict now
*flows* — a sub-problem's False crosses ``engine/fission.py`` merge
loops, the ``engine/shrink.py`` prefix recursion, the fleet-side
``serve/aggregate.py`` recombiner, and the ``serve/fission_plane.py``
witness-recovery seam before a caller sees it.  An annotation on the
construction says nothing about the *path*: a merge function that does
``if r.get("valid") is False: return r`` launders an unwitnessed child
refutation into a recombined verdict without constructing anything.

This rule therefore dataflow-proves the table contract from
docs/fission.md over the call graph, in the fission subsystems only
(:data:`SCOPE`):

- **construction sites** (dict literal ``{"valid": False}`` or a
  ``result["valid"] = False`` store) must be *witness-bearing*: carry
  literal ``"op"`` and ``"witness"`` keys, sit under a dominating guard
  that tests both ``"op" in r`` and ``"witness" in r``, or carry the
  SOUND01 ``# witness:`` annotation.  Inside an ``except`` handler the
  site is a finding regardless — exception paths have no witness;
- **pass-through returns** — ``return r`` on a refutation path (an
  enclosing ``... is False`` guard) — must either sit under a
  witness-presence guard, or return a value produced by an in-scope
  callee, in which case the obligation follows the call edge: if that
  callee has any unwitnessed False path, the whole chain is reported
  with its symbols (``aggregate.py::merge -> shrink.py::probe``).

Like DL01, the rule reports positively-detected violations only:
unknown provenance (dynamic dispatch, out-of-scope callees — SOUND01's
jurisdiction) is not a finding.  Messages are line-free symbol chains,
keying the baseline ledger on (rule, path, message).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from jepsen_tpu.lint.callgraph import CallGraph, FuncInfo
from jepsen_tpu.lint.findings import Finding

RULE = "SOUND02"

#: The fission merge surface: every module a sub-verdict crosses between
#: a worker's refutation and the recombined verdict a caller sees.
SCOPE = (
    "jepsen_tpu/engine/fission.py",
    "jepsen_tpu/engine/shrink.py",
    "jepsen_tpu/serve/aggregate.py",
    "jepsen_tpu/serve/fission_plane.py",
)

_WITNESS_RE = re.compile(r"#\s*witness:\s*\S")

_FN = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _is_false(node: ast.AST) -> bool:
    return isinstance(node, ast.Constant) and node.value is False


def _walk_fn(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk one function's own body — nested defs are their own graph
    nodes and are not descended into — annotating ``.parent``."""
    stack: List[ast.AST] = []
    for stmt in fn.body:
        stmt.parent = fn                    # type: ignore[attr-defined]
        stack.append(stmt)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, _FN):
            continue
        for child in ast.iter_child_nodes(node):
            child.parent = node             # type: ignore[attr-defined]
            stack.append(child)


def _guards(node: ast.AST) -> List[ast.If]:
    """Enclosing ``if`` tests dominating ``node`` (body branch only —
    an ``else`` arm runs exactly when the test failed), innermost
    first, not crossing the function boundary."""
    out: List[ast.If] = []
    child, cur = node, getattr(node, "parent", None)
    while cur is not None and not isinstance(cur, _FN):
        if isinstance(cur, ast.If) and child in cur.body:
            out.append(cur)
        child, cur = cur, getattr(cur, "parent", None)
    return out


def _test_has_false_cmp(test: ast.AST) -> bool:
    """A verdict-refutation test: ``... is/== False`` whose left side
    reads the ``"valid"`` field (``r.get("valid")``, ``r["valid"]``).
    A bare ``x is False`` on anything else (feature knobs, flags) is
    not a refutation path."""
    for sub in ast.walk(test):
        if isinstance(sub, ast.Compare) \
                and any(isinstance(op, (ast.Is, ast.Eq))
                        for op in sub.ops) \
                and any(_is_false(c) for c in sub.comparators) \
                and any(isinstance(n, ast.Constant) and n.value == "valid"
                        for n in ast.walk(sub.left)):
            return True
    return False


def _test_witness_keys(test: ast.AST) -> Set[str]:
    found: Set[str] = set()
    for sub in ast.walk(test):
        if isinstance(sub, ast.Compare) \
                and any(isinstance(op, ast.In) for op in sub.ops) \
                and isinstance(sub.left, ast.Constant) \
                and sub.left.value in ("op", "witness"):
            found.add(sub.left.value)
    return found


def _in_handler(node: ast.AST) -> bool:
    cur = getattr(node, "parent", None)
    while cur is not None and not isinstance(cur, _FN):
        if isinstance(cur, ast.ExceptHandler):
            return True
        cur = getattr(cur, "parent", None)
    return False


class _Sound02:

    def __init__(self, graph: CallGraph):
        self.g = graph
        self.scoped = [f for f in graph.funcs.values()
                       if f.path.startswith(SCOPE)]
        self.findings: List[Finding] = []
        self._seen: Set[Tuple[str, str]] = set()
        #: fid -> symbol chain proving it can emit an unwitnessed False
        self.tainted: Dict[str, Tuple[str, ...]] = {}
        #: return-flow deferrals: (returner fid, callee fid, lineno)
        self.retdeps: List[Tuple[str, str, int]] = []

    def _emit(self, path: str, lineno: int, msg: str, hint: str) -> None:
        key = (path, msg)
        if key in self._seen:
            return
        self._seen.add(key)
        self.findings.append(Finding(RULE, path, lineno, msg, hint=hint))

    def _annotated(self, path: str, *lines: int) -> bool:
        src = self.g.sources.get(path) or []
        for ln in lines:                    # 1-based; look on and above
            for cand in (ln, ln - 1):
                if 0 < cand <= len(src) \
                        and _WITNESS_RE.search(src[cand - 1]):
                    return True
        return False

    def _witness_guarded(self, node: ast.AST) -> bool:
        keys: Set[str] = set()
        for g in _guards(node):
            keys |= _test_witness_keys(g.test)
        return keys >= {"op", "witness"}

    def _on_false_path(self, node: ast.AST) -> bool:
        return any(_test_has_false_cmp(g.test) for g in _guards(node))

    # -- provenance of a returned name ------------------------------------

    def _callee_of(self, fid: str, value: ast.AST) -> Optional[str]:
        """In-scope callee fid a call expression resolves to, else None."""
        if not isinstance(value, ast.Call):
            return None
        edge = self.g.edge_at.get(fid, {}).get(
            (value.lineno, value.col_offset))
        if edge is None or edge.kind != "call":
            return None
        callee = self.g.funcs[edge.callee]
        return callee.id if callee.path.startswith(SCOPE) else None

    def _build_env(self, f: FuncInfo) -> Dict[str, Tuple]:
        """name -> ("scope", callee fid) | ("opaque",) | ("raw",) for
        single-target assignments.  "opaque" covers dict literals (the
        construction site carries its own obligation) and calls outside
        the fission surface (SOUND01's jurisdiction); "raw" means the
        name holds a sub-result reaching us from a parameter."""
        env: Dict[str, Tuple] = {}
        for node in _walk_fn(f.node):
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            name = node.targets[0].id
            v = node.value
            callee = self._callee_of(f.id, v)
            if callee is not None:
                env[name] = ("scope", callee)
            elif isinstance(v, (ast.Call, ast.Dict)):
                env[name] = ("opaque",)
            else:
                env[name] = ("raw",)
        return env

    # -- per-function pass ------------------------------------------------

    def _analyze(self, f: FuncInfo) -> None:
        env = self._build_env(f)
        for node in _walk_fn(f.node):
            site = None                      # (lineno, description)
            if isinstance(node, ast.Dict):
                keys = {k.value for k in node.keys
                        if isinstance(k, ast.Constant)}
                for k, v in zip(node.keys, node.values):
                    if isinstance(k, ast.Constant) and k.value == "valid" \
                            and _is_false(v):
                        if {"op", "witness"} <= keys:
                            site = None      # evidence in the literal
                        else:
                            site = (k.lineno, "dict literal "
                                              "{'valid': False}")
                        if _in_handler(node):
                            self._handler_finding(f, k.lineno)
                            site = None
            elif isinstance(node, ast.Assign) and _is_false(node.value):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Subscript) \
                            and isinstance(tgt.slice, ast.Constant) \
                            and tgt.slice.value == "valid":
                        if _in_handler(node):
                            self._handler_finding(f, node.lineno)
                        else:
                            site = (node.lineno,
                                    "store result['valid'] = False")
            if site is not None:
                lineno, desc = site
                if not (self._witness_guarded(node)
                        or self._annotated(f.path, lineno,
                                           getattr(node, "lineno",
                                                   lineno))):
                    self.tainted.setdefault(f.id, (f.label,))
                    self._emit(
                        f.path, lineno,
                        f"unwitnessed {desc} at a fission merge site "
                        f"({f.label}): a recombined false must carry the "
                        f"refuting sub-problem's op + witness",
                        hint="guard on '\"op\" in r and \"witness\" in "
                             "r', put the evidence in the verdict, or "
                             "degrade to 'unknown'")
            if isinstance(node, ast.Return) and node.value is not None \
                    and not _in_handler(node) \
                    and self._on_false_path(node) \
                    and not self._witness_guarded(node) \
                    and not self._annotated(f.path, node.lineno):
                self._ret_site(f, env, node)

    def _handler_finding(self, f: FuncInfo, lineno: int) -> None:
        self.tainted.setdefault(f.id, (f.label,))
        self._emit(
            f.path, lineno,
            f"'valid: False' constructed inside an except handler at a "
            f"fission merge site ({f.label}): an exception path has no "
            f"witness and must degrade to 'unknown'",
            hint="return {'valid': 'unknown', 'error': ...}; false "
                 "requires a counterexample")

    def _ret_site(self, f: FuncInfo, env: Dict[str, Tuple],
                  node: ast.Return) -> None:
        v = node.value
        callee = self._callee_of(f.id, v)
        if callee is None and isinstance(v, ast.Name):
            prov = env.get(v.id, ("raw",))
            if prov[0] == "scope":
                callee = prov[1]
            elif prov[0] == "opaque":
                return
        elif callee is None and isinstance(v, (ast.Call, ast.Dict)):
            return                # construction/other-jurisdiction
        if callee is not None:
            self.retdeps.append((f.id, callee, node.lineno))
            return
        self._emit(
            f.path, node.lineno,
            f"sub-result passed through as the recombined verdict on a "
            f"refutation path in {f.label} with no witness guard: any "
            f"path from a 'valid: False' sub-result into a recombined "
            f"verdict must flow through a witness-bearing refutation "
            f"site",
            hint="test '\"op\" in r and \"witness\" in r' before "
                 "returning a child refutation, or degrade to "
                 "'unknown'")
        self.tainted.setdefault(f.id, (f.label,))

    # -- whole-program ----------------------------------------------------

    def run(self) -> List[Finding]:
        for f in sorted(self.scoped, key=lambda f: f.id):
            self._analyze(f)
        # return-flow taint: a merge function returning an in-scope
        # callee's refutation inherits that callee's obligation
        changed = True
        while changed:
            changed = False
            for fid, callee, lineno in self.retdeps:
                if callee in self.tainted and fid not in self.tainted:
                    f = self.g.funcs[fid]
                    chain = (f.label,) + self.tainted[callee]
                    self.tainted[fid] = chain
                    self._emit(
                        f.path, lineno,
                        f"refutation flows {' -> '.join(chain)} but "
                        f"originates at an unwitnessed 'valid: False' "
                        f"site: every false entering a recombined "
                        f"verdict must flow through a witness-bearing "
                        f"refutation site",
                        hint="fix the origin site (attach op + witness "
                             "there) — the pass-through is only as "
                             "sound as its source")
                    changed = True
        return self.findings


def check_program(graph: CallGraph) -> List[Finding]:
    return _Sound02(graph).run()
