"""DEV01: no host-sync or recompile hazards inside jit-traced code.

The device engines are compiled once per shape bucket and replayed
thousands of times; anything inside a traced function that forces a
host round-trip or a retrace silently turns a device-resident search
into a device<->host ping-pong (or a compile storm) that only bench
regressions reveal much later.  Hazards:

- ``.item()`` / ``.tolist()`` — a blocking device->host transfer per
  call, inside code that is supposed to stay on device;
- ``float()/int()/bool()`` **on a traced value** — implicit
  concretization: either a TracerError at trace time or, worse, a baked
  constant when the value happens to be static at one call site;
- ``np.*`` **on a traced value** — silently pulls the array to the host
  (numpy has no tracer protocol);
- ``if``/``while``/``for`` **on a traced value** — a data-dependent
  Python branch: trace-time concretization, and a fresh compile per
  taken path when it survives via static fallback.

What counts as traced: a function referenced inside a ``jax.jit(...)``
call in its module (``jax.jit(run_chunk)``, ``jax.jit(jax.vmap(lane))``),
every def nested inside a traced def (scan/cond/switch bodies), and
every lexically-visible def a traced body calls by name.  *Taint* then
tracks tracer values: parameters of traced functions are tracers;
assignments propagate; ``.shape/.ndim/.dtype``, ``len()``,
``isinstance()``, and ``is (not) None`` tests are static and clear
taint.  Engine-builder closure variables (``window``, ``capacity``,
``realtime``) are static Python and stay untainted, so config branches
like ``if single_round_closure:`` are — correctly — legal.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set, Tuple

from jepsen_tpu.lint.findings import Finding
from jepsen_tpu.lint.rules import dotted, walk_with_parents

RULE = "DEV01"

SCOPE = (
    "jepsen_tpu/parallel/",
    "jepsen_tpu/elle_tpu/",
    "jepsen_tpu/checker/",
    "jepsen_tpu/ops/",
    "jepsen_tpu/engine/",
)

_FN = (ast.FunctionDef, ast.AsyncFunctionDef)
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_CONCRETIZERS = {"float", "int", "bool", "complex"}
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding"}
_STATIC_CALLS = {"len", "isinstance", "type", "getattr", "hasattr", "range"}
_JIT_WRAPPERS = {"jax.jit", "jit"}


def _scope_chain(node: ast.AST) -> Tuple[ast.AST, ...]:
    """Enclosing FunctionDef chain, outermost first."""
    chain: List[ast.AST] = []
    cur = getattr(node, "parent", None)
    while cur is not None:
        if isinstance(cur, _FN):
            chain.append(cur)
        cur = getattr(cur, "parent", None)
    return tuple(reversed(chain))


def _visible(caller_chain: Tuple[ast.AST, ...],
             target: ast.AST) -> bool:
    """Is ``target``'s def lexically visible from a function with scope
    chain ``caller_chain``?  True when the target's enclosing chain is a
    prefix of the caller's chain (module-level defs, ancestors' siblings,
    own siblings)."""
    tchain = _scope_chain(target)
    return tchain == caller_chain[:len(tchain)]


def _body_names(fn: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(fn)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}


def _traced_defs(tree: ast.Module) -> Set[ast.AST]:
    """Fixpoint of jit-traced defs (see module docstring)."""
    defs = [n for n in ast.walk(tree) if isinstance(n, _FN)]
    by_name: Dict[str, List[ast.AST]] = {}
    for d in defs:
        by_name.setdefault(d.name, []).append(d)

    roots: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and dotted(node.func) in _JIT_WRAPPERS:
            for arg in node.args:
                roots.update(n.id for n in ast.walk(arg)
                             if isinstance(n, ast.Name))
    traced: Set[ast.AST] = {d for d in defs if d.name in roots}
    changed = True
    while changed:
        changed = False
        for t in list(traced):
            chain = _scope_chain(t) + (t,)
            for name in _body_names(t):
                for cand in by_name.get(name, ()):
                    if cand not in traced and _visible(chain, cand):
                        traced.add(cand)
                        changed = True
            for child in ast.walk(t):
                if isinstance(child, _FN) and child is not t \
                        and child not in traced:
                    traced.add(child)
                    changed = True
    return traced


def _tainted_expr(node: ast.AST, tainted: Set[str]) -> bool:
    """Does evaluating ``node`` touch a traced value?  Static constructs
    (shape/dtype reads, len(), is-None tests) clear taint."""
    if isinstance(node, ast.Name):
        return node.id in tainted
    if isinstance(node, ast.Attribute):
        if node.attr in _STATIC_ATTRS:
            return False
        return _tainted_expr(node.value, tainted)
    if isinstance(node, ast.Call):
        fname = dotted(node.func)
        if fname in _STATIC_CALLS:
            return False
        operands = list(node.args) + [kw.value for kw in node.keywords]
        if isinstance(node.func, ast.Attribute):
            operands.append(node.func.value)   # method receiver
        return any(_tainted_expr(a, tainted) for a in operands)
    if isinstance(node, ast.Compare):
        if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
            return False
    if isinstance(node, (ast.Lambda,) + _FN):
        return False
    return any(_tainted_expr(c, tainted)
               for c in ast.iter_child_nodes(node)
               if isinstance(c, ast.expr))


def _target_names(target: ast.AST) -> Iterator[str]:
    for n in ast.walk(target):
        if isinstance(n, ast.Name):
            yield n.id


class _FnAuditor:
    """Two-pass taint walk over one traced def (pass 1 accumulates taint,
    pass 2 reports), recursing into nested defs with inherited taint."""

    def __init__(self, path: str, qual: str):
        self.path = path
        self.qual = qual
        self.findings: List[Finding] = []

    def audit(self, fn: ast.AST, inherited: Set[str]) -> None:
        tainted = set(inherited)
        tainted.update(a.arg for a in fn.args.args
                       + fn.args.posonlyargs + fn.args.kwonlyargs)
        if fn.args.vararg:
            tainted.add(fn.args.vararg.arg)
        for report in (False, True):
            self._stmts(fn.body, tainted, report)
        for child in fn.body:
            self._recurse_nested(child, tainted)

    def _recurse_nested(self, node: ast.AST, tainted: Set[str]) -> None:
        if isinstance(node, _FN):
            sub = _FnAuditor(self.path, f"{self.qual}.{node.name}")
            sub.audit(node, tainted)
            self.findings.extend(sub.findings)
            return
        for child in ast.iter_child_nodes(node):
            self._recurse_nested(child, tainted)

    # -- statements --------------------------------------------------------
    def _stmts(self, body: List[ast.stmt], tainted: Set[str],
               report: bool) -> None:
        for stmt in body:
            self._stmt(stmt, tainted, report)

    def _stmt(self, stmt: ast.stmt, tainted: Set[str],
              report: bool) -> None:
        if isinstance(stmt, _FN):
            return                            # audited via _recurse_nested
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            value = stmt.value
            if value is not None:
                self._exprs(value, tainted, report)
                if _tainted_expr(value, tainted):
                    targets = stmt.targets if isinstance(stmt, ast.Assign) \
                        else [stmt.target]
                    for t in targets:
                        tainted.update(_target_names(t))
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._exprs(stmt.test, tainted, report)
            if report and _tainted_expr(stmt.test, tainted):
                kind = "if" if isinstance(stmt, ast.If) else "while"
                self._find(stmt.lineno,
                           f"data-dependent Python `{kind}` on a traced "
                           f"value in jitted code ({self.qual})",
                           "branch on device with jnp.where/lax.cond; "
                           "Python control flow concretizes the tracer")
            for b in (stmt.body, stmt.orelse):
                self._stmts(b, tainted, report)
            return
        if isinstance(stmt, ast.For):
            self._exprs(stmt.iter, tainted, report)
            if _tainted_expr(stmt.iter, tainted):
                if report:
                    self._find(stmt.lineno,
                               f"Python `for` over a traced value in "
                               f"jitted code ({self.qual})",
                               "use lax.scan/fori_loop; iterating a "
                               "tracer concretizes it")
                tainted.update(_target_names(stmt.target))
            self._stmts(stmt.body, tainted, report)
            self._stmts(stmt.orelse, tainted, report)
            return
        # generic: visit child expressions, then child statement blocks
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._exprs(child, tainted, report)
            elif isinstance(child, ast.stmt):
                self._stmt(child, tainted, report)
            elif isinstance(child, (ast.ExceptHandler,)):
                self._stmts(child.body, tainted, report)

    # -- expressions -------------------------------------------------------
    def _exprs(self, node: ast.expr, tainted: Set[str],
               report: bool) -> None:
        if not report:
            return
        for sub in ast.walk(node):
            if isinstance(sub, (ast.Lambda,) + _FN):
                continue
            if not isinstance(sub, ast.Call):
                continue
            fname = dotted(sub.func)
            args = list(sub.args) + [kw.value for kw in sub.keywords]
            if isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr in _SYNC_METHODS:
                self._find(sub.lineno,
                           f"`.{sub.func.attr}()` in jitted code "
                           f"({self.qual}): blocking device->host sync",
                           "keep the value on device; read scalars on "
                           "the host after the dispatch returns")
            elif fname.split(".")[0] in ("np", "numpy") \
                    and any(_tainted_expr(a, tainted) for a in args):
                self._find(sub.lineno,
                           f"`{fname}` applied to a traced value in "
                           f"jitted code ({self.qual}): implicit host "
                           f"transfer",
                           "use the jnp equivalent; numpy pulls the "
                           "array off device")
            elif isinstance(sub.func, ast.Name) \
                    and sub.func.id in _CONCRETIZERS \
                    and any(_tainted_expr(a, tainted) for a in args):
                self._find(sub.lineno,
                           f"`{sub.func.id}()` on a traced value in "
                           f"jitted code ({self.qual}): concretizes the "
                           f"tracer",
                           "use .astype()/jnp casts on device, or hoist "
                           "the read to the host driver")

    def _find(self, line: int, message: str, hint: str) -> None:
        self.findings.append(Finding(RULE, self.path, line, message, hint))


def check(tree: ast.Module, src_lines: List[str],
          path: str) -> Iterator[Finding]:
    list(walk_with_parents(tree))            # annotate parents
    traced = _traced_defs(tree)
    # Audit only "top" traced defs; nested traced defs are covered by the
    # recursive walk with inherited taint.
    for fn in traced:
        parent_fns = _scope_chain(fn)
        if parent_fns and parent_fns[-1] in traced:
            continue
        qual = ".".join([f.name for f in parent_fns] + [fn.name])
        auditor = _FnAuditor(path, qual)
        auditor.audit(fn, set())
        yield from auditor.findings
