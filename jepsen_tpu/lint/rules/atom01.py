"""ATOM01: a guarded check whose dependent act reacquires the lock.

Holding the right lock at every site (RACE01's contract) is not enough
when a *decision* spans two critical sections: read a field under the
lock, release, branch on the captured value, then reacquire the lock to
act — the field may have changed between the check and the act, and the
act applies a stale decision.  The classic shape::

    with self._lock:
        depth = self._depth          # check, under 'scheduler'
    if depth < limit:                # lock released here
        with self._lock:
            self._depth += 1         # act reacquires — not atomic

The rule is deliberately narrow (positively-detected patterns only, no
speculative dataflow): within one function it finds a name bound from a
tracked attribute inside a ``with`` of a declared lock, a later
``if``/``while`` whose test uses that name (or re-reads the attribute)
*outside* that critical section, and inside the branch an act that
writes the same attribute under a **fresh** acquisition of the same
lock — lexically, or through a call edge into a callee that may acquire
the lock and may write the attribute (the CONC02-style may-summaries).
A check and act inside one ``with`` block never fires; neither does a
re-check of the attribute after reacquiring (the double-checked idiom
re-reads under the lock before acting).

Messages are line-free symbol text; the finding's location is the
check, where the fix (widen the critical section, or re-validate under
the lock) belongs.  Sanctioned stale-decision sites carry
``# lint: disable=ATOM01(reason)``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from jepsen_tpu.lint import guards
from jepsen_tpu.lint.callgraph import CallGraph, FuncInfo
from jepsen_tpu.lint.findings import Finding
from jepsen_tpu.lint.guards import Lock
from jepsen_tpu.lint.lock_order import lock_level

RULE = "ATOM01"

SCOPE = ("jepsen_tpu/", "suites/")

_CLASS_SCOPE = ("jepsen_tpu/serve/", "jepsen_tpu/monitor/",
                "jepsen_tpu/obs/")

_FN = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _names(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _attrs_read(node: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for n in ast.walk(node):
        a = _self_attr(n)
        if a is not None and isinstance(n.ctx, ast.Load):
            out.add(a)
    return out


def _attrs_written(node: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, _FN):
            continue
        a = _self_attr(n)
        if a is not None and isinstance(n.ctx, (ast.Store, ast.Del)):
            out.add(a)
        if isinstance(n, ast.AugAssign):
            a = _self_attr(n.target)
            if a is not None:
                out.add(a)
    return out


def _with_locks(f: FuncInfo, node: ast.With) -> Set[Lock]:
    out: Set[Lock] = set()
    for item in node.items:
        try:
            expr_s = ast.unparse(item.context_expr)
        except Exception:  # pragma: no cover - defensive
            continue
        lv = lock_level(f.path, expr_s)
        if lv is not None:
            out.add(lv)
    return out


def _may_write_fixpoint(graph: CallGraph,
                        ga: guards.GuardAnalysis
                        ) -> Dict[str, Set[str]]:
    """attr names each function may write (self-attrs), transitively
    through call edges — the act side of a check-then-act may hide in a
    helper."""
    may: Dict[str, Set[str]] = {
        fid: {a.attr for a in s.accesses if a.is_write}
        for fid, s in ga.local.items()}
    changed = True
    while changed:
        changed = False
        for fid, edges in graph.out.items():
            s = may.get(fid)
            if s is None:
                continue
            for e in edges:
                if e.kind != "call":
                    continue
                callee = may.get(e.callee)
                if callee and not callee <= s:
                    s |= callee
                    changed = True
    return may


def _may_acquire_fixpoint(graph: CallGraph,
                          ga: guards.GuardAnalysis
                          ) -> Dict[str, Set[Lock]]:
    from jepsen_tpu.lint.rules import conc02
    may: Dict[str, Set[Lock]] = {
        fid: set(conc02._summarize(f).acquires)
        for fid, f in graph.funcs.items()}
    changed = True
    while changed:
        changed = False
        for fid, edges in graph.out.items():
            s = may.get(fid)
            if s is None:
                continue
            for e in edges:
                if e.kind != "call":
                    continue
                callee = may.get(e.callee)
                if callee and not callee <= s:
                    s |= callee
                    changed = True
    return may


def _act_reacquires(graph: CallGraph, f: FuncInfo, branch_body: List,
                    attr: str, lock: Lock,
                    may_write: Dict[str, Set[str]],
                    may_acquire: Dict[str, Set[Lock]]
                    ) -> Optional[str]:
    """Does the branch body write ``attr`` under a fresh acquisition of
    ``lock``?  Returns a human label for the act site, or None.  A
    re-read of ``attr`` inside the reacquired section before the write
    (double-checked idiom) clears the pattern."""
    for stmt in branch_body:
        for node in ast.walk(stmt):
            if isinstance(node, _FN):
                continue
            if isinstance(node, ast.With) and \
                    lock in _with_locks(f, node):
                body_reads: Set[str] = set()
                for inner in node.body:
                    # an If/While test re-reads before its body writes
                    if isinstance(inner, (ast.If, ast.While)):
                        body_reads |= _attrs_read(inner.test)
                    if attr in _attrs_written(inner) and \
                            attr not in body_reads:
                        return f"`with` in {f.label}"
                    body_reads |= _attrs_read(inner)
            if isinstance(node, ast.Call):
                edge = graph.edge_at.get(f.id, {}).get(
                    (node.lineno, node.col_offset))
                if edge is not None and edge.kind == "call" and \
                        lock in may_acquire.get(edge.callee, ()) and \
                        attr in may_write.get(edge.callee, ()):
                    return f"call to {graph.funcs[edge.callee].label}"
    return None


def _check_function(graph: CallGraph, ga: guards.GuardAnalysis,
                    f: FuncInfo, may_write: Dict[str, Set[str]],
                    may_acquire: Dict[str, Set[Lock]]
                    ) -> List[Finding]:
    findings: List[Finding] = []

    def scan_block(body: List, held: Tuple[Lock, ...]) -> None:
        #: name -> (attr, lock, check lineno) captured under a lock
        captured: Dict[str, Tuple[str, Lock, int]] = {}
        for stmt in body:
            if isinstance(stmt, _FN):
                continue
            if isinstance(stmt, ast.With):
                locks = _with_locks(f, stmt)
                for inner in stmt.body:
                    if isinstance(inner, ast.Assign) and \
                            len(inner.targets) == 1 and \
                            isinstance(inner.targets[0], ast.Name):
                        for attr in _attrs_read(inner.value):
                            for lk in locks:
                                captured[inner.targets[0].id] = \
                                    (attr, lk, inner.lineno)
                scan_block(stmt.body, held + tuple(sorted(locks)))
                continue
            if isinstance(stmt, (ast.If, ast.While)):
                test_names = _names(stmt.test)
                for name, (attr, lk, check_ln) in list(captured.items()):
                    if name not in test_names or lk in held:
                        continue
                    act = _act_reacquires(graph, f, stmt.body, attr, lk,
                                          may_write, may_acquire)
                    if act is not None:
                        findings.append(Finding(
                            RULE, f.path, check_ln,
                            f"check-then-act on `self.{attr}` in "
                            f"{f.label} is not atomic: the check reads "
                            f"it under '{lk[1]}' into `{name}`, the "
                            f"lock is released, and the dependent act "
                            f"({act}) reacquires '{lk[1]}' to write it "
                            f"— the checked value can be stale by the "
                            f"time the act runs",
                            hint="widen the critical section over "
                                 "check+act, or re-validate the field "
                                 "after reacquiring (double-checked "
                                 "idiom), or add `# lint: disable="
                                 "ATOM01(reason)` if staleness is "
                                 "acceptable here"))
                        del captured[name]
                scan_block(stmt.body, held)
                scan_block(stmt.orelse, held)
                continue
            # any other compound statement: recurse into blocks
            for field_name in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field_name, None)
                if isinstance(sub, list):
                    scan_block(sub, held)
            if isinstance(stmt, ast.Try):
                for h in stmt.handlers:
                    scan_block(h.body, held)
            # a write to the attr outside the pattern invalidates the
            # captured snapshot (the function re-synchronized its view)
            written = _attrs_written(stmt)
            for name in [n for n, (a, _l, _ln) in captured.items()
                         if a in written]:
                del captured[name]

    scan_block(f.node.body, ())
    return findings


def check_program(graph: CallGraph) -> List[Finding]:
    ga = guards.analyze(graph)
    may_write = _may_write_fixpoint(graph, ga)
    may_acquire = _may_acquire_fixpoint(graph, ga)
    findings: List[Finding] = []
    for fid, f in sorted(graph.funcs.items()):
        if f.cls is None or not any(f.path.startswith(p)
                                    for p in _CLASS_SCOPE):
            continue
        findings.extend(_check_function(graph, ga, f, may_write,
                                        may_acquire))
    return findings
