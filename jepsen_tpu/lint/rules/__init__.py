"""Project-specific AST rules: one invariant per module.

Each rule module exports:

- ``RULE`` — the finding id (e.g. ``"SOUND01"``);
- ``SCOPE`` — repo-relative path prefixes the rule audits;
- ``check(tree, src_lines, path)`` — yields :class:`~jepsen_tpu.lint
  .findings.Finding` for one parsed module.

The catalog (rationale per rule lives in docs/static_analysis.md):

- SOUND01 — verdicts may degrade valid -> unknown, never valid -> false,
  so a literal ``valid: False`` is legal only at witness-bearing sites;
- DEV01   — no host syncs or data-dependent Python branches inside
  jit-traced engine code;
- SHAPE01 — every engine-entry shape in serve/ derives from the bucket
  ladder, never from raw history shape;
- CONC01  — monotonic-clock discipline, lock-order manifest, no blocking
  I/O while holding a lock;
- OBS01   — span discipline on the tracing plane: exported durations
  are monotonic intervals, the wall anchor is export-alignment only,
  trace identity is plumbed, never minted from literals;
- ENV01   — every literal JEPSEN_TPU_*/JTPU_* env read is documented in
  README.md's environment table (verbatim or via a placeholder family
  row).
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Tuple


def in_scope(path: str, scope: Tuple[str, ...]) -> bool:
    return any(path.startswith(p) for p in scope)


def walk_with_parents(tree: ast.AST) -> Iterator[ast.AST]:
    """ast.walk, but every yielded node carries ``.parent``."""
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child.parent = node  # type: ignore[attr-defined]
    return ast.walk(tree)


def qualname_of(node: ast.AST) -> str:
    """Dotted enclosing-scope name of a node (requires walk_with_parents
    to have annotated parents)."""
    parts: List[str] = []
    cur = getattr(node, "parent", None)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            parts.append(cur.name)
        cur = getattr(cur, "parent", None)
    return ".".join(reversed(parts)) or "<module>"


def enclosing_handler(node: ast.AST) -> Optional[ast.ExceptHandler]:
    """The nearest ``except`` handler lexically containing ``node``, not
    crossing a function boundary (a nested def's body runs later, outside
    the handler's dynamic extent)."""
    cur = getattr(node, "parent", None)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            return None
        if isinstance(cur, ast.ExceptHandler):
            return cur
        cur = getattr(cur, "parent", None)
    return None


def names_in(node: ast.AST) -> List[str]:
    return [n.id for n in ast.walk(node) if isinstance(n, ast.Name)]


def dotted(node: ast.AST) -> str:
    """``a.b.c`` for Name/Attribute chains, '' otherwise."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else ""
    return ""


def all_rules():
    from jepsen_tpu.lint.rules import (conc01, dev01, env01, obs01,
                                       shape01, sound01)
    return (sound01, dev01, shape01, conc01, obs01, env01)


def interp_rules():
    """The interprocedural (call-graph) rules.  Unlike :func:`all_rules`
    modules, these export ``check_program(graph)`` — they see the whole
    program through :mod:`jepsen_tpu.lint.callgraph`, not one module:

    - CONC02 — cross-function lock-chain inversions + lock-manifest
      drift (every Lock() under serve|monitor|obs must be declared);
    - SEC01  — the fleet token (and HMAC material derived from it)
      never reaches logs, exceptions, metrics, frames outside the
      ``auth`` field, or files;
    - DL01   — deadlines cross process boundaries only as remaining
      budget, never as wall-clock or absolute monotonic values;
    - SOUND02 — unknown-never-false dataflow-proven across the fission
      merge surface: any 'valid: False' sub-result reaching a
      recombined verdict flows through a witness-bearing site.

    The Warden tier (lint/guards.py's guarded-by inference) rides the
    same graph:

    - RACE01 — every shared mutable attribute of the threaded
      subsystems has a consistent declared guard (Eraser-style lockset
      intersection over all post-publication access sites);
    - ATOM01 — no guarded check whose dependent act reacquires the
      lock (check-then-act torn across two critical sections);
    - RES01  — every constructed Request/Cell reaches a finish
      terminal on all paths including raise edges (no leaked
      admissions).
    """
    from jepsen_tpu.lint.rules import (atom01, conc02, dl01, race01,
                                       res01, sec01, sound02)
    return (conc02, sec01, dl01, sound02, race01, atom01, res01)
