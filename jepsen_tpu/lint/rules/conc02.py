"""CONC02: interprocedural lock discipline — cross-function lock chains
and manifest drift.

CONC01 proves the lock order only where both acquisitions share a
function; the deepest stacks in the repo (fleet-supervisor -> fleet ->
fleet-registry -> fleet-slot -> transport) span five files, so a PR can
introduce an inversion no single function shows.  Two whole-program
checks close that hole:

1. **Held-lock propagation.**  For every function the rule computes the
   set of declared locks (lock_order.py manifest) it *may acquire*,
   transitively through resolved call edges.  A call site that holds a
   declared lock and reaches — through any chain of calls — an
   acquisition of an earlier-or-equal-level lock is an inversion, and
   the finding prints the offending chain.  ``kind="thread"`` edges do
   not propagate: the target runs on a fresh stack without the
   spawner's locks.  The propagation is an over-approximation (every
   call edge is assumed feasible); calls the graph cannot resolve are
   listed in the call-graph dump's ``unresolved`` ledger rather than
   silently assumed lock-free — see callgraph.py's conservatism
   contract.

2. **Manifest drift.**  Every ``threading.Lock()`` / ``RLock()``
   construction under ``jepsen_tpu/serve|monitor|obs`` must match a
   lock_order.py manifest entry (by the expression its holders will
   acquire it through) or carry a pragma.  Without this, a brand-new
   lock silently escapes both CONC01 and the propagation above — the
   analyzer would vouch for an order it never saw.

Finding messages carry symbol chains, never line numbers, so the
baseline ledger keys (rule, path, symbol-chain) and unrelated edits
don't churn it.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from jepsen_tpu.lint.callgraph import CallGraph, FuncInfo
from jepsen_tpu.lint.findings import Finding
from jepsen_tpu.lint.lock_order import lock_level

RULE = "CONC02"

SCOPE = ("jepsen_tpu/", "suites/")

#: trees whose Lock constructions must be manifest-covered
_DRIFT_SCOPE = ("jepsen_tpu/serve/", "jepsen_tpu/monitor/",
                "jepsen_tpu/obs/")

_FN = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


# -- per-function local summaries ---------------------------------------------

class _Local:
    """What one function does with declared locks, lexically."""

    def __init__(self) -> None:
        #: (level, name) acquired anywhere in the body
        self.acquires: Set[Tuple[int, str]] = set()
        #: call sites: (lineno, col, held [(level, name)])
        self.callsites: List[Tuple[int, int,
                                   Tuple[Tuple[int, str], ...]]] = []


def _summarize(f: FuncInfo) -> _Local:
    out = _Local()

    def visit(node: ast.AST, held: Tuple[Tuple[int, str], ...]) -> None:
        if isinstance(node, _FN):
            return                      # separate graph node / deferred
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new_held = held
            for item in node.items:
                try:
                    expr_s = ast.unparse(item.context_expr)
                except Exception:  # pragma: no cover - defensive
                    expr_s = ""
                lv = lock_level(f.path, expr_s)
                if lv is not None:
                    out.acquires.add(lv)
                    new_held = new_held + (lv,)
            for child in node.body:
                visit(child, new_held)
            return
        if isinstance(node, ast.Call):
            out.callsites.append((node.lineno, node.col_offset, held))
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in f.node.body:
        visit(stmt, ())
    return out


# -- transitive may-acquire ----------------------------------------------------

def _fixpoint(graph: CallGraph,
              local: Dict[str, _Local]) -> Dict[str, Set[Tuple[int, str]]]:
    summary = {fid: set(loc.acquires) for fid, loc in local.items()}
    changed = True
    while changed:
        changed = False
        for fid, edges in graph.out.items():
            s = summary.get(fid)
            if s is None:
                continue
            for e in edges:
                if e.kind != "call":
                    continue
                callee = summary.get(e.callee)
                if callee and not callee <= s:
                    s |= callee
                    changed = True
    return summary


def _chain_to(graph: CallGraph, start: str, lock: Tuple[int, str],
              local: Dict[str, _Local],
              summary: Dict[str, Set[Tuple[int, str]]]) -> List[str]:
    """Shortest call chain (function ids) from ``start`` to a function
    that lexically acquires ``lock``."""
    seen = {start}
    queue: List[Tuple[str, List[str]]] = [(start, [start])]
    while queue:
        fid, path = queue.pop(0)
        if lock in local[fid].acquires:
            return path
        for e in graph.out.get(fid, []):
            if e.kind != "call" or e.callee in seen:
                continue
            if e.callee in summary and lock in summary[e.callee]:
                seen.add(e.callee)
                queue.append((e.callee, path + [e.callee]))
    return [start]                      # pragma: no cover - summary invariant


def _check_chains(graph: CallGraph) -> List[Finding]:
    local = {fid: _summarize(f) for fid, f in graph.funcs.items()}
    summary = _fixpoint(graph, local)
    findings: List[Finding] = []
    seen: Set[Tuple[str, str, str, str]] = set()
    for fid, loc in local.items():
        f = graph.funcs[fid]
        for lineno, col, held in loc.callsites:
            if not held:
                continue
            edge = graph.edge_at.get(fid, {}).get((lineno, col))
            if edge is None or edge.kind != "call":
                continue
            for lock in sorted(summary.get(edge.callee, ())):
                level, name = lock
                for hlevel, hname in held:
                    if level > hlevel:
                        continue
                    chain = [fid] + _chain_to(graph, edge.callee, lock,
                                              local, summary)
                    chain_s = " -> ".join(graph.funcs[c].label
                                          for c in chain)
                    key = (f.path, chain_s, name, hname)
                    if key in seen:
                        continue
                    seen.add(key)
                    findings.append(Finding(
                        RULE, f.path, lineno,
                        f"interprocedural lock-order inversion: call "
                        f"chain {chain_s} may acquire '{name}' (level "
                        f"{level}) while '{hname}' (level {hlevel}) is "
                        f"held at the call site",
                        hint="acquire locks in the lock_order.py "
                             "manifest order along every call chain, or "
                             "move the call outside the critical "
                             "section"))
    return findings


# -- manifest drift ------------------------------------------------------------

def _lock_ctor(graph: CallGraph, path: str, value: ast.AST) -> bool:
    if not isinstance(value, ast.Call):
        return False
    d = ""
    if isinstance(value.func, (ast.Name, ast.Attribute)):
        try:
            d = ast.unparse(value.func)
        except Exception:  # pragma: no cover - defensive
            return False
    m = graph.modules.get(path)
    ext = graph.external_name(m, d) if m else None
    return (ext or d) in ("threading.Lock", "threading.RLock",
                          "Lock", "RLock")


def _qual_at(f_by_line: List[Tuple[int, int, str]], lineno: int) -> str:
    best = "<module>"
    for start, end, qual in f_by_line:
        if start <= lineno <= end:
            best = qual
    return best


def _check_drift(graph: CallGraph) -> List[Finding]:
    findings: List[Finding] = []
    for path, m in sorted(graph.modules.items()):
        if not any(path.startswith(p) for p in _DRIFT_SCOPE):
            continue
        spans = [(f.lineno, max(f.lineno,
                                getattr(f.node, "end_lineno", f.lineno)),
                  f.qual)
                 for f in graph.funcs.values() if f.path == path]
        spans.sort()
        for node in ast.walk(m.tree):
            targets: List[ast.AST] = []
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            else:
                continue
            if not _lock_ctor(graph, path, value):
                continue
            for t in targets:
                try:
                    t_s = ast.unparse(t)
                except Exception:  # pragma: no cover - defensive
                    continue
                if lock_level(path, t_s) is not None:
                    continue
                qual = _qual_at(spans, node.lineno)
                findings.append(Finding(
                    RULE, path, node.lineno,
                    f"undeclared lock `{t_s}` constructed in {qual}: "
                    f"every Lock()/RLock() under serve|monitor|obs "
                    f"must match a lock_order.py manifest entry, or "
                    f"both CONC01 and CONC02 are blind to it",
                    hint="add a manifest entry at the level matching "
                         "its acquisition order, or add `# lint: "
                         "disable=CONC02(reason)` if the lock is "
                         "provably leaf-local"))
    return findings


def check_program(graph: CallGraph) -> List[Finding]:
    return _check_chains(graph) + _check_drift(graph)
