"""ENV01: every environment knob the code reads is documented.

The service is configured through ``JEPSEN_TPU_*`` / ``JTPU_*``
environment variables, and README.md's environment table is the single
operator-facing inventory.  A knob the code reads but the table omits is
invisible configuration: deployments copy the table, so the knob is
effectively unusable — or worse, used with a stale name after a rename.

The rule finds every *literal* env read in scope —

- ``os.environ.get("JTPU_X")`` / ``os.environ.get("JTPU_X", d)``
- ``os.getenv("JTPU_X")``
- ``os.environ["JTPU_X"]``
- ``"JTPU_X" in os.environ``

(also through ``from os import environ, getenv`` aliases) — and requires
the name to appear in README.md: either verbatim, or covered by a
placeholder family row such as ``JEPSEN_TPU_SLO_<NAME>`` or an
optional-suffix row like ``JEPSEN_TPU_TENANT_QUOTA[_<NAME>]``
(``<...>`` matches any ``[A-Z0-9_]+`` run; ``[...]`` is optional).

Knobs read through a *computed* name (``os.environ.get(name)`` where
``name`` is built at runtime — the autoscaler's ``_env_num`` helper
pattern) are out of scope here by construction: the literal sits at the
helper's call sites, where this rule sees it.

The message carries the knob name and the reading symbol, no line
numbers, so the baseline key is stable; a deliberately-undocumented
knob (test-only escape hatches) carries
``# lint: disable=ENV01(reason)`` at the read.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterator, List, Optional

from jepsen_tpu.lint.findings import Finding
from jepsen_tpu.lint.rules import qualname_of, walk_with_parents

RULE = "ENV01"

SCOPE = ("jepsen_tpu/", "suites/")

_PREFIX_RE = re.compile(r"^(JEPSEN_TPU|JTPU)_")

#: README rows: a knob token, possibly with <PLACEHOLDER> runs and
#: [optional] groups
_DOC_TOKEN_RE = re.compile(
    r"(?:JEPSEN_TPU|JTPU)(?:_[A-Z0-9]+|_?<[A-Za-z_]+>|\[[^\]\n]*\])+")

_README_CACHE: dict = {}


def _readme_patterns(readme_path: Optional[str] = None) -> List[re.Pattern]:
    """Compiled matchers for every documented knob token in README.md."""
    if readme_path is None:
        from jepsen_tpu.lint.ast_lint import repo_root
        readme_path = os.path.join(repo_root(), "README.md")
    cached = _README_CACHE.get(readme_path)
    if cached is not None:
        return cached
    try:
        with open(readme_path) as f:
            text = f.read()
    except OSError:
        text = ""
    pats: List[re.Pattern] = []
    for tok in sorted(set(_DOC_TOKEN_RE.findall(text))):
        esc = re.escape(tok)
        # optional [...] groups first (their contents may hold a
        # placeholder), then <PLACEHOLDER> runs
        esc = re.sub(r"\\\[([^\]]*)\\\]", r"(?:\1)?", esc)
        esc = re.sub(r"<[A-Za-z_]+>", "[A-Z0-9_]+", esc)
        try:
            pats.append(re.compile(f"^{esc}$"))
        except re.error:  # pragma: no cover - defensive
            continue
    _README_CACHE[readme_path] = pats
    return pats


def documented(knob: str, readme_path: Optional[str] = None) -> bool:
    return any(p.match(knob) for p in _readme_patterns(readme_path))


def _env_reads(tree: ast.AST) -> Iterator[ast.AST]:
    """Nodes whose first string argument/key is an env-var name read
    through os.environ / os.getenv (dotted or imported bare)."""

    def is_environ(node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id == "environ"
        if isinstance(node, ast.Attribute):
            return node.attr == "environ" and \
                isinstance(node.value, ast.Name) and node.value.id == "os"
        return False

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            f = node.func
            # os.environ.get(...) / environ.get(...)
            if isinstance(f, ast.Attribute) and f.attr == "get" and \
                    is_environ(f.value) and node.args:
                yield node.args[0]
            # os.getenv(...) / getenv(...)
            elif ((isinstance(f, ast.Attribute) and f.attr == "getenv"
                   and isinstance(f.value, ast.Name)
                   and f.value.id == "os")
                  or (isinstance(f, ast.Name) and f.id == "getenv")) \
                    and node.args:
                yield node.args[0]
        elif isinstance(node, ast.Subscript) and is_environ(node.value):
            yield node.slice
        elif isinstance(node, ast.Compare) and len(node.ops) == 1 and \
                isinstance(node.ops[0], (ast.In, ast.NotIn)) and \
                is_environ(node.comparators[0]):
            yield node.left


def check(tree: ast.AST, src_lines: List[str],
          path: str) -> List[Finding]:
    findings: List[Finding] = []
    walk_with_parents(tree)                 # annotate for qualname_of
    seen = set()
    for arg in _env_reads(tree):
        if not (isinstance(arg, ast.Constant)
                and isinstance(arg.value, str)):
            continue                        # computed name: out of scope
        knob = arg.value
        if not _PREFIX_RE.match(knob) or documented(knob):
            continue
        qual = qualname_of(arg)
        key = (knob, qual)
        if key in seen:
            continue
        seen.add(key)
        findings.append(Finding(
            RULE, path, arg.lineno,
            f"env knob `{knob}` read in {qual} is not in README.md's "
            f"environment table — undocumented configuration is "
            f"unusable configuration",
            hint="add a row to README.md's env table (name, default, "
                 "what it does), or `# lint: disable=ENV01(reason)` "
                 "for a deliberately-internal knob"))
    return findings
