"""CONC01: clock discipline and lock discipline for the threaded layers.

Three invariants, one rule:

1. **Monotonic time.**  Every interval, deadline, and timeout in the
   library uses ``jepsen_tpu.clock.mono_now`` (``time.monotonic``), never
   ``time.time()``.  Wall clock steps under NTP adjustment — a deadline
   computed from it can expire hours early or never, and a serve/
   deadline that never expires wedges a batch slot forever.  Wall-clock
   *timestamps* for humans are legal but must say so with a pragma:
   ``# lint: disable=CONC01(user-facing wall clock)``.

2. **Lock order.**  Acquiring a declared lock (see
   :mod:`jepsen_tpu.lint.lock_order`) lexically inside a ``with`` that
   holds a later-or-equal one is an inversion: two threads taking the
   pair in opposite orders deadlock under load.  This check is
   deliberately syntactic — lexical ``with`` nesting only; inversions
   that span function boundaries are CONC02's job
   (:mod:`jepsen_tpu.lint.rules.conc02`, which propagates held-lock
   sets through the whole-program call graph).

3. **No blocking I/O under a declared lock.**  ``time.sleep``,
   ``subprocess``, sockets, HTTP, and ``open()`` inside a held declared
   lock stall every thread queued on that lock (the scheduler cond, the
   monitor flush) for the duration of the I/O.

Nested ``def``s reset the held-lock context: their bodies run later,
outside the ``with``'s dynamic extent.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Set, Tuple

from jepsen_tpu.lint.findings import Finding
from jepsen_tpu.lint.lock_order import lock_level
from jepsen_tpu.lint.rules import dotted, qualname_of, walk_with_parents

RULE = "CONC01"

SCOPE = ("jepsen_tpu/", "suites/")

_FN = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)

_BLOCKING_EXACT = {"time.sleep", "sleep", "os.system", "open",
                   "socket.create_connection"}
_BLOCKING_PREFIXES = ("subprocess.", "requests.", "urllib.")


# -- wall-clock discipline ----------------------------------------------------

def _wallclock_names(tree: ast.Module) -> Tuple[Set[str], Set[str]]:
    """(module aliases of ``time``, local names bound to ``time.time``)."""
    mods: Set[str] = set()
    fns: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "time":
                    mods.add(alias.asname or "time")
        elif isinstance(node, ast.ImportFrom) and node.module == "time":
            for alias in node.names:
                if alias.name == "time":
                    fns.add(alias.asname or "time")
    return mods, fns


def _check_wallclock(tree: ast.Module, path: str) -> Iterator[Finding]:
    mods, fns = _wallclock_names(tree)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        d = dotted(node.func)
        parts = d.split(".")
        if (len(parts) == 2 and parts[0] in mods and parts[1] == "time") \
                or (len(parts) == 1 and d in fns):
            yield Finding(
                RULE, path, node.lineno,
                f"`{d}()` in {qualname_of(node)}: wall clock is not "
                f"monotonic — deadlines and intervals computed from it "
                f"break under NTP steps",
                hint="use jepsen_tpu.clock.mono_now() for intervals/"
                     "deadlines; for a user-facing timestamp add "
                     "`# lint: disable=CONC01(user-facing wall clock)`")


# -- lock order + blocking I/O under lock ------------------------------------

class _LockWalker:
    def __init__(self, path: str):
        self.path = path
        self.findings: List[Finding] = []

    def visit(self, node: ast.AST,
              held: List[Tuple[int, str, int]]) -> None:
        if isinstance(node, _FN):
            # a nested def's body runs outside the with's dynamic extent
            for child in ast.iter_child_nodes(node):
                self.visit(child, [])
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new_held = list(held)
            for item in node.items:
                try:
                    expr_s = ast.unparse(item.context_expr)
                except Exception:  # pragma: no cover - defensive
                    expr_s = ""
                lv = lock_level(self.path, expr_s)
                if lv is None:
                    continue
                level, name = lv
                for hlevel, hname, hline in new_held:
                    if level <= hlevel:
                        self.findings.append(Finding(
                            RULE, self.path, item.context_expr.lineno,
                            f"lock-order inversion: acquiring "
                            f"'{name}' (level {level}) while holding "
                            f"'{hname}' (level {hlevel}, line {hline})",
                            hint="acquire locks in the manifest order "
                                 "declared in jepsen_tpu/lint/"
                                 "lock_order.py, or split the critical "
                                 "section"))
                new_held.append((level, name, item.context_expr.lineno))
            for child in node.body:
                self.visit(child, new_held)
            return
        if held and isinstance(node, ast.Call):
            d = dotted(node.func)
            if d in _BLOCKING_EXACT \
                    or any(d.startswith(p) for p in _BLOCKING_PREFIXES):
                _, hname, _ = held[-1]
                self.findings.append(Finding(
                    RULE, self.path, node.lineno,
                    f"blocking call `{d}(...)` while holding lock "
                    f"'{hname}': every thread queued on the lock stalls "
                    f"for the I/O",
                    hint="move the I/O outside the critical section; "
                         "snapshot state under the lock, write after "
                         "releasing it"))
        for child in ast.iter_child_nodes(node):
            self.visit(child, held)


def check(tree: ast.Module, src_lines: List[str],
          path: str) -> Iterator[Finding]:
    list(walk_with_parents(tree))            # annotate parents for qualnames
    yield from _check_wallclock(tree, path)
    walker = _LockWalker(path)
    walker.visit(tree, [])
    yield from walker.findings
