"""RES01: an admitted Request/Cell always reaches a finish terminal.

The serve plane's second load-bearing invariant (after unknown-never-
false) is "an admitted request is always resolved, never dropped": every
``Request``/``Cell`` that enters the lifecycle must reach
``claim_finish()`` / ``finish()`` / a ``_finish_*`` / ``_finalize*``
terminal on **every** path — including the raise edges.  Today that is
pinned dynamically (expiry-while-blocked smokes, chaos suites); this
rule proves the per-function discipline statically.

Per function, the rule tracks each name bound from a ``Request(...)`` /
``Cell(...)`` construction (resolved through the call graph, so aliased
imports and subclasses count).  From that binding until the obligation
is **discharged**, every statement that can raise is a leak edge unless
a protector is in scope.  Discharge events:

- a terminal call on the object (``req.claim_finish()``,
  ``req.finish(...)``, ``self._finish_expired(req)``, ...);
- a hand-off: the object passed as an argument to any resolved call or
  thread spawn, stored into an attribute/container, returned or yielded
  — ownership moved, the new owner's own discipline applies;
- entering a ``try`` whose ``finally`` or catch-all handler reaches a
  terminal for the object (directly, or via a callee that may call a
  terminal — the may-terminal summary propagates through call edges).

Statements that cannot raise on the tracked path (constant/name
assignments, attribute writes on the object itself, ``pass``) do not
open leak edges; anything containing an unrelated call or an explicit
``raise``/bare ``return`` does.  The finding names the function, the
object, and the leaking expression — line-free, so baseline/SARIF keys
survive line churn; the location is the leaking statement, where either
the ``try/finally`` or the ``# lint: disable=RES01(reason)`` belongs.

What this rule does *not* prove (the conservatism contract): ownership
through untracked parameters (a helper that receives a live cell is
audited only at its call sites' hand-off boundary), and containers as
queues (once stored, the consumer side's discipline is the scheduler
loop's catch-all — covered by its own creation-site window when the
consumer also constructs, else by the chaos smokes).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set

from jepsen_tpu.lint.callgraph import CallGraph, FuncInfo
from jepsen_tpu.lint.findings import Finding

RULE = "RES01"

SCOPE = ("jepsen_tpu/", "suites/")

#: classes whose instances carry the resolve obligation
_TRACKED_CLASSES = ("Request", "Cell")

#: method/function names that resolve the obligation
_TERMINAL_RE = re.compile(r"^(claim_finish|finish|cancel"
                          r"|_finish\w*|_finalize\w*)$")

_FN = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _tracked_ctor_classes(graph: CallGraph) -> Set[str]:
    """fids of ``__init__`` methods of Request/Cell (and subclasses)."""
    out: Set[str] = set()
    for cid, info in graph.classes.items():
        names = {info.name}
        stack = [(graph.modules.get(info.path), b) for b in info.bases]
        while stack:
            m, b = stack.pop()
            t = graph.resolve_dotted(m, b) if m else None
            if t and t[0] == "class":
                base = graph.classes[t[1]]
                names.add(base.name)
                bm = graph.modules.get(base.path)
                stack.extend((bm, bb) for bb in base.bases)
        if names & set(_TRACKED_CLASSES):
            init = graph.method_of(cid, "__init__")
            if init:
                out.add(init)
    return out


def _may_terminal_fixpoint(graph: CallGraph) -> Set[str]:
    """Functions that call a terminal-named method, transitively."""
    may: Set[str] = set()
    for fid, f in graph.funcs.items():
        for node in ast.walk(f.node):
            if isinstance(node, ast.Call):
                name = None
                if isinstance(node.func, ast.Attribute):
                    name = node.func.attr
                elif isinstance(node.func, ast.Name):
                    name = node.func.id
                if name and _TERMINAL_RE.match(name):
                    may.add(fid)
                    break
    changed = True
    while changed:
        changed = False
        for fid, edges in graph.out.items():
            if fid in may:
                continue
            for e in edges:
                if e.callee in may:
                    may.add(fid)
                    changed = True
                    break
    return may


def _uses_name(node: ast.AST, name: str) -> bool:
    return any(isinstance(n, ast.Name) and n.id == name
               for n in ast.walk(node))


def _discharges(graph: CallGraph, f: FuncInfo, stmt: ast.stmt,
                name: str, may_terminal: Set[str]) -> bool:
    """Does this statement resolve or hand off the tracked object?"""
    for node in ast.walk(stmt):
        if isinstance(node, _FN):
            continue
        if isinstance(node, ast.Return) and node.value is not None \
                and _uses_name(node.value, name):
            return True
        if isinstance(node, (ast.Yield, ast.YieldFrom)) \
                and node.value is not None \
                and _uses_name(node.value, name):
            return True
        if isinstance(node, ast.Call):
            # terminal invoked on the object itself
            if isinstance(node.func, ast.Attribute) and \
                    _TERMINAL_RE.match(node.func.attr) and \
                    _uses_name(node.func.value, name):
                return True
            # the object passed onward: to a terminal-named callee, a
            # may-terminal callee, a thread spawn, or any call at all —
            # ownership is no longer this function's alone
            args = list(node.args) + [kw.value for kw in node.keywords]
            if any(_uses_name(a, name) for a in args):
                return True
        if isinstance(node, ast.Assign):
            if _uses_name(node.value, name):
                for t in node.targets:
                    if isinstance(t, (ast.Attribute, ast.Subscript)):
                        return True         # stored: published/handed off
    return False


def _may_raise(stmt: ast.stmt, name: str) -> Optional[str]:
    """The source text of the first raise edge in this statement that
    does not involve the tracked object, or None when the statement is
    raise-free.  Attribute stores on the object itself (``n.seq = 7``)
    and trivial assignments don't raise on the tracked path."""
    for node in ast.walk(stmt):
        if isinstance(node, _FN):
            continue
        if isinstance(node, ast.Raise):
            try:
                return ast.unparse(node)
            except Exception:  # pragma: no cover - defensive
                return "raise"
        if isinstance(node, ast.Call) and not _uses_name(node, name):
            # calls on/with the object itself were hand-off/terminal
            # candidates already; an unrelated call is the leak edge
            try:
                return ast.unparse(node)[:60]
            except Exception:  # pragma: no cover - defensive
                return "a call"
    return None


def _protected(graph: CallGraph, f: FuncInfo, try_stmt: ast.Try,
               name: str, may_terminal: Set[str]) -> bool:
    """Does the try's finally or a catch-all handler reach a terminal
    (or hand the object off) for the tracked name?"""
    blocks: List[List[ast.stmt]] = []
    if try_stmt.finalbody:
        blocks.append(try_stmt.finalbody)
    for h in try_stmt.handlers:
        is_catch_all = h.type is None or (
            isinstance(h.type, ast.Name) and
            h.type.id in ("Exception", "BaseException"))
        if is_catch_all:
            blocks.append(h.body)
    for body in blocks:
        for stmt in body:
            if _discharges(graph, f, stmt, name, may_terminal):
                return True
            # a catch-all that delegates wholesale to a may-terminal
            # callee (the scheduler loop's `self._finalize_all()` shape)
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call):
                    edge = graph.edge_at.get(f.id, {}).get(
                        (node.lineno, node.col_offset))
                    if edge is not None and edge.callee in may_terminal:
                        return True
    return False


def _check_function(graph: CallGraph, f: FuncInfo, ctors: Set[str],
                    may_terminal: Set[str]) -> List[Finding]:
    findings: List[Finding] = []

    def scan_block(body: List[ast.stmt]) -> None:
        #: live obligations: name -> class label
        live: Dict[str, str] = {}
        for stmt in body:
            if isinstance(stmt, _FN):
                continue
            # discharge first: a statement may both bind and hand off
            for name in [n for n in live
                         if _discharges(graph, f, stmt, n, may_terminal)]:
                del live[name]
            if isinstance(stmt, ast.Try):
                for name in list(live):
                    if _protected(graph, f, stmt, name, may_terminal):
                        del live[name]
            for name, label in sorted(live.items()):
                edge_src = _may_raise(stmt, name)
                if edge_src is not None:
                    findings.append(Finding(
                        RULE, f.path, stmt.lineno,
                        f"admitted {label} `{name}` in {f.label} can "
                        f"leak on a raise edge: `{edge_src}` may raise "
                        f"after the {label} is constructed and before "
                        f"any finish terminal or hand-off; no "
                        f"try/finally or catch-all reaches "
                        f"claim_finish()/_finish_*/_finalize* for it",
                        hint="wrap the admission window in try/finally "
                             "that resolves the object, hand it off "
                             "first, or add `# lint: disable=RES01"
                             "(reason)` if the raise provably cannot "
                             "leak it"))
                    del live[name]
            # new obligations bound by this statement
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name) \
                    and isinstance(stmt.value, ast.Call):
                edge = graph.edge_at.get(f.id, {}).get(
                    (stmt.value.lineno, stmt.value.col_offset))
                if edge is not None and edge.callee in ctors:
                    cls = graph.funcs[edge.callee].qual.split(".")[0]
                    live[stmt.targets[0].id] = cls
            # recurse into compound statements with a fresh window —
            # obligations do not cross block boundaries (conservatively
            # narrow: the lexical window is the contract)
            for field_name in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field_name, None)
                if isinstance(sub, list):
                    scan_block(sub)
            if isinstance(stmt, ast.Try):
                for h in stmt.handlers:
                    scan_block(h.body)

    scan_block(f.node.body)
    return findings


def check_program(graph: CallGraph) -> List[Finding]:
    ctors = _tracked_ctor_classes(graph)
    if not ctors:
        return []
    may_terminal = _may_terminal_fixpoint(graph)
    findings: List[Finding] = []
    for fid, f in sorted(graph.funcs.items()):
        findings.extend(_check_function(graph, f, ctors, may_terminal))
    return findings
