"""SHAPE01: engine-entry shapes in serve/ derive from the bucket ladder.

The serving layer's whole compile-cache story rests on one discipline:
every shape that reaches a device engine (pad targets, window floors,
chunk sizes) comes from ``serve/buckets.py``'s power-of-two ladder, so
the set of compiled signatures is bounded by the ladder, not by the
traffic.  One call site that pads to a raw history length (``len(h)``,
``max(p.window ...)``) silently reopens an unbounded compile cache —
every novel history size compiles a fresh executable and the service
death-spirals under diverse load.

The rule audits engine entry points called from serve/ (``check_batch``,
``check_megabatch``, ``make_engine``, ``events_array``, ``pack_group``):

- shape-carrying kwargs (``window_floor``, ``n_pad_floor``, ``chunk``,
  ``n_pad``, ``b_pad``, ``window``, ``pad_to``), when present, must be
  *bucket-derived*: reference a ``*bucket*``/``*floor*``/``pow2`` name,
  a ``buckets.`` helper, or the canonical ``_batch_chunk`` derivation
  (literal ``0`` = "disabled" is also fine).  Non-zero literals and raw
  shape expressions fire;
- a ``check_batch`` call *missing* its floor kwarg fires — the default
  floor of 0 means "pad to this history's own size", exactly the
  unbounded behaviour — except when the call pins ``engine="cpu"``
  (the host tier compiles nothing);
- a ``check_megabatch`` call must pass BOTH ``window_floor`` and
  ``ev_floor`` (the megabatch packer buckets internally, but without
  the cell's floors successive dispatches of one bucket land in
  different internal rungs and the lane/shape ladder decoheres), and
  its ``lanes`` count, when present, must come from the lane ladder
  (``mega_lane_bucket``) like every other shape.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional

from jepsen_tpu.lint.findings import Finding
from jepsen_tpu.lint.rules import dotted, qualname_of, walk_with_parents

RULE = "SHAPE01"

SCOPE = ("jepsen_tpu/serve/", "jepsen_tpu/engine/")

#: kwargs that carry a shape into an engine, per entry-point name.
_SHAPE_KWARGS = {
    "check_batch": ("window_floor", "n_pad_floor", "chunk", "pad_to"),
    "check_megabatch": ("window_floor", "ev_floor", "lanes", "chunk"),
    "make_engine": ("window", "capacity", "gwords"),
    "events_array": ("chunk", "pad_to"),
    "pack_group": ("n_pad", "b_pad"),
    # engine-substrate entry points: the shared shape derivation itself
    # (ladder.batch_shape) and the model factories whose kwargs become
    # engine-cache key components (a raw len(h) here is exactly the
    # unbounded-compile-cache leak the ladder exists to close).
    "batch_shape": ("window_floor",),
    "fifo_queue_jax": ("slots",),
    "txn_register_jax": ("keys", "vbits"),
    "multi_register_jax": ("keys", "vbits"),
    "bitset_jax": ("domain",),
    # the state-width ladder derivations: their state_width argument is
    # an engine-cache key component (quantized internally, but a call
    # site threading a raw shape through a kwarg still gets audited)
    "mega_chunk": ("state_width",),
    "state_capacity": ("state_width",),
}

#: which floor kwarg a check_batch variant requires, by defining module.
_FLOOR_FOR_ORIGIN = {
    "jepsen_tpu.parallel.batch": "window_floor",
    "jepsen_tpu.elle_tpu.engine": "n_pad_floor",
}

#: floors a check_megabatch call must ALL pass (the packer buckets
#: internally, but the cell's floors are what pin successive dispatches
#: of one bucket to one internal rung).
_MEGABATCH_FLOORS = ("window_floor", "ev_floor")

_BUCKETISH_NAME = re.compile(r"bucket|floor|pow2", re.IGNORECASE)
_BUCKETISH_FUNC = re.compile(
    r"bucket|floor|pow2|_batch_chunk|mega_chunk|capacity")


def _bucket_derived(node: ast.AST) -> bool:
    """Is this shape expression anchored in the ladder?  True when any
    name/call in it smells of the bucket derivation; literal 0 (feature
    disabled) also passes."""
    if isinstance(node, ast.Constant):
        return node.value == 0 or node.value is None
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and _BUCKETISH_NAME.search(sub.id):
            return True
        if isinstance(sub, ast.Attribute) \
                and _BUCKETISH_NAME.search(sub.attr):
            return True
        if isinstance(sub, ast.Call) \
                and _BUCKETISH_FUNC.search(dotted(sub.func)):
            return True
    return False


def _engine_is_cpu(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "engine" and isinstance(kw.value, ast.Constant) \
                and kw.value.value == "cpu":
            return True
    return False


def _import_origins(tree: ast.Module) -> Dict[ast.AST, Dict[str, str]]:
    """Per-scope ``from X import name [as alias]`` bindings: scope node ->
    {local name: defining module}.  Scopes are the module and each
    function def; lookup walks outward."""
    list(walk_with_parents(tree))
    origins: Dict[ast.AST, Dict[str, str]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.ImportFrom) or node.module is None:
            continue
        scope: ast.AST = node
        while not isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Module)):
            scope = scope.parent  # type: ignore[attr-defined]
        table = origins.setdefault(scope, {})
        for alias in node.names:
            table[alias.asname or alias.name] = node.module
    return origins


def _origin_of(call: ast.Call, origins: Dict[ast.AST, Dict[str, str]],
               name: str) -> Optional[str]:
    cur = getattr(call, "parent", None)
    while cur is not None:
        table = origins.get(cur)
        if table and name in table:
            return table[name]
        cur = getattr(cur, "parent", None)
    return None


def check(tree: ast.Module, src_lines: List[str],
          path: str) -> Iterator[Finding]:
    origins = _import_origins(tree)          # also annotates parents
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fname = dotted(node.func).split(".")[-1]
        if fname not in _SHAPE_KWARGS:
            continue
        qn = qualname_of(node)
        kwargs = {kw.arg: kw.value for kw in node.keywords if kw.arg}
        for kw_name in _SHAPE_KWARGS[fname]:
            value = kwargs.get(kw_name)
            if value is not None and not _bucket_derived(value):
                yield Finding(
                    RULE, path, value.lineno,
                    f"`{fname}(..., {kw_name}=...)` in {qn} passes a "
                    f"shape not derived from the bucket ladder",
                    hint="derive it via serve/buckets.py (events_bucket/"
                         "width_bucket/elle_bucket/...) so the compile "
                         "cache stays bounded by the ladder")
        if fname == "check_megabatch" and not _engine_is_cpu(node):
            for r in _MEGABATCH_FLOORS:
                if r not in kwargs:
                    yield Finding(
                        RULE, path, node.lineno,
                        f"`check_megabatch(...)` in {qn} omits `{r}`: "
                        f"without the cell's floor, successive dispatches "
                        f"of one bucket land in different internal packer "
                        f"rungs and the shape ladder decoheres",
                        hint="pass the cell's bucket as the floor (see "
                             "scheduler._dispatch_wgl's megabatch arm)")
        if fname == "check_batch" and not _engine_is_cpu(node):
            origin = _origin_of(node, origins, dotted(node.func)
                                .split(".")[0] or fname)
            floor = _FLOOR_FOR_ORIGIN.get(origin or "")
            required = (floor,) if floor else tuple(_FLOOR_FOR_ORIGIN
                                                    .values())
            if not any(r in kwargs for r in required):
                want = " or ".join(f"`{r}`" for r in required)
                yield Finding(
                    RULE, path, node.lineno,
                    f"`check_batch(...)` in {qn} omits {want}: the "
                    f"default floor pads each batch to its own raw "
                    f"shape, reopening an unbounded compile cache",
                    hint="pass the bucket as the floor (see scheduler."
                         "_dispatch_*), or pin engine=\"cpu\" for a "
                         "host-tier call that compiles nothing")
