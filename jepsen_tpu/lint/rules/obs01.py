"""OBS01: span discipline for the tracing plane (serve/ + monitor/).

The trace story only merges cleanly — worker spans absorbed into fleet
traces, Perfetto exports lining up with flight-recorder records — when
three invariants hold everywhere spans are made:

1. **Span and record durations are monotonic intervals.**  A
   ``RECORDER.record(..., dur_s=...)`` (or ``t=...``) whose duration
   expression involves wall-clock material (``time.time``, a
   ``wall_anchor``/``anchor_unix_s`` attribute) breaks under NTP steps
   exactly like a CONC01 deadline — except it corrupts *exported* data,
   which is worse: a dashboard can't re-measure the past.

2. **The wall anchor is for export alignment only.**  Each trace
   carries one ``anchor_unix_s`` so exporters can place monotonic spans
   on a calendar axis; arithmetic on it anywhere in serve/ or monitor/
   means someone is deriving intervals from wall clock again, one
   attribute-hop removed from check 1.

3. **Trace identity comes from the request plumbing, never literals.**
   A dict literal carrying both a ``"trace-id"`` key and a span-id key
   with a *constant or f-string* trace-id value is a hand-built trace
   context — it forks the request's identity, so the fleet's absorb
   step files those spans under a trace nobody else shares.  Plumbed
   ids (``self.trace_id``, ``serve.get("trace-id")``) are attribute or
   call expressions and stay clean.

Legitimate wall-clock *display* sites carry the usual pragma:
``# lint: disable=OBS01(export-only wall anchor)``.
"""

from __future__ import annotations

import ast
from typing import Iterator, List

from jepsen_tpu.lint.findings import Finding
from jepsen_tpu.lint.rules import dotted, qualname_of, walk_with_parents

RULE = "OBS01"

SCOPE = ("jepsen_tpu/serve/", "jepsen_tpu/monitor/")

#: names whose appearance inside a duration expression marks it as
#: wall-clock-derived
_WALL_MARKERS = ("time.time", "wall_anchor", "anchor_unix_s")

#: RECORDER.record kwargs that carry durations/instants and must be
#: monotonic-derived
_DUR_KWARGS = ("dur_s", "t")

_SPAN_KEYS = ("span-id", "parent-span-id")


def _expr_names(node: ast.AST) -> List[str]:
    """Dotted names of every Name/Attribute/Call-func inside ``node``."""
    out = []
    for n in ast.walk(node):
        if isinstance(n, (ast.Name, ast.Attribute)):
            d = dotted(n)
            if d:
                out.append(d)
            elif isinstance(n, ast.Attribute):
                out.append(n.attr)      # a.b().c — keep the leaf attr
    return out


def _check_record_durations(tree: ast.Module,
                            path: str) -> Iterator[Finding]:
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        d = dotted(node.func)
        if not d.endswith("RECORDER.record"):
            continue
        for kw in node.keywords:
            if kw.arg not in _DUR_KWARGS:
                continue
            names = _expr_names(kw.value)
            bad = [n for n in names
                   if any(n == m or n.endswith("." + m)
                          for m in _WALL_MARKERS)]
            if bad:
                yield Finding(
                    RULE, path, node.lineno,
                    f"wall-clock material `{bad[0]}` in "
                    f"`{kw.arg}=` of RECORDER.record in "
                    f"{qualname_of(node)}: exported durations must be "
                    f"monotonic intervals",
                    hint="measure with jepsen_tpu.clock.mono_now() "
                         "deltas; the wall anchor exists only so "
                         "exporters can place monotonic spans on a "
                         "calendar axis")


def _check_anchor_arithmetic(tree: ast.Module,
                             path: str) -> Iterator[Finding]:
    for node in ast.walk(tree):
        if not isinstance(node, ast.BinOp):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Attribute) \
                    and sub.attr == "anchor_unix_s":
                yield Finding(
                    RULE, path, node.lineno,
                    f"arithmetic on `{dotted(sub) or sub.attr}` in "
                    f"{qualname_of(node)}: the wall anchor aligns "
                    f"exports, it is not an interval operand",
                    hint="derive intervals from mono_now() deltas; if "
                         "this is a display-only conversion, add "
                         "`# lint: disable=OBS01(export-only wall "
                         "anchor)`")
                break


def _check_handbuilt_trace_dicts(tree: ast.Module,
                                 path: str) -> Iterator[Finding]:
    for node in ast.walk(tree):
        if not isinstance(node, ast.Dict):
            continue
        keys = {k.value: v for k, v in zip(node.keys, node.values)
                if isinstance(k, ast.Constant) and isinstance(k.value, str)}
        if "trace-id" not in keys \
                or not any(s in keys for s in _SPAN_KEYS):
            continue
        tid = keys["trace-id"]
        if isinstance(tid, (ast.Constant, ast.JoinedStr)):
            yield Finding(
                RULE, path, node.lineno,
                f"hand-built trace context in {qualname_of(node)}: "
                f"literal `trace-id` next to a span-id key forks the "
                f"request's trace identity",
                hint="thread the request's own trace_id/span_id "
                     "through (request.span / trace_payload); never "
                     "mint trace ids from literals")


def check(tree: ast.Module, src_lines: List[str],
          path: str) -> Iterator[Finding]:
    list(walk_with_parents(tree))       # annotate parents for qualnames
    yield from _check_record_durations(tree, path)
    yield from _check_anchor_arithmetic(tree, path)
    yield from _check_handbuilt_trace_dicts(tree, path)
