"""Repo-wide call graph: the substrate for the interprocedural lint tier.

The PR 5 rules are deliberately lexical — CONC01 sees a lock inversion
only when both ``with`` blocks share a function.  The whole-program
invariants (cross-function lock chains, the fleet token never reaching
an artifact, deadlines crossing process boundaries only as remaining
budget) need to follow calls, so this module builds one graph over
``jepsen_tpu/`` + ``suites/`` that the CONC02/SEC01/DL01 rules consume.

Resolution is *intraprocedural*: no dataflow across functions is needed
to name the callee.  What resolves:

- **direct calls** — module-level functions, nested ``def``s called from
  their enclosing function, and names reached through ``import`` /
  ``from ... import`` chains, following package ``__init__`` re-exports;
- **method calls** — ``self.m()`` through the class (and repo-resolvable
  bases, including ``super().m()``); ``Cls.m()`` / ``Cls()``
  (constructor -> ``__init__`` through the MRO); ``self.attr.m()`` and
  ``local.m()`` when the attribute/local was assigned a repo-class
  constructor anywhere in the class / earlier in the function;
- **thread-entry seams** — ``threading.Thread(target=f)`` adds a
  ``kind="thread"`` edge to ``f``.  Every long-lived loop in the repo
  (scheduler device loop, wire reader threads, heartbeat/reaper/
  telemetry loops) starts exactly this way, so thread entries are edges,
  not holes.  Rules decide per-invariant whether a thread edge
  propagates (CONC02 does not: the target runs without the spawner's
  locks).

Everything else — calls through dynamic dispatch tables, stored
callbacks, non-constructor-typed attributes — lands in the per-function
``unresolved`` ledger with its source text and line.  That is the
documented conservatism contract: the graph **over-approximates nothing
silently and under-approximates nothing silently** — a rule walking
edges sees every call it could resolve, and the dump shows every call it
could not, so "no finding" is auditable rather than assumed.
"""

from __future__ import annotations

import ast
import builtins
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# data model
# ---------------------------------------------------------------------------


@dataclass
class FuncInfo:
    """One function/method: ``id`` is ``"<path>::<qual>"``."""

    id: str
    path: str
    qual: str                   # "Fleet.submit", "fleet_token", "f.inner"
    lineno: int
    node: Any                   # the ast.FunctionDef / AsyncFunctionDef
    cls: Optional[str] = None   # owning class id, for methods

    @property
    def label(self) -> str:
        """Stable line-free symbol for finding messages."""
        return f"{os.path.basename(self.path)}::{self.qual}"

    def params(self) -> List[str]:
        a = self.node.args
        names = [p.arg for p in a.posonlyargs] + [p.arg for p in a.args]
        names += [p.arg for p in a.kwonlyargs]
        return names


@dataclass
class ClassInfo:
    id: str
    path: str
    name: str
    bases: List[str] = field(default_factory=list)   # dotted source text
    methods: Dict[str, str] = field(default_factory=dict)   # name -> func id
    #: ``self.x = Cls(...)`` assignments seen anywhere in the class body:
    #: attribute name -> the constructor's dotted callee text (resolved
    #: lazily, once the whole symbol table exists)
    attr_ctors: Dict[str, str] = field(default_factory=dict)


@dataclass
class Edge:
    caller: str
    callee: str
    lineno: int
    col: int
    kind: str = "call"          # "call" | "thread"
    bound: bool = False         # instance-bound: args map to params[1:]


@dataclass
class _Module:
    path: str
    name: str                   # dotted module name
    tree: Any
    defs: Dict[str, Tuple[str, str]] = field(default_factory=dict)
    # local name -> ("mod", modname) | ("sym", modname, orig)
    #            | ("ext", dotted-external)
    imports: Dict[str, Tuple] = field(default_factory=dict)
    consts: Dict[str, str] = field(default_factory=dict)  # str constants


class CallGraph:
    """The finished graph plus the indices rules need."""

    def __init__(self) -> None:
        self.funcs: Dict[str, FuncInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        self.modules: Dict[str, _Module] = {}        # by path
        self.by_name: Dict[str, _Module] = {}        # by dotted name
        self.out: Dict[str, List[Edge]] = {}
        self.unresolved: Dict[str, List[Tuple[str, int]]] = {}
        #: call-site index: fid -> {(lineno, col): Edge}
        self.edge_at: Dict[str, Dict[Tuple[int, int], Edge]] = {}
        self.sources: Dict[str, List[str]] = {}

    # -- queries -----------------------------------------------------------

    def in_edges(self, fid: str) -> List[Edge]:
        return [e for edges in self.out.values() for e in edges
                if e.callee == fid]

    def find(self, path_suffix: str, qual: str) -> Optional[FuncInfo]:
        for f in self.funcs.values():
            if f.qual == qual and f.path.endswith(path_suffix):
                return f
        return None

    def class_attr_taintable(self, cid: str, attr: str,
                             tainted: set) -> bool:
        """Is ``(cls-or-ancestor, attr)`` in the tainted-attribute set?"""
        seen = set()
        stack = [cid]
        while stack:
            c = stack.pop()
            if c in seen:
                continue
            seen.add(c)
            if (c, attr) in tainted:
                return True
            info = self.classes.get(c)
            if info:
                for b in info.bases:
                    t = self.resolve_dotted(self.modules[info.path], b)
                    if t and t[0] == "class":
                        stack.append(t[1])
        return False

    def method_of(self, cid: str, name: str) -> Optional[str]:
        """MRO walk (repo classes only) for a method."""
        seen = set()
        stack = [cid]
        while stack:
            c = stack.pop(0)
            if c in seen:
                continue
            seen.add(c)
            info = self.classes.get(c)
            if info is None:
                continue
            if name in info.methods:
                return info.methods[name]
            for b in info.bases:
                t = self.resolve_dotted(self.modules[info.path], b)
                if t and t[0] == "class":
                    stack.append(t[1])
        return None

    def module_const(self, path: str, name: str) -> Optional[str]:
        m = self.modules.get(path)
        return m.consts.get(name) if m else None

    def _is_pkg_prefix(self, name: str) -> bool:
        """True when repo modules live under ``name.`` even though
        ``name`` itself has no indexed module (namespace package)."""
        prefix = name + "."
        return any(k.startswith(prefix) for k in self.by_name)

    # -- symbol resolution -------------------------------------------------

    def resolve_symbol(self, modname: str, name: str,
                       _seen: Optional[set] = None) -> Optional[Tuple]:
        """("func", fid) | ("class", cid) | ("mod", modname) |
        ("ext", dotted) for ``name`` in module ``modname``, following
        re-export chains."""
        _seen = _seen if _seen is not None else set()
        if (modname, name) in _seen:
            return None
        _seen.add((modname, name))
        m = self.by_name.get(modname)
        if m is None:
            # namespace package: no __init__ module of its own, but
            # submodules exist under the prefix
            sub = f"{modname}.{name}"
            if sub in self.by_name or self._is_pkg_prefix(sub):
                return ("mod", sub)
            return None
        if name in m.defs:
            return m.defs[name]
        imp = m.imports.get(name)
        if imp is not None:
            if imp[0] == "mod":
                # classified lazily: the module may not have been indexed
                # yet when the import was recorded
                if imp[1] in self.by_name or self._is_pkg_prefix(imp[1]):
                    return ("mod", imp[1])
                return ("ext", imp[1])
            if imp[0] == "ext":
                return imp
            if imp[0] == "sym":
                sub = f"{imp[1]}.{imp[2]}"
                if sub in self.by_name:
                    return ("mod", sub)
                if imp[1] in self.by_name:
                    return self.resolve_symbol(imp[1], imp[2], _seen)
                return ("ext", sub)
        # a submodule never explicitly imported into the package ns
        sub = f"{modname}.{name}"
        if sub in self.by_name:
            return ("mod", sub)
        return None

    def resolve_dotted(self, m: _Module, dotted: str) -> Optional[Tuple]:
        """Resolve ``a.b.c`` source text in module ``m`` to a target:
        ("func", fid) | ("class", cid) | ("classmethod", fid) |
        ("ext", canonical-dotted) | None."""
        if not dotted:
            return None
        parts = dotted.split(".")
        tgt = self.resolve_symbol(m.name, parts[0])
        if tgt is None:
            return None
        i = 1
        while tgt is not None and tgt[0] == "mod" and i < len(parts):
            tgt = self.resolve_symbol(tgt[1], parts[i])
            i += 1
        if tgt is None:
            return None
        if tgt[0] == "ext":
            rest = parts[i:]
            return ("ext", ".".join([tgt[1]] + rest))
        if i == len(parts):
            return tgt
        if tgt[0] == "class" and i == len(parts) - 1:
            fid = self.method_of(tgt[1], parts[i])
            if fid:
                return ("classmethod", fid)
        return None

    def external_name(self, m: _Module, dotted: str) -> Optional[str]:
        """Canonical external dotted name (``log.warning`` with ``import
        logging as log`` -> ``logging.warning``), or None if the name is
        repo-internal / unknown."""
        t = self.resolve_dotted(m, dotted)
        if t is not None and t[0] == "ext":
            return t[1]
        if t is None and dotted and dotted.split(".")[0] in _BUILTINS:
            return dotted
        return None

    # -- export ------------------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        return {
            "version": 1,
            "functions": {
                fid: {"path": f.path, "qual": f.qual, "line": f.lineno,
                      "class": f.cls,
                      "calls": [{"callee": e.callee, "line": e.lineno,
                                 "kind": e.kind} for e in
                                self.out.get(fid, [])],
                      "unresolved": [{"call": c, "line": ln} for c, ln in
                                     self.unresolved.get(fid, [])]}
                for fid, f in sorted(self.funcs.items())
            },
            "classes": {
                cid: {"path": c.path, "bases": c.bases,
                      "methods": sorted(c.methods)}
                for cid, c in sorted(self.classes.items())
            },
        }


_BUILTINS = frozenset(dir(builtins))


# ---------------------------------------------------------------------------
# construction
# ---------------------------------------------------------------------------

def _mod_name(path: str) -> str:
    p = path[:-3] if path.endswith(".py") else path
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    return p.replace("/", ".")


def _dotted(node: ast.AST) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else ""
    return ""


def _index_module(g: CallGraph, path: str, tree: ast.Module) -> None:
    m = _Module(path=path, name=_mod_name(path), tree=tree)
    g.modules[path] = m
    g.by_name[m.name] = m

    # imports anywhere in the file fold into the module namespace — a
    # function-local `from x import y` resolves the same way
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                local = alias.asname or alias.name.split(".")[0]
                top = alias.name if alias.asname else alias.name.split(".")[0]
                m.imports.setdefault(local, ("mod", top))
        elif isinstance(node, ast.ImportFrom) and node.module:
            for alias in node.names:
                local = alias.asname or alias.name
                m.imports.setdefault(
                    local, ("sym", node.module, alias.name))

    def reg_func(fn: ast.AST, qual: str, cls: Optional[str]) -> None:
        fid = f"{path}::{qual}"
        g.funcs[fid] = FuncInfo(id=fid, path=path, qual=qual,
                                lineno=fn.lineno, node=fn, cls=cls)
        if cls is None and "." not in qual:
            m.defs[qual] = ("func", fid)
        for child in ast.iter_child_nodes(fn):
            _walk_nested(child, qual, cls)

    def _walk_nested(node: ast.AST, outer: str, cls: Optional[str]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            reg_func(node, f"{outer}.{node.name}", cls)
            return
        for child in ast.iter_child_nodes(node):
            _walk_nested(child, outer, cls)

    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            reg_func(node, node.name, None)
        elif isinstance(node, ast.ClassDef):
            cid = f"{path}::{node.name}"
            ci = ClassInfo(id=cid, path=path, name=node.name,
                           bases=[_dotted(b) for b in node.bases
                                  if _dotted(b)])
            g.classes[cid] = ci
            m.defs[node.name] = ("class", cid)
            for item in node.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    mq = f"{node.name}.{item.name}"
                    reg_func(item, mq, cid)
                    ci.methods[item.name] = f"{path}::{mq}"
            # self.x = Ctor(...) anywhere in the class body
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                        and isinstance(sub.targets[0], ast.Attribute) \
                        and isinstance(sub.targets[0].value, ast.Name) \
                        and sub.targets[0].value.id == "self" \
                        and isinstance(sub.value, ast.Call):
                    callee = _dotted(sub.value.func)
                    if callee:
                        ci.attr_ctors.setdefault(
                            sub.targets[0].attr, callee)
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            m.consts[node.targets[0].id] = node.value.value


def _class_of_target(g: CallGraph, m: _Module,
                     t: Optional[Tuple]) -> Optional[str]:
    return t[1] if t is not None and t[0] == "class" else None


def _resolve_calls(g: CallGraph, f: FuncInfo) -> None:
    m = g.modules[f.path]
    edges: List[Edge] = []
    unresolved: List[Tuple[str, int]] = []
    edge_at: Dict[Tuple[int, int], Edge] = {}

    # names of defs nested directly inside this function
    local_funcs = {
        g.funcs[fid].qual.rsplit(".", 1)[1]: fid
        for fid in g.funcs
        if g.funcs[fid].path == f.path
        and g.funcs[fid].qual.startswith(f.qual + ".")
        and "." not in g.funcs[fid].qual[len(f.qual) + 1:]
    }
    # locals assigned a repo-class constructor, in statement order
    var_types: Dict[str, str] = {}

    def resolve_target(expr: ast.AST) -> Optional[Tuple[str, bool]]:
        """-> (callee fid, bound) for a callable reference."""
        d = _dotted(expr)
        if isinstance(expr, ast.Name):
            if expr.id in local_funcs:
                return local_funcs[expr.id], False
            t = g.resolve_dotted(m, expr.id)
            if t is None:
                return None
            if t[0] == "func":
                return t[1], False
            if t[0] == "class":
                init = g.method_of(t[1], "__init__")
                return (init, True) if init else None
            return None
        if isinstance(expr, ast.Attribute):
            # super().m()
            if isinstance(expr.value, ast.Call) \
                    and isinstance(expr.value.func, ast.Name) \
                    and expr.value.func.id == "super" and f.cls:
                ci = g.classes[f.cls]
                for b in ci.bases:
                    bt = g.resolve_dotted(m, b)
                    if bt and bt[0] == "class":
                        fid = g.method_of(bt[1], expr.attr)
                        if fid:
                            return fid, True
                return None
            parts = d.split(".") if d else []
            if parts and parts[0] == "self" and f.cls:
                if len(parts) == 2:
                    fid = g.method_of(f.cls, parts[1])
                    return (fid, True) if fid else None
                if len(parts) == 3:
                    ctor = g.classes[f.cls].attr_ctors.get(parts[1])
                    if ctor:
                        t = g.resolve_dotted(m, ctor)
                        cid = _class_of_target(g, m, t)
                        if cid:
                            fid = g.method_of(cid, parts[2])
                            return (fid, True) if fid else None
                return None
            if len(parts) == 2 and parts[0] in var_types:
                fid = g.method_of(var_types[parts[0]], parts[1])
                return (fid, True) if fid else None
            if d:
                t = g.resolve_dotted(m, d)
                if t is None:
                    return None
                if t[0] == "func":
                    return t[1], False
                if t[0] == "classmethod":
                    return t[1], False
                if t[0] == "class":
                    init = g.method_of(t[1], "__init__")
                    return (init, True) if init else None
            return None
        return None

    def is_thread_ctor(call: ast.Call) -> bool:
        d = _dotted(call.func)
        if not d:
            return False
        ext = g.external_name(m, d)
        return ext == "threading.Thread" or d == "threading.Thread"

    def visit(node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return                      # its own graph node
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call):
            t = g.resolve_dotted(m, _dotted(node.value.func))
            cid = _class_of_target(g, m, t)
            if cid:
                var_types[node.targets[0].id] = cid
        if isinstance(node, ast.Call):
            if is_thread_ctor(node):
                tgt = None
                for kw in node.keywords:
                    if kw.arg == "target":
                        tgt = resolve_target(kw.value)
                if tgt is not None:
                    e = Edge(f.id, tgt[0], node.lineno, node.col_offset,
                             kind="thread", bound=tgt[1])
                    edges.append(e)
                    edge_at[(node.lineno, node.col_offset)] = e
            else:
                r = resolve_target(node.func)
                if r is not None:
                    e = Edge(f.id, r[0], node.lineno, node.col_offset,
                             bound=r[1])
                    edges.append(e)
                    edge_at[(node.lineno, node.col_offset)] = e
                else:
                    d = _dotted(node.func)
                    known_ext = d and g.external_name(m, d) is not None
                    if not known_ext:
                        unresolved.append(
                            (d or type(node.func).__name__, node.lineno))
        for child in ast.iter_child_nodes(node):
            visit(child)

    for stmt in f.node.body:
        visit(stmt)
    g.out[f.id] = edges
    g.unresolved[f.id] = unresolved
    g.edge_at[f.id] = edge_at


def build_graph(files: Dict[str, str]) -> CallGraph:
    """Build the graph from ``{repo-relative path: source text}``.
    Files that fail to parse are skipped here — the AST tier already
    turns them into PARSE findings, which fail lint on their own."""
    g = CallGraph()
    trees: Dict[str, ast.Module] = {}
    for path in sorted(files):
        try:
            trees[path] = ast.parse(files[path], filename=path)
        except SyntaxError:
            continue
        g.sources[path] = files[path].splitlines()
    for path, tree in trees.items():
        _index_module(g, path, tree)
    for f in list(g.funcs.values()):
        _resolve_calls(g, f)
    return g


def map_args_to_params(edge: Edge, call: ast.Call,
                       callee: FuncInfo) -> Dict[str, ast.AST]:
    """Which argument expression feeds which callee parameter.  Bound
    calls (``self.m(x)``, constructors) skip the receiver slot."""
    params = callee.params()
    if edge.bound and params:
        params = params[1:]
    elif params and params[0] in ("self", "cls"):
        # unbound call through the class is rare; be permissive
        if len(call.args) < len(params):
            params = params[1:]
    out: Dict[str, ast.AST] = {}
    for i, a in enumerate(call.args):
        if isinstance(a, ast.Starred):
            break
        if i < len(params):
            out[params[i]] = a
    for kw in call.keywords:
        if kw.arg is not None:
            out[kw.arg] = kw.value
    return out
