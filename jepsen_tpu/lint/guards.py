"""Guarded-by inference: which declared lock actually guards which field.

CONC02 proves the locks are taken in a safe *order*; nothing before this
module proved a lock is taken *at all* around a given shared field.  This
is the Warden tier's substrate: an Eraser-style lockset analysis over the
PR 15 call graph that, for every ``self.<attr>`` access in the threaded
subsystems, computes the set of declared-manifest locks that are
guaranteed held at that access — and therefore, per attribute, the
candidate-guard set (the intersection across all of its access sites).

The held set at an access is the union of two parts:

- the **lexical** part — ``with`` blocks of declared locks
  (lint/lock_order.py) enclosing the access inside its own function,
  exactly CONC01/CONC02's notion of "held";
- the **inherited** part — the function's *MUST-hold entry set*: the
  intersection, over every resolved call edge reaching the function, of
  (caller's entry set ∪ locks lexically held at that call site).  A
  helper called only from inside ``with self._lock`` blocks inherits the
  lock; one call site outside the lock empties the entry set, which is
  the point — MUST analysis, so a single unlocked path surfaces.
  ``kind="thread"`` edges contribute the empty set (the spawned target
  runs on a fresh stack), as do functions with no in-edges at all
  (public entry points: external callers hold nothing we can see).

Concurrency structure comes from the same thread seams the call graph
already models: every ``threading.Thread(target=...)`` edge is a
concurrency root, and an attribute is **shared** only when its
post-publication accesses span at least two distinct roots ("main" —
code reachable from functions that are not thread-entered — counts as
one root).  State touched only inside a single spawned loop's call tree
is single-threaded and never reported.

Safe publication: writes in ``__init__`` *before the first statement
that may start a thread* (a ``threading.Thread`` construction, a
``.start()`` call, or a call into a callee that may transitively spawn)
happen-before any sharing of the object and are exempt; so are
attributes bound to internally-synchronized stdlib types
(``queue.Queue``, ``threading.Event``, locks themselves, ...).

Resolution limits (the conservatism contract, same as callgraph.py):
only ``self.<attr>`` and ``self.<ctor-typed-attr>.<attr>`` receivers are
tracked — writes through untyped locals and parameters are invisible
here and remain the chaos smokes' department; the call-graph dump's
``unresolved`` ledger shows every call edge the entry-set propagation
could not follow.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from jepsen_tpu.lint.callgraph import CallGraph, Edge, FuncInfo
from jepsen_tpu.lint.lock_order import lock_level

#: a declared lock: (manifest level, manifest name)
Lock = Tuple[int, str]

_FN = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)

#: constructors whose instances synchronize internally — an attribute
#: bound to one of these needs no external guard
_THREADSAFE_CTORS = frozenset({
    "queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
    "queue.PriorityQueue", "collections.deque",
    "threading.Event", "threading.Lock", "threading.RLock",
    "threading.Condition", "threading.Semaphore",
    "threading.BoundedSemaphore", "threading.Barrier", "threading.local",
})

#: receiver-mutating method names: ``self.d.pop(...)`` mutates ``d``
#: even though the attribute itself is only loaded
_MUTATORS = frozenset({
    "append", "appendleft", "add", "insert", "extend", "update",
    "remove", "discard", "clear", "pop", "popleft", "popitem",
    "setdefault", "sort", "reverse",
})


@dataclass
class Access:
    """One read/write of a tracked attribute at one source location."""

    fid: str                    # accessing function id
    cid: str                    # owning class id of the attribute
    attr: str
    lineno: int
    col: int
    kind: str                   # "read" | "write" | "rmw" | "mutate"
    held: Tuple[Lock, ...]      # lexically held at the access
    in_init: bool               # access is inside the owning __init__

    @property
    def is_write(self) -> bool:
        return self.kind in ("write", "rmw", "mutate")


@dataclass
class _FnSummary:
    """What one function does, lexically."""

    accesses: List[Access] = field(default_factory=list)
    #: (lineno, col) -> locks lexically held at that call site
    callsite_held: Dict[Tuple[int, int], Tuple[Lock, ...]] = \
        field(default_factory=dict)
    #: linenos of statements that may start a thread directly
    #: (Thread construction or a ``self.*.start()`` call)
    spawn_lines: List[int] = field(default_factory=list)
    #: linenos of calls that can carry ``self`` into the callee
    #: (``self.m()`` or ``self`` in the arguments) — only these can
    #: publish the object through a may-spawn callee
    self_call_lines: Set[int] = field(default_factory=set)
    #: constructs threading.Thread lexically
    spawns: bool = False


class GuardAnalysis:
    """The finished inference: per-function entry sets, per-attribute
    access sites, sharing classification, publication points."""

    def __init__(self, graph: CallGraph) -> None:
        self.graph = graph
        self.local: Dict[str, _FnSummary] = {}
        #: MUST-hold set on entry, per function
        self.entry: Dict[str, FrozenSet[Lock]] = {}
        #: (cid, attr) -> all access sites
        self.accesses: Dict[Tuple[str, str], List[Access]] = {}
        #: functions that may (transitively) start a thread
        self.may_spawn: Set[str] = set()
        #: first lineno in each __init__ at which a thread may already
        #: be running (publication point); absent = never publishes
        self.init_pub_line: Dict[str, int] = {}
        #: concurrency roots reaching each function: "main" or a
        #: thread-edge callee fid
        self.origins: Dict[str, FrozenSet[str]] = {}
        self._run()

    # -- public queries ----------------------------------------------------

    def held_at(self, a: Access) -> FrozenSet[Lock]:
        """Locks guaranteed held at an access: lexical ∪ entry set."""
        return frozenset(a.held) | self.entry.get(a.fid, frozenset())

    def pre_publication(self, a: Access) -> bool:
        """Writes in ``__init__`` before the first possible thread start
        happen-before every share of the object."""
        if not a.in_init:
            return False
        init_fid = f"{a.cid.split('::')[0]}::" \
                   f"{self.graph.classes[a.cid].name}.__init__"
        if a.fid != init_fid:
            return False
        pub = self.init_pub_line.get(init_fid)
        return pub is None or a.lineno < pub

    def shared(self, cid: str, attr: str) -> bool:
        """Do post-publication accesses span ≥ 2 concurrency roots?"""
        roots: Set[str] = set()
        for a in self.accesses.get((cid, attr), ()):
            if self.pre_publication(a):
                continue
            roots |= self.origins.get(a.fid, frozenset())
            if len(roots) >= 2:
                return True
        return False

    def post_publication_sites(self, cid: str, attr: str) -> List[Access]:
        return [a for a in self.accesses.get((cid, attr), ())
                if not self.pre_publication(a)]

    def threadsafe_attr(self, cid: str, attr: str) -> bool:
        """Attribute bound to an internally-synchronized stdlib type
        anywhere in the class body (queue.Queue, Event, a lock, ...)."""
        info = self.graph.classes.get(cid)
        if info is None:
            return False
        ctor = info.attr_ctors.get(attr)
        if not ctor:
            return False
        m = self.graph.modules.get(info.path)
        ext = self.graph.external_name(m, ctor) if m else None
        return (ext or ctor) in _THREADSAFE_CTORS or \
            ctor.split(".")[-1] in ("Lock", "RLock", "Condition",
                                    "Event", "deque", "Queue")

    def chain_from_root(self, fid: str) -> List[Tuple[str, str]]:
        """Shortest chain [(edge-kind, fid), ...] from a concurrency
        root (a no-in-edge function or a thread-edge target) down to
        ``fid``; the first element's kind is "" (the root itself)."""
        rev: Dict[str, List[Tuple[str, str]]] = {}
        for cfid, edges in self.graph.out.items():
            for e in edges:
                rev.setdefault(e.callee, []).append((e.kind, cfid))
        seen = {fid}
        queue: List[List[Tuple[str, str]]] = [[("", fid)]]
        while queue:
            path = queue.pop(0)
            kind, cur = path[0]
            ins = rev.get(cur, [])
            if not ins or kind == "thread":
                return path
            for ekind, caller in sorted(ins):
                if caller not in seen:
                    seen.add(caller)
                    queue.append([(ekind, caller)] + path)
        return [("", fid)]                  # cycle with no entry

    def render_chain(self, fid: str) -> str:
        """``a.py::f ~thread~> b.py::g -> b.py::h`` — element ``i``'s
        recorded kind is the kind of the edge from ``i`` to ``i+1``."""
        chain = self.chain_from_root(fid)
        parts = [self.graph.funcs[chain[0][1]].label]
        for i in range(1, len(chain)):
            arrow = "~thread~>" if chain[i - 1][0] == "thread" else "->"
            parts.append(f"{arrow} {self.graph.funcs[chain[i][1]].label}")
        return " ".join(parts)

    # -- construction ------------------------------------------------------

    def _run(self) -> None:
        g = self.graph
        for fid, f in g.funcs.items():
            self.local[fid] = _summarize(g, f)
        self._spawn_fixpoint()
        self._publication_points()
        self._entry_fixpoint()
        self._origin_sets()
        for fid, s in self.local.items():
            for a in s.accesses:
                self.accesses.setdefault((a.cid, a.attr), []).append(a)

    def _spawn_fixpoint(self) -> None:
        g = self.graph
        self.may_spawn = {fid for fid, s in self.local.items() if s.spawns}
        changed = True
        while changed:
            changed = False
            for fid, edges in g.out.items():
                if fid in self.may_spawn:
                    continue
                for e in edges:
                    if e.kind == "call" and e.callee in self.may_spawn:
                        self.may_spawn.add(fid)
                        changed = True
                        break

    def _publication_points(self) -> None:
        """First lineno in each __init__ at which another thread may
        already be running *with a reference to self*: a lexical spawn
        marker, or a call that both carries ``self`` and reaches a
        may-spawn callee.  A callee spawning threads on a different
        object (``self.fleet = Fleet(...)`` starting Fleet's own loops)
        does not publish this object."""
        g = self.graph
        for fid, f in g.funcs.items():
            if not f.qual.endswith(".__init__") or f.cls is None:
                continue
            s = self.local[fid]
            candidates = list(s.spawn_lines)
            for (lineno, _col), e in g.edge_at.get(fid, {}).items():
                if e.kind == "thread" or (
                        e.kind == "call" and e.callee in self.may_spawn
                        and lineno in s.self_call_lines):
                    candidates.append(lineno)
            if candidates:
                self.init_pub_line[fid] = min(candidates)

    def _entry_fixpoint(self) -> None:
        """Greatest fixpoint of
        entry(f) = ⋂ over call in-edges (entry(caller) ∪ held-at-site),
        with thread-edge targets and no-in-edge functions pinned at ∅."""
        g = self.graph
        in_edges: Dict[str, List[Tuple[str, Edge]]] = {}
        for cfid, edges in g.out.items():
            for e in edges:
                in_edges.setdefault(e.callee, []).append((cfid, e))
        top: Optional[FrozenSet[Lock]] = None   # ⊤ sentinel
        entry: Dict[str, Optional[FrozenSet[Lock]]] = {}
        for fid in g.funcs:
            ins = in_edges.get(fid, [])
            if not ins or any(e.kind == "thread" for _c, e in ins):
                entry[fid] = frozenset()
            else:
                entry[fid] = top
        changed = True
        while changed:
            changed = False
            for fid in g.funcs:
                ins = in_edges.get(fid, [])
                if not ins or any(e.kind == "thread" for _c, e in ins):
                    continue
                acc: Optional[FrozenSet[Lock]] = top
                for cfid, e in ins:
                    ce = entry.get(cfid, frozenset())
                    if ce is top:
                        continue            # ⊤ caller constrains nothing yet
                    held = self.local[cfid].callsite_held.get(
                        (e.lineno, e.col), ())
                    contrib = frozenset(ce) | frozenset(held)
                    acc = contrib if acc is top else (acc & contrib)
                if acc is top:
                    continue
                # force monotone descent so the loop terminates even if
                # a caller's entry set arrives late in the iteration
                new = acc if entry[fid] is top else (entry[fid] & acc)
                if new != entry[fid]:
                    entry[fid] = new
                    changed = True
        self.entry = {fid: (v if v is not top and v is not None
                            else frozenset())
                      for fid, v in entry.items()}

    def _origin_sets(self) -> None:
        """"main" = closure from functions that are not thread-entered;
        each thread-edge target is its own root, closed over call edges."""
        g = self.graph
        in_kinds: Dict[str, Set[str]] = {}
        for edges in g.out.values():
            for e in edges:
                in_kinds.setdefault(e.callee, set()).add(e.kind)
        origins: Dict[str, Set[str]] = {fid: set() for fid in g.funcs}

        def close_from(roots: List[str], tag_of) -> None:
            for root in roots:
                tag = tag_of(root)
                stack, seen = [root], {root}
                while stack:
                    cur = stack.pop()
                    origins[cur].add(tag)
                    for e in g.out.get(cur, []):
                        if e.kind == "call" and e.callee not in seen \
                                and e.callee in origins:
                            seen.add(e.callee)
                            stack.append(e.callee)

        main_roots = [fid for fid in g.funcs
                      if "thread" not in in_kinds.get(fid, set())
                      and not in_kinds.get(fid)]
        close_from(sorted(main_roots), lambda _r: "main")
        thread_roots = sorted({e.callee for edges in g.out.values()
                               for e in edges if e.kind == "thread"
                               if e.callee in g.funcs})
        close_from(thread_roots, lambda r: r)
        # functions with call in-edges but unreachable from any root
        # (dead code / cycles): treat as main so they are not silently
        # dropped from sharing decisions
        for fid, o in origins.items():
            if not o:
                o.add("main")
        self.origins = {fid: frozenset(o) for fid, o in origins.items()}


# ---------------------------------------------------------------------------
# per-function lexical summary
# ---------------------------------------------------------------------------

def _annotate_parents(root: ast.AST) -> None:
    for node in ast.walk(root):
        for child in ast.iter_child_nodes(node):
            child.parent = node  # type: ignore[attr-defined]


def _dotted(node: ast.AST) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else ""
    return ""


def _receiver_class(g: CallGraph, f: FuncInfo,
                    node: ast.Attribute) -> Optional[Tuple[str, str]]:
    """(owning class id, attr name) for a tracked attribute access:
    ``self.x`` resolves to the enclosing class; ``self.a.b`` resolves
    through ``a``'s constructor type when the class recorded one."""
    if f.cls is None:
        return None
    v = node.value
    if isinstance(v, ast.Name) and v.id == "self":
        return f.cls, node.attr
    if isinstance(v, ast.Attribute) and isinstance(v.value, ast.Name) \
            and v.value.id == "self":
        info = g.classes.get(f.cls)
        ctor = info.attr_ctors.get(v.attr) if info else None
        if ctor:
            m = g.modules.get(f.path)
            t = g.resolve_dotted(m, ctor) if m else None
            if t and t[0] == "class":
                return t[1], node.attr
    return None


def _classify(g: CallGraph, f: FuncInfo,
              node: ast.Attribute) -> Optional[str]:
    """Access kind for an attribute node, or None when it is not a data
    access (method references/calls belong to the call graph)."""
    parent = getattr(node, "parent", None)
    ctx = node.ctx
    if isinstance(ctx, (ast.Store, ast.Del)):
        if isinstance(parent, ast.AugAssign) and parent.target is node:
            return "rmw"
        return "write"
    # Load contexts
    if isinstance(parent, ast.Call) and parent.func is node:
        return None                         # self.m() — a call, not data
    if isinstance(parent, ast.Attribute) and parent.value is node:
        gp = getattr(parent, "parent", None)
        if isinstance(gp, ast.Call) and gp.func is parent:
            if parent.attr in _MUTATORS:
                return "mutate"             # self.d.pop(...)
            # self.attr.m() — receiver load; a method call on a typed
            # attr is an edge, a data read otherwise.  Either way the
            # reference itself is read.
            return "read"
        return "read"
    if isinstance(parent, ast.Subscript) and parent.value is node:
        sctx = parent.ctx
        if isinstance(sctx, (ast.Store, ast.Del)):
            return "mutate"                 # self.d[k] = v / del self.d[k]
        gp = getattr(parent, "parent", None)
        if isinstance(gp, ast.AugAssign) and gp.target is parent:
            return "mutate"                 # self.d[k] += v
        return "read"
    # bound-method reference (target=self._loop) — not a data access
    if f.cls is not None and isinstance(node.value, ast.Name) \
            and node.value.id == "self" \
            and g.method_of(f.cls, node.attr) is not None:
        return None
    return "read"


def _summarize(g: CallGraph, f: FuncInfo) -> _FnSummary:
    out = _FnSummary()
    _annotate_parents(f.node)
    m = g.modules.get(f.path)
    in_init = f.qual.endswith(".__init__") and f.cls is not None

    def is_spawn_marker(call: ast.Call) -> Optional[str]:
        d = _dotted(call.func)
        if not d:
            return None
        ext = g.external_name(m, d) if m else None
        if (ext or d) == "threading.Thread":
            return "ctor"
        # only self-rooted receivers: a helper object's .start() does
        # not hand this object to a new thread
        if d.endswith(".start") and d.startswith("self."):
            return "start"
        return None

    def carries_self(call: ast.Call) -> bool:
        if _dotted(call.func).startswith("self."):
            return True
        args = list(call.args) + [kw.value for kw in call.keywords]
        return any(isinstance(n, ast.Name) and n.id == "self"
                   for a in args for n in ast.walk(a))

    def visit(node: ast.AST, held: Tuple[Lock, ...]) -> None:
        if isinstance(node, _FN):
            return                          # its own graph node
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new_held = held
            for item in node.items:
                try:
                    expr_s = ast.unparse(item.context_expr)
                except Exception:  # pragma: no cover - defensive
                    expr_s = ""
                lv = lock_level(f.path, expr_s)
                if lv is not None:
                    new_held = new_held + (lv,)
                visit(item.context_expr, held)
            for child in node.body:
                visit(child, new_held)
            return
        if isinstance(node, ast.Call):
            out.callsite_held[(node.lineno, node.col_offset)] = held
            if carries_self(node):
                out.self_call_lines.add(node.lineno)
            marker = is_spawn_marker(node)
            if marker is not None:
                out.spawn_lines.append(node.lineno)
                if marker == "ctor":
                    out.spawns = True
        if isinstance(node, ast.Attribute):
            rc = _receiver_class(g, f, node)
            if rc is not None:
                kind = _classify(g, f, node)
                if kind is not None:
                    cid, attr = rc
                    out.accesses.append(Access(
                        fid=f.id, cid=cid, attr=attr,
                        lineno=node.lineno, col=node.col_offset,
                        kind=kind, held=held,
                        in_init=in_init and cid == f.cls))
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in f.node.body:
        visit(stmt, ())
    return out


# ---------------------------------------------------------------------------
# shared entry point (memoized per graph: RACE01 and ATOM01 both consume it)
# ---------------------------------------------------------------------------

def analyze(graph: CallGraph) -> GuardAnalysis:
    cached = getattr(graph, "_guard_analysis", None)
    if cached is None:
        cached = GuardAnalysis(graph)
        graph._guard_analysis = cached      # type: ignore[attr-defined]
    return cached
