"""Device-side primitive ops shared by the analysis engines."""

from jepsen_tpu.ops.dedup import sort_dedup_compact  # noqa: F401
