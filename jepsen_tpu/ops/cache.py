"""Persistent XLA compilation cache.

The device engine's first compile costs tens of seconds (≈100 s for the
full capacity-escalation ladder on a tunneled TPU) while the 10k-op check
itself runs in ~18 s — every fresh process paid 6x the work in compiles.
JAX ships a persistent cache (serialized executables keyed by HLO +
compile options + platform); enabling it makes the second process's
"compile" a disk load.

The reference has no counterpart (knossos is a JVM library, warmed by the
JIT per-process); this is a TPU-native concern.  Cache lives under
``store/cache/xla`` by default so it ships with the run archive workflow
and is wiped by the same housekeeping that prunes old runs.
"""

from __future__ import annotations

import os
from typing import Optional

_enabled = False


def enable_compilation_cache(cache_dir: Optional[str] = None) -> str:
    """Point JAX's persistent compilation cache at ``cache_dir`` (default
    ``$JEPSEN_TPU_CACHE or store/cache/xla``).  Idempotent; safe to call
    before or after the first trace.  Returns the directory used."""
    global _enabled
    import jax

    if jax.default_backend() == "cpu" and "JEPSEN_TPU_CACHE_CPU" not in os.environ:
        # CPU AOT cache entries embed exact machine features and XLA warns
        # they may SIGILL on a host whose feature set differs (virtual-mesh
        # test runs move between machines); CPU compiles are cheap, so only
        # accelerator executables are worth persisting.
        return ""
    d = (cache_dir
         or os.environ.get("JEPSEN_TPU_CACHE")
         or os.path.join("store", "cache", "xla"))
    d = os.path.abspath(d)
    if _enabled and jax.config.jax_compilation_cache_dir == d:
        return d
    os.makedirs(d, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", d)
    # Cache everything: engine shapes compile in 1-40 s each, and even
    # sub-second helper kernels add up across the escalation ladder.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    _enabled = True
    return d


def init_compilation_cache(store_base: Optional[str] = None) -> str:
    """The one shared init used by the serve service, bench.py, and the
    CLI: point the persistent XLA cache at ``<store_base>/cache/xla`` (or
    the enable_compilation_cache defaults when no base is given) so every
    repeated process — a second bench run, a restarted service, each
    bench subprocess tier — loads executables from disk instead of
    recompiling.  Never raises (a read-only filesystem, a CPU-only CI box
    with no accelerator cache to keep — see the CPU gate above — or a
    broken JAX install must not take checking down with it); returns the
    directory used, or "" when caching stayed off."""
    try:
        d = (os.path.join(store_base, "cache", "xla")
             if store_base else None)
        return enable_compilation_cache(d)
    except Exception:  # noqa: BLE001
        return ""
