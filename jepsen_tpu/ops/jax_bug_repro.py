"""Minimized reproducer for the >=1024-lane vmapped bool-scatter bug.

Found while scaling the batch checking driver (parallel/batch.py): a
vmapped scatter into a BOOL array inside ``lax.scan`` returns wrong
results at batch >= 1024, on both the CPU and TPU backends, jitted or
eager.  int32 arrays are unaffected; batch 1023 is bit-perfect.  The
engine's ``active``/``fresh`` slot updates are exactly this shape, so the
batch driver caps vmapped groups at ``MAX_LANES_PER_GROUP`` (512) — see
parallel/batch.py.

Run ``python -m jepsen_tpu.ops.jax_bug_repro`` to print ok/BAD per batch
size; kept as an executable record so the workaround can be dropped the
day this prints all-ok on the pinned jax.
"""

from __future__ import annotations

import numpy as np

W = 8


def reproduce(batch: int, steps: int = 6) -> bool:
    """True iff jax matches the numpy reference at this batch size."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def step(c, ev):
        cond, arr = c
        slot = ev[0] % W
        arr = arr.at[slot].set(jnp.where(cond, False, arr[slot]))
        return (ev[1] % 2 == 0, arr), None

    def run(carry, events):
        return lax.scan(step, carry, events)[0]

    rng = np.random.default_rng(0)
    events = rng.integers(0, 100, (batch, steps, 2)).astype(np.int32)
    conds = rng.random(batch) < 0.5
    arrs = np.ones((batch, W), bool)
    f = jax.jit(jax.vmap(run, in_axes=((0, 0), 0)))
    _, arr = f((jnp.asarray(conds), jnp.asarray(arrs)),
               jnp.asarray(events))
    c = conds.copy()
    a = arrs.copy()
    for s in range(steps):
        sl = events[:, s, 0] % W
        a[np.arange(batch), sl] = np.where(
            c, False, a[np.arange(batch), sl])
        c = events[:, s, 1] % 2 == 0
    return bool(np.array_equal(np.asarray(arr), a))


if __name__ == "__main__":
    for b in (512, 1022, 1023, 1024, 2048):
        print(b, "ok" if reproduce(b) else "BAD", flush=True)
