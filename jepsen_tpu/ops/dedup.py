"""Sort-based row deduplication with fixed-capacity compaction.

The TPU search's configuration sets live in fixed-shape buffers; after each
closure expansion the union of (existing ∪ candidate) rows must be
deduplicated and compacted back to capacity.  Rows are fully described by
their key columns, so a multi-operand lexicographic ``lax.sort`` (invalid rows
keyed last), a neighbour-equality pass, and a stable-sort compaction
(compact_rows — TPU scatters serialize per update, sorts don't) do the
whole job with static shapes — no host round-trips, no dynamic allocation.

This replaces what knossos does with JVM hash sets of configuration objects;
sort+compare is the shape XLA tiles well.
"""

from __future__ import annotations

import os
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

#: Subsumption probe count (earlier in-group rows checked per row).  Read at
#: import time; engines embed it in their cache keys (see wgl_tpu.make_engine)
#: so changing it requires a fresh process, never a silent no-op.
#: Default 3 (was 5): measured on hardware, probes 3 drop exactly the same
#: rows on the crash-heavy hard tier and the subsumption ablation (same
#: configs explored, same capacity trajectory) while the per-merge
#: gather/compare chains cost ~9% of the easy-tier wall (7.5s -> 6.9s).
N_PROBES = int(os.environ.get("JTPU_PROBES", "3"))

#: Above this row count the dedup sorts with ``_lex_perm`` (a chain of
#: 2-operand stable sorts composing a permutation) instead of one wide
#: variadic ``lax.sort``.  A 7-operand sort over C*(W+1) ~ 4.26M rows
#: (capacity 65536 x window 64, the bench hard tier) crashes the TPU worker
#: outright; 2-operand sorts at the same row count compile in ~26 s and run
#: in milliseconds.  1.06M-row x 7-operand variadic sorts are measured-good,
#: so the threshold keeps the single-sort path for every small shape.
WIDE_SORT_ROWS = int(os.environ.get("JTPU_WIDE_SORT_ROWS", "1200000"))

#: Ablation switch for ghost subsumption (``JTPU_SUBSUME=0`` disables the
#: subset-drop; ghost columns then act as plain identity columns, i.e. the
#: classic 2^crashes configuration search).  Import-time constant, part of
#: the engine cache key — exists so the bench can measure what subsumption
#: buys on hardware.
SUBSUME = os.environ.get("JTPU_SUBSUME", "1") != "0"


def compact_rows(cols: Sequence[jnp.ndarray], keep: jnp.ndarray,
                 capacity: int):
    """Stable compaction of the rows where ``keep`` into ``capacity``-row
    buffers via one stable sort + GATHER — no scatter.

    TPU scatters serialize over their updates (a C*W-row grid compaction
    measured 60 us per scatter — the single hottest op in the whole
    closure, 42% of device time), while sorts and gathers are parallel
    (the same merge's 1536-row variadic sort: 6 us).  A single stable
    2-operand sort of ``(~keep, iota)`` ranks the kept rows' indices
    first, in order — the whole inverse map in one parallel op; the rows
    then GATHER into place.  Rows past the kept count are masked to zero
    to keep the old scatter semantics (callers rely on valid-gating, but
    zeroed tails keep artifacts reproducible).  Rows past ``capacity``
    are silently truncated, exactly like the scatter's ``mode="drop"`` —
    callers detect that via ``total``.

    Returns ``(out_cols, out_valid, total)``.
    """
    n = keep.shape[0]
    total = jnp.sum(keep.astype(jnp.int32))
    # One stable single-KEY sort with every column riding along as a
    # payload operand: payloads don't enter the comparator (num_keys=1),
    # they are just carried by the permutation network — so the kept rows
    # land first, in order, with zero per-column gathers (TPU row-gathers
    # serialize like scatters; 4 of them cost 30 us/round before this).
    flat, meta = [], []
    for c in cols:
        if c.ndim == 1:
            flat.append(c)
            meta.append(None)
        else:
            flat.extend(c[:, j] for j in range(c.shape[1]))
            meta.append(c.shape[1])
    if n <= WIDE_SORT_ROWS:
        sorted_ops = jax.lax.sort(
            tuple([(~keep).astype(jnp.int32)] + flat),
            num_keys=1, is_stable=True)[1:]
    else:
        # Multi-million-row wide variadic sorts crash the TPU compiler
        # (see WIDE_SORT_ROWS): sort only (key, iota) and gather each
        # column — gather cost scales with the OUTPUT (capacity), not n.
        _, src = jax.lax.sort(((~keep).astype(jnp.int32),
                               jnp.arange(n, dtype=jnp.int32)),
                              num_keys=1, is_stable=True)
        src = src[:min(capacity, n)]
        sorted_ops = [jnp.take(c, src, axis=0) for c in flat]
        n = src.shape[0]
    out_valid = jnp.arange(capacity) < total

    def fit(c):
        c = c[:capacity] if capacity <= n else jnp.concatenate(
            [c, jnp.zeros(capacity - n, c.dtype)])
        return jnp.where(out_valid, c, jnp.zeros((), c.dtype))

    outs, k = [], 0
    for m in meta:
        if m is None:
            outs.append(fit(sorted_ops[k]))
            k += 1
        else:
            outs.append(jnp.stack([fit(sorted_ops[k + j])
                                   for j in range(m)], axis=-1))
            k += m
    return outs, out_valid, total


def _lex_perm(keys: Sequence[jnp.ndarray]) -> jnp.ndarray:
    """Permutation sorting rows lexicographically by ``keys`` (first key most
    significant), stable — equivalent to ``np.lexsort(reversed(keys))``.

    Built least-significant-key-first from 2-operand stable sorts: each pass
    gathers the next key through the permutation so far and stable-sorts
    (key, perm).  Stability makes the passes compose into a lexicographic
    order.  Narrow sorts sidestep the TPU compiler failure that wide variadic
    sorts hit at multi-million-row shapes."""
    n = keys[0].shape[0]
    perm = jnp.arange(n, dtype=jnp.int32)
    for k in reversed(list(keys)):
        kk = jnp.take(k, perm)
        _, perm = jax.lax.sort((kk, perm), num_keys=1, is_stable=True)
    return perm


def sort_dedup_compact(cols: Sequence[jnp.ndarray],
                       valid: jnp.ndarray,
                       capacity: int,
                       ghost_cols: Sequence[jnp.ndarray] = (),
                       origin: jnp.ndarray = None,
                       ):
    """Deduplicate rows described by ``cols`` (+ ``ghost_cols``, each [N],
    int dtypes) among entries where ``valid`` is True; compact the distinct
    rows into buffers of ``capacity`` rows.

    ``ghost_cols`` enable *subsumption*: rows agreeing on every ``cols``
    entry form a group, and a member is dropped when the group's head (the
    sort-first member) has a ghost bitset that is a subset of the member's
    (checked word-wise: ``head & ~row == 0``).  Soundness (see
    checker/wgl_tpu.py): ghost bits mark pending ops that never return, so
    they are never consulted by pruning; a config whose ghost set contains
    the head's is reachable from the head again at any later closure, and
    the head has a superset of its futures.  Without ``ghost_cols`` this is
    plain exact dedup.

    ``origin`` (optional, int32 [N], 1 = newly-generated candidate) is
    carried as a payload; when given, the return gains ``new_rows`` (True
    iff any *kept* row is a candidate — this, not a count delta, is the
    sound fixpoint signal for a closure loop, because subsumption can drop
    existing rows in the same round that adds new ones, leaving the count
    unchanged while the set moved) and ``out_origin``, the compacted
    per-row origin column (the delta closure's next-round expansion
    frontier).  For a dropped duplicate the kept copy's origin wins (the
    stable sort keeps the existing row ahead of an identical candidate).

    Returns ``(out_cols, out_valid, total, overflow[, new_rows,
    out_origin])`` — ``out_cols`` in the order ``[*cols, *ghost_cols]``;
    ``total`` is the number of kept rows (may exceed capacity — then
    ``overflow`` is True and the surplus rows were dropped).
    """
    n = valid.shape[0]
    n_key = len(cols)
    # Key 0: invalid rows sort after all valid rows.  Ghost columns sort
    # ascending after the group key, so a numerically-minimal ghost set
    # (e.g. the empty set) heads its group.  The stable sort keeps an
    # existing row ahead of an identical candidate, so exact-dup keeps the
    # existing one and ``new_rows`` stays quiet.
    inv = (~valid).astype(jnp.int32)
    keys = [inv] + list(cols) + list(ghost_cols)
    extras = [origin] if origin is not None else []
    if n <= WIDE_SORT_ROWS:
        sorted_ops = jax.lax.sort(tuple(keys + extras),
                                  num_keys=1 + n_key + len(ghost_cols))
    else:
        perm = _lex_perm(keys)
        sorted_ops = [jnp.take(c, perm) for c in keys + extras]
    s_inv = sorted_ops[0]
    s_cols = list(sorted_ops[1:1 + n_key])
    s_ghost = list(sorted_ops[1 + n_key:1 + n_key + len(ghost_cols)])
    s_origin = sorted_ops[-1] if origin is not None else None
    s_valid = s_inv == 0

    same_as_prev = jnp.ones(n, dtype=bool)
    for c in s_cols:
        same_as_prev &= c == jnp.roll(c, 1)
    same_as_prev = same_as_prev.at[0].set(False)
    exact_same = same_as_prev
    for c in s_ghost:
        exact_same &= c == jnp.roll(c, 1)
    drop = exact_same & jnp.roll(s_valid, 1)

    if s_ghost and SUBSUME:
        # Group head per row: the index where the row's group starts.
        # (cumsum + scatter/gather, NOT lax.cummax — cummax nested inside
        # scan/while_loop control flow has crashed the TPU compiler at
        # ~1M-row shapes; cumsum is already exercised by the compaction.)
        is_head = s_valid & ~(same_as_prev & jnp.roll(s_valid, 1))
        idx = jnp.arange(n)
        seg = jnp.cumsum(is_head.astype(jnp.int32)) - 1
        # Index of each group's head row, gather-side: one stable sort
        # ranks the head rows' indices first, in order (scatters
        # serialize on TPU — see compact_rows).
        _, head_idx = jax.lax.sort(((~is_head).astype(jnp.int32),
                                    idx.astype(jnp.int32)),
                                   num_keys=1, is_stable=True)
        head_of = jnp.take(head_idx, jnp.clip(seg, 0, n - 1))
        in_group = s_valid & (head_of != idx) & (seg >= 0)
        # Probe several earlier in-group rows: ANY earlier row with a
        # subset ghost bitset justifies the drop (its own drop reason, if
        # dropped, chains down to a kept subset).  A subset sorts before
        # its supersets, so probing the head plus a few nearby offsets
        # catches most dominated rows; leftovers only cost capacity.
        # The head probe is the one true GATHER; the offset probes are
        # static ROLLS guarded by a same-group check — a TPU row-gather
        # serializes per element (3 probe gathers cost 31 us/round), a
        # roll is parallel slices.  Equivalent hits: the old clamped
        # probe max(idx-off, head_of) degenerated to the (already
        # probed) head exactly when the roll's same-group guard fails.
        subsumed = jnp.zeros(n, dtype=bool)
        hit = in_group
        for c in s_ghost:
            hit &= (c[jnp.maximum(head_of, 0)] & ~c) == 0
        subsumed |= hit
        for off in (1, 2, 4, 8, 16)[:N_PROBES]:
            hit = in_group & (idx >= off) & (jnp.roll(seg, off) == seg)
            for c in s_ghost:
                hit &= (jnp.roll(c, off) & ~c) == 0
            subsumed |= hit
        drop = drop | subsumed

    keep = s_valid & ~drop

    src_cols = s_cols + s_ghost + ([s_origin] if origin is not None else [])
    outs, out_valid, total = compact_rows(src_cols, keep, capacity)
    overflow = total > capacity
    if origin is None:
        return outs, out_valid, total, overflow
    new_rows = jnp.any(keep & (s_origin == 1))
    return outs[:-1], out_valid, total, overflow, new_rows, outs[-1]
