"""Sort-based row deduplication with fixed-capacity compaction.

The TPU search's configuration sets live in fixed-shape buffers; after each
closure expansion the union of (existing ∪ candidate) rows must be
deduplicated and compacted back to capacity.  Rows are fully described by
their key columns, so a multi-operand lexicographic ``lax.sort`` (invalid rows
keyed last), a neighbour-equality pass, and a cumsum/scatter compaction do the
whole job with static shapes — no host round-trips, no dynamic allocation.

This replaces what knossos does with JVM hash sets of configuration objects;
sort+compare is the shape XLA tiles well.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp


def sort_dedup_compact(cols: Sequence[jnp.ndarray],
                       valid: jnp.ndarray,
                       capacity: int,
                       ) -> Tuple[List[jnp.ndarray], jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Deduplicate rows described by ``cols`` (each [N], int dtypes) among
    entries where ``valid`` is True; compact the distinct rows into buffers of
    ``capacity`` rows.

    Returns ``(out_cols, out_valid, total, overflow)`` where ``total`` is the
    number of distinct valid rows (may exceed capacity — then ``overflow`` is
    True and the surplus rows were dropped).
    """
    n = valid.shape[0]
    # Key 0: invalid rows sort after all valid rows.
    inv = (~valid).astype(jnp.int32)
    operands = [inv] + [c for c in cols]
    sorted_ops = jax.lax.sort(tuple(operands), num_keys=len(operands))
    s_inv, s_cols = sorted_ops[0], list(sorted_ops[1:])
    s_valid = s_inv == 0

    same_as_prev = jnp.ones(n, dtype=bool)
    for c in s_cols:
        same_as_prev &= c == jnp.roll(c, 1)
    same_as_prev = same_as_prev.at[0].set(False)
    keep = s_valid & ~(same_as_prev & jnp.roll(s_valid, 1))

    pos = jnp.cumsum(keep.astype(jnp.int32)) - 1
    total = pos[-1] + 1
    overflow = total > capacity
    dest = jnp.where(keep & (pos < capacity), pos, capacity)

    out_cols = []
    for c in s_cols:
        buf = jnp.zeros(capacity + 1, dtype=c.dtype)
        out_cols.append(buf.at[dest].set(c, mode="drop")[:capacity])
    out_valid = jnp.arange(capacity) < jnp.minimum(total, capacity)
    return out_cols, out_valid, total, overflow
