"""Fleetport: the multi-host control plane over the existing wire.

serve/fleet.py builds its worker set in the constructor — N slots, all
local, supervised by SIGKILL and respawn.  Fleetport inverts every one
of those assumptions while keeping the *entire* driver stack (route,
wait, hedge, reroute, journal, telemetry, SLOs) unchanged:

- **discovery** — workers on any host dial in with a REGISTER frame
  (serve/transport.py's sixth frame type) carrying their dial-back
  ``host:port``, device inventory, mesh shape, and capability buckets.
  Each admitted worker becomes a registry-backed slot appended to the
  fleet's (index-stable, append-only) worker list, so the router's
  rendezvous ranking and ``_note_worker_telemetry``'s ``wid == index``
  invariant hold exactly as they do for fixed fleets.
- **leases, not signals** — a registered worker holds a lease
  (serve/registry.py) renewed by its TELEMETRY pushes; the supervisor
  here is a *lease reaper*, not a process killer.  A worker that stops
  renewing — crashed, partitioned, decommissioned — is evicted with no
  local signal of any kind: its slot goes dead, the rendezvous walk
  reroutes its keys to siblings (cells in flight degrade to transport
  unknowns and reroute through the normal driver path), and its journal
  entries drain as those cells finalize.  This is the property the
  whole PR exists for: P-compositionality (arXiv:1504.00204) makes a
  relocated cell verdict-identical, so losing a host changes *where*
  checking happens and nothing else.
- **authenticated frames** — with ``JEPSEN_TPU_FLEET_TOKEN`` set, every
  frame in either direction carries an HMAC envelope (serve/auth.py):
  constant-time verify, typed ERROR (``error-class: AuthError``) +
  hangup on failure, and the token itself never appears in any log,
  trace, telemetry payload, or metrics artifact — export surfaces carry
  at most ``auth-enabled: true``.
- **mesh-aware placement** — each record advertises a device-mesh shape;
  :meth:`FleetportWorker.fits` admits a cell only when the worker's
  lane capacity covers the cell's bucketed demand, and the router's
  ranked walk filters on it (falling back to the unfiltered ranking
  when nobody fits — placement is an optimization, never an
  availability loss).  CPU CI workers advertise the degenerate 1-mesh
  and take everything today's tests route.

Lock order (lint/lock_order.py): the slot-create/evict lock here is
``fleet-supervisor`` (``self._sup_lock``), above the registry's own
lock (``fleet-registry``), above the per-slot restart lock
(``fleet-slot``).
"""

from __future__ import annotations

import logging
import socket
import threading
from typing import Any, Dict, Optional

from jepsen_tpu.clock import mono_now
from jepsen_tpu.obs.telemetry import set_gauge
from jepsen_tpu.serve.auth import (TENANT_FIELD, fleet_token, sign_frame,
                                   tenant_tokens, verify_frame)
from jepsen_tpu.serve.fleet import Fleet, FleetWorker
from jepsen_tpu.serve.registry import FleetRegistry, WorkerRecord
from jepsen_tpu.serve.transport import (F_ERROR, F_REGISTER, F_REPLY,
                                        F_TELEMETRY, FrameError,
                                        MAX_FRAME_BYTES, ProcWorkerService,
                                        encode_frame, read_frame)

log = logging.getLogger("jepsen.serve.fleetport")


def cell_lane_demand(cell) -> int:
    """The lane capacity a cell's bucket asks of its worker.  Buckets
    are ``(kind, engine-identity, *shape)`` (serve/decompose.py): for
    elle the shape is the lane-group size (``elle_n_bucket`` — a
    512-lane group demands a 512-lane worker); for wgl it is
    ``(events, width)`` and the width bucket bounds the per-dispatch
    lane fan-out.  Anything unbucketed demands 1 — an unknown shape
    must not be unroutable."""
    b = getattr(cell, "bucket", ()) or ()
    if len(b) < 3:
        return 1
    try:
        if b[0] == "elle":
            return max(1, int(b[2]))
        return max(1, int(b[-1]))
    except (TypeError, ValueError):
        return 1


class RemoteWorkerLauncher:
    """The launcher facade for a worker the fleet did NOT spawn.  The
    usual launcher contract (``await_ready``/``alive``/``kill``/
    ``terminate``/``status``) backed by the registry instead of a child
    process: liveness IS lease liveness for this generation, and kill /
    terminate are deliberate no-ops — eviction is lease-expiry-first,
    and this process holds no signal authority over a worker on another
    machine anyway."""

    def __init__(self, record: WorkerRecord, registry: FleetRegistry):
        self.record = record
        self.name = record.name
        self._registry = registry

    @property
    def host(self) -> str:
        return self.record.host

    @property
    def port(self) -> int:
        return self.record.port

    def await_ready(self) -> int:
        # a registered worker was listening when it dialed in; its
        # advertised port is the readiness handshake
        return self.record.port

    def alive(self) -> bool:
        return self._registry.is_live(self.name,
                                      generation=self.record.generation)

    def retarget(self, record: WorkerRecord) -> None:
        """Adopt a re-registration: new address, new generation.  The
        slot's ProcWorkerService re-reads host/port on every dial
        (serve/transport.py ``_wire``), so no client surgery is needed
        beyond the record swap."""
        self.record = record

    def kill(self) -> None:
        """No local signal — the lease reaper already owns eviction."""

    def terminate(self, timeout_s: float = 10.0) -> None:
        """No local signal; a remote worker outlives this fleet."""

    def status(self) -> Dict[str, Any]:
        return {"kind": "remote", "name": self.name,
                "host": self.record.host, "port": self.record.port,
                "pid": self.record.pid,
                "generation": self.record.generation,
                "alive": self.alive()}


class FleetportWorker(FleetWorker):
    """A registry-backed worker slot: a FleetWorker whose service is a
    wire facade over a :class:`RemoteWorkerLauncher` and whose placement
    predicate is the record's advertised mesh capacity."""

    def __init__(self, wid: int, make_service,
                 launcher: RemoteWorkerLauncher,
                 fail_threshold: int = 3, open_s: float = 1.0):
        self.launcher = launcher
        super().__init__(wid, make_service, devices=[],
                         fail_threshold=fail_threshold, open_s=open_s)

    def fits(self, cell) -> bool:
        return self.launcher.record.fits_lanes(cell_lane_demand(cell))

    def status(self) -> Dict[str, Any]:
        st = super().status()
        rec = self.launcher.record
        st["remote"] = {"name": rec.name, "host": rec.host,
                        "port": rec.port,
                        "mesh": "x".join(str(d) for d in rec.mesh),
                        "max-lanes": rec.max_lanes,
                        "generation": rec.generation,
                        "lease-remaining-s":
                            round(rec.lease_remaining_s(), 3),
                        "evicted": rec.evicted}
        return st


class Fleetport(Fleet):
    """The registry-backed fleet: zero constructor slots, membership by
    REGISTER frame, supervision by lease reaper.  The whole Fleet
    surface (submit/check/metrics/healthz/close) works unchanged; the
    worker list simply starts empty and grows as workers dial in."""

    def __init__(self, *, listen_host: str = "127.0.0.1",
                 listen_port: int = 0,
                 lease_s: Optional[float] = None,
                 reap_s: Optional[float] = None,
                 token: Optional[str] = None,
                 **kw):
        self.registry = FleetRegistry(lease_s)
        # the shared secret: held for mac computation only, NEVER logged
        # or exported (snapshots carry the auth-enabled boolean)
        self._token = token if token is not None else fleet_token()
        self._slots: Dict[str, FleetportWorker] = {}  # by worker name
        self._sup_lock = threading.Lock()   # slot create/rejoin/evict
        self._fp_stop = threading.Event()
        self.auth_rejections = 0
        self._reap_s = (float(reap_s) if reap_s
                        else max(min(self.registry.lease_s / 4.0, 1.0),
                                 0.05))
        kw.setdefault("pin_devices", False)
        super().__init__(workers=1, **kw)   # n floor only; slots are
        # registry-backed — _make_workers below returns the empty,
        # append-only list every later join extends in place
        self._srv = socket.socket()
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((listen_host, listen_port))
        self._srv.listen(64)
        self.listen_host = listen_host
        self.listen_port = self._srv.getsockname()[1]
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="fleetport-accept").start()
        self._reap_thread = threading.Thread(
            target=self._reap_loop, daemon=True, name="fleetport-reaper")
        self._reap_thread.start()

    def _make_workers(self, n, lanes_each, device_sets, **kw):
        return []

    # -- the wire ----------------------------------------------------------
    def _accept_loop(self) -> None:
        while True:
            try:
                sock, peer = self._srv.accept()
            except OSError:
                return  # listener closed
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            threading.Thread(target=self._serve_conn,
                             args=(sock, f"{peer[0]}:{peer[1]}"),
                             daemon=True, name="fleetport-conn").start()

    def _send(self, sock: socket.socket, frame: Dict[str, Any]) -> None:
        try:
            sock.sendall(encode_frame(sign_frame(frame, self._token),
                                      MAX_FRAME_BYTES))
        except OSError:
            pass  # the peer is gone; its next dial starts over

    def _serve_conn(self, sock: socket.socket, peer: str) -> None:
        try:
            while not self._fp_stop.is_set():
                frame = read_frame(sock, MAX_FRAME_BYTES)
                if frame is None:
                    return  # clean close
                # a frame naming a tenant (while tenant tokens are
                # configured) verifies against THAT tenant's secret —
                # the tenant field is inside the digest, so a mac minted
                # for one tenant cannot be replayed as another; a
                # claimed tenant with no issued token is a hard reject
                tok, known = self._token, True
                if frame.get(TENANT_FIELD) is not None:
                    ttoks = tenant_tokens()
                    if ttoks:
                        tok = ttoks.get(str(frame[TENANT_FIELD]))
                        known = tok is not None
                if not known or not verify_frame(frame, tok):
                    # fail closed: typed ERROR, then hangup.  Count it —
                    # the smoke asserts rejected workers never reach the
                    # registry — and log the failure MODE only, never
                    # any token or mac material (nor the claimed tenant
                    # string: it arrived unauthenticated).
                    with self._sup_lock:
                        self.auth_rejections += 1
                    self.metrics.inc("auth-rejections")
                    what = ("unknown tenant" if not known
                            else "unauthenticated frame"
                            if not isinstance(frame.get("auth"), str)
                            else "bad frame mac")
                    log.warning("rejected %s from %s", what, peer)
                    self._send(sock, {"type": F_ERROR,
                                      "id": frame.get("id"),
                                      "error": f"{what} rejected",
                                      "error-class": "AuthError"})
                    return
                ftype = frame.get("type")
                if ftype == F_REGISTER:
                    payload = self._handle_register(frame, peer)
                    if payload is None:
                        # the name is chaos-blocked (a simulated
                        # partition): refuse + hangup.  The worker sees
                        # a TransportError and keeps backoff-retrying;
                        # the heal's unblock lets the next try in.
                        self._send(sock, {"type": F_ERROR,
                                          "id": frame.get("id"),
                                          "error": "registration blocked "
                                                   "for this worker",
                                          "error-class":
                                              "RegistrationBlocked"})
                        return
                    self._send(sock, {"type": F_REPLY,
                                      "id": frame.get("id"),
                                      "payload": payload})
                elif ftype == F_TELEMETRY:
                    if not self._handle_renewal(frame):
                        # renewing a name that is no member (evicted or
                        # never registered): typed ERROR + hangup so the
                        # worker's registration loop notices the lost
                        # link and re-registers as a new generation
                        self._send(sock, {"type": F_ERROR,
                                          "id": frame.get("id"),
                                          "error": "not a registered "
                                                   "member; re-register",
                                          "error-class": "NotRegistered"})
                        return
                    if frame.get("id") is not None:
                        # the registration client renews via RPC so a
                        # refusal is observable; ack the happy path
                        self._send(sock, {"type": F_REPLY,
                                          "id": frame.get("id"),
                                          "payload": {"renewed": True}})
                else:
                    self._send(sock, {"type": F_ERROR,
                                      "id": frame.get("id"),
                                      "error": f"unexpected frame type "
                                               f"{ftype!r} at fleetport",
                                      "error-class": "FrameError"})
        except (FrameError, OSError):
            return  # torn frame / RST: this connection only
        finally:
            try:
                sock.close()
            except OSError:
                pass

    # -- membership --------------------------------------------------------
    def _handle_register(self, frame: Dict[str, Any],
                         peer: str) -> Optional[Dict[str, Any]]:
        name = str(frame.get("name") or peer)
        host = str(frame.get("host") or peer.rsplit(":", 1)[0])
        port = int(frame.get("port") or 0)
        rec, created = self.registry.register(
            name, host, port, pid=frame.get("pid"),
            devices=frame.get("devices") or (),
            mesh=frame.get("mesh") or (1,),
            buckets=frame.get("buckets") or ())
        if rec is None:
            log.warning("refused blocked registration for %s from %s",
                        name, peer)
            self.metrics.inc("registrations-refused")
            return None
        with self._sup_lock:
            slot = self._slots.get(name)
            if slot is None:
                slot = self._admit_slot(rec)
            else:
                slot.launcher.retarget(rec)
                self.registry.bind_slot(name, slot.wid)
                if created:
                    # comeback after eviction: fresh service (the old
                    # wire client died with the lease), clean breaker,
                    # fresh staleness clock
                    slot.restart()
                    self.telemetry.register(slot.wid)
                    self.metrics.inc("fleet-rejoins")
        log.info("worker %s registered from %s (wid %d, mesh %s, "
                 "gen %d)", name, peer, slot.wid,
                 "x".join(str(d) for d in rec.mesh), rec.generation)
        from jepsen_tpu.serve.fission_plane import fleetfission_threshold
        return {"registered": True, "wid": slot.wid,
                "lease-s": self.registry.lease_s,
                "generation": rec.generation,
                # sizing handshake (docs/deployment.md, "Sizing fleet
                # fission"): the fleet edge's scatter threshold rides
                # the ack so a joining worker can log when its own
                # JTPU_FISSION_THRESHOLD exceeds what the edge will
                # ever hand it in one sub-problem
                "fleetfission-threshold": fleetfission_threshold()}

    def _admit_slot(self, rec: WorkerRecord) -> FleetportWorker:
        """Append one registry-backed slot (caller holds the sup lock).
        Append-only: a wid is an index into ``self.workers`` forever —
        eviction marks the slot dead, it never removes it."""
        wid = len(self.workers)
        launcher = RemoteWorkerLauncher(rec, self.registry)
        slot = FleetportWorker(wid, self._make_slot_service(launcher),
                               launcher)
        self.workers.append(slot)
        self._slots[rec.name] = slot
        self.registry.bind_slot(rec.name, wid)
        self.telemetry.register(wid)
        self.metrics.inc("fleet-joins")
        return slot

    def _make_slot_service(self, launcher: RemoteWorkerLauncher):
        name = launcher.name

        def make():
            svc = ProcWorkerService(launcher, None,
                                    retry_policy=self.retry_policy,
                                    name=name)
            # pushes over the service wire are lease renewals too: any
            # frame that proves the worker is alive extends the lease
            # (unless chaos has renewals blocked)
            svc.on_telemetry = \
                lambda payload: self._note_named_telemetry(name, payload)
            return svc
        return make

    # -- leases ------------------------------------------------------------
    def _handle_renewal(self, frame: Dict[str, Any]) -> bool:
        """A named TELEMETRY frame at the listener: the worker's
        registration client heartbeating.  Renews the lease and lands
        the payload in the same Watchtower store the wired pushes
        feed.  Returns False when the name is no member (evicted or
        unknown) — the caller hangs up so the worker re-registers.  A
        live-but-chaos-blocked member is accepted silently (the renewal
        itself is discarded so the fault can expire the lease)."""
        name = frame.get("name")
        if not name:
            return True  # unnamed telemetry: nothing to renew
        name = str(name)
        rec = self.registry.get(name)
        if rec is None or rec.evicted:
            return False
        self._note_named_telemetry(name, frame.get("payload") or {})
        return True

    def _note_named_telemetry(self, name: str,
                              payload: Dict[str, Any]) -> None:
        if self.registry.renew(name):
            self.metrics.inc("lease-renewals")
        rec = self.registry.get(name)
        if rec is not None and rec.wid is not None:
            self._note_worker_telemetry(rec.wid, payload)

    def _reap_loop(self) -> None:
        """The supervisor, reimagined: no respawn, no SIGKILL — sweep
        the registry for spent leases and evict.  Also exports the
        lease-age high-water gauge every sweep."""
        while not self._fp_stop.is_set():
            try:
                for rec in self.registry.expire_leases():
                    self._evict(rec)
                set_gauge("fleet-lease-age-max-s",
                          round(self.registry.max_lease_age_s(), 3))
            except Exception:  # noqa: BLE001 — the reaper must outlive
                log.exception("lease reap sweep failed")  # one bad sweep
            self._fp_stop.wait(timeout=self._reap_s)

    def _evict(self, rec: WorkerRecord) -> None:
        """One lease eviction: the slot goes dead (wire dropped — its
        in-flight cells degrade to transport unknowns and reroute via
        the rendezvous ranking, draining their journal entries through
        the normal finalize path), and the telemetry plane forgets the
        member so the staleness sweep cannot alert on a ghost."""
        log.warning("lease expired for worker %s (wid %s): evicting — "
                    "no local signal; keys reroute to siblings",
                    rec.name, rec.wid)
        self.metrics.inc("lease-evictions")
        with self._sup_lock:
            slot = self._slots.get(rec.name)
            if (slot is not None
                    and slot.launcher.record.generation == rec.generation):
                try:
                    slot.service.kill()   # closes the wire client only:
                except Exception:  # noqa: BLE001 — already dead
                    pass           # RemoteWorkerLauncher.kill is a no-op
        if rec.wid is not None:
            self.telemetry.evict(rec.wid)
            self.slo.forget(rec.wid)

    # -- export ------------------------------------------------------------
    def fleet_view(self, now: Optional[float] = None) -> Dict[str, Any]:
        """The ``GET /fleet`` membership document.  Secret-free by
        construction: the registry snapshot carries addresses and lease
        arithmetic; auth status is a boolean."""
        now = mono_now() if now is None else now
        with self._sup_lock:
            rejections = self.auth_rejections
        return {"listen": {"host": self.listen_host,
                           "port": self.listen_port},
                "auth-enabled": bool(self._token),
                "auth-rejections": rejections,
                "reap-s": self._reap_s,
                **self.registry.snapshot(now)}

    def fleet_status(self) -> Dict[str, Any]:
        st = super().fleet_status()
        st["registry"] = self.registry.snapshot()
        st["auth-enabled"] = bool(self._token)
        return st

    # -- lifecycle ---------------------------------------------------------
    def _shutdown_port(self) -> None:
        self._fp_stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        if self._reap_thread.is_alive():
            self._reap_thread.join(timeout=2 * self._reap_s + 1.0)

    def close(self, timeout: Optional[float] = None) -> bool:
        self._shutdown_port()
        return super().close(timeout=timeout)

    def kill(self) -> None:
        self._shutdown_port()
        super().kill()
