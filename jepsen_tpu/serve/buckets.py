"""Shape bucketing: pad cells into a small ladder of engine shapes.

Every distinct (window, events-chunk, lane-count) triple the wgl engine
sees — and every (n_pad, lane-count) the elle closure kernel sees — is a
fresh XLA trace + compile.  Histories arriving at a service vary
continuously in length and concurrency, so without bucketing the engine
cache would see an unbounded stream of near-miss shapes and the device
would spend its life compiling.

The ladder here is coarse on purpose: power-of-two event counts, power-
of-two width/adjacency buckets, power-of-two lane groups.  Padding waste
is bounded by 2x per axis (and measured: the scheduler reports lane
occupancy through the metrics endpoint), while the shape universe
collapses to a few dozen buckets that the bounded engine LRU
(parallel.batch._CACHE) keeps resident.
"""

from __future__ import annotations

from typing import Tuple

from jepsen_tpu.history import FAIL, History, INVOKE, NEMESIS, OK

#: floor of the event-count ladder (matches the engine's 64-row chunking)
MIN_EVENTS_BUCKET = 64
#: floor of the wgl window ladder (engine windows are >= 8 anyway)
MIN_WIDTH_BUCKET = 8
#: floor of the elle adjacency ladder (graphs.padded_n rounds to >= 32)
MIN_N_BUCKET = 32
#: lanes per dispatch are padded to a power of two up to this cap; beyond
#: it groups dispatch at the cap exactly (parallel.batch groups at 512
#: internally anyway)
MAX_LANE_BUCKET = 512


def pow2_at_least(n: int, floor: int) -> int:
    b = floor
    while b < n:
        b *= 2
    return b


def events_bucket(h: History) -> int:
    return pow2_at_least(len(h), MIN_EVENTS_BUCKET)


def width_bucket(h: History) -> int:
    """Bucketed upper bound on the wgl engine window: the maximum number
    of simultaneously-open client ops (crashed ops never close — they hold
    window slots forever, exactly like the engine's ghost slots)."""
    open_ = 0
    peak = 1
    for op in h:
        if op.process == NEMESIS:
            continue
        if op.type == INVOKE:
            open_ += 1
            peak = max(peak, open_)
        elif op.type in (OK, FAIL):
            open_ = max(0, open_ - 1)
        # INFO: crashed — stays open
    return pow2_at_least(peak, MIN_WIDTH_BUCKET)


def elle_n_bucket(h: History) -> int:
    """Bucketed upper bound on the elle adjacency dimension: committed +
    indeterminate txns (encode keeps ok and info txns as graph nodes)."""
    n = sum(1 for op in h if op.type != INVOKE and op.process != NEMESIS)
    return pow2_at_least(max(1, n), MIN_N_BUCKET)


def lane_bucket(n_lanes: int, cap: int = MAX_LANE_BUCKET) -> int:
    """Lanes per dispatch, padded to a power of two (stable ``bpad`` in
    the engine cache key) and clamped to ``cap``."""
    return min(pow2_at_least(max(1, n_lanes), 1), cap)


#: floor of the per-worker lane ladder: a fleet worker never dispatches
#: narrower groups than this, however many siblings share the device.
MIN_WORKER_LANES = 8


def worker_lane_share(total_lanes: int, n_workers: int) -> int:
    """A fleet worker's per-dispatch lane budget when one device's lane
    allowance is split across N workers: ceil-divide, then round UP onto
    the power-of-two ladder (floor :data:`MIN_WORKER_LANES`).  Rounding
    up — not down — keeps every worker's dispatches on the same ladder
    rungs a solo service would use, so the fleet and the single-service
    oracle share compiled-engine cache entries instead of doubling the
    shape universe."""
    n = max(1, n_workers)
    share = (max(1, total_lanes) + n - 1) // n
    return min(MAX_LANE_BUCKET,
               pow2_at_least(max(share, MIN_WORKER_LANES),
                             MIN_WORKER_LANES))


def proc_worker_lanes(total_lanes: int, n_workers: int,
                      shared_host: bool = True) -> int:
    """A ProcFleet worker's per-dispatch lane budget.  Out-of-process
    workers on ONE host (today's shape: N subprocesses sharing the
    host's device) still split the device's lane allowance, so the
    budget divides exactly like :func:`worker_lane_share` — same ladder
    rungs, same shared compile cache with the solo oracle.  Workers that
    will land on their *own* hosts (``shared_host=False``, the
    multi-host direction) each take the full rung: nothing is shared,
    and dividing would just waste their private device."""
    if not shared_host:
        return min(MAX_LANE_BUCKET,
                   pow2_at_least(max(max(1, total_lanes),
                                     MIN_WORKER_LANES),
                                 MIN_WORKER_LANES))
    return worker_lane_share(total_lanes, n_workers)


#: ceiling of the megabatch lane-count ladder: concurrently-resident
#: device lanes across a bucket's groups.  Lanes beyond MAX_LANE_BUCKET
#: run as grouped vmaps of <= MAX_LANE_BUCKET width that reuse ONE
#: compiled executable (the same engine-cache entry; reuse shows up as
#: the cache's ``group_reuses`` counter) — the vmap width never grows
#: past the 512-lane bool-scatter cliff documented in parallel.batch.
MAX_MEGA_LANES = 4096

#: event buckets at or below this route through the megabatch refill
#: path when it is enabled — the "small-history path" whose steady-state
#: traffic is thousands of short per-key cells.  Larger buckets keep the
#: barrier path: their lanes are few and long, so refill wins nothing.
MEGA_EVENTS_MAX = 1024


def mega_lane_bucket(n_lanes: int, cap: int = MAX_MEGA_LANES) -> int:
    """Concurrently-resident lanes for the megabatch path: a power of
    two up to :data:`MAX_MEGA_LANES` (>= 512 means multiple grouped
    vmaps sharing one executable).  Same ladder discipline as
    :func:`lane_bucket`, one rung higher."""
    return min(pow2_at_least(max(1, n_lanes), 1), cap)


#: floor of the model state-width ladder: packed per-configuration model
#: states (register scalars, queue rings, set bitmask words, txn-register
#: key vectors) quantize onto pow2 widths starting here, so the carry
#: layout the megabatch path compiles for is a pure function of the
#: bucket — a queue sized by ``derive_queue_slots`` and a bare register
#: land on the SAME finite rung set.
MIN_STATE_WIDTH_BUCKET = 4


def state_width_bucket(state_width: int) -> int:
    """The pow2 rung for a model's packed int32 state width (the
    ``JaxModel.state_size`` axis of the megabatch carry).  Model sizing
    hooks (``derive_queue_slots`` etc.) already emit pow2 sizes, so this
    collapses the per-model width spread onto a handful of rungs shared
    by every model family — the state axis of the bounded shape universe
    megabatch and ``check_batch`` dispatch from."""
    return pow2_at_least(max(1, state_width), MIN_STATE_WIDTH_BUCKET)


#: floor / ceiling of the derived wgl start-capacity ladder
MIN_WGL_CAPACITY = 64
MAX_WGL_CAPACITY = 65536


def wgl_start_capacity(ev_bucket: int, w_bucket: int) -> int:
    """Derive the wgl engine's *starting* configuration capacity from the
    bucket shape instead of a fixed knob.

    The config frontier is bounded by (subsets of the pending window) x
    (reachable model states); in practice it tracks the window width far
    more than history length, so the ladder is quadratic in the width
    bucket (w=8 -> 256, the old fixed default; w=16 -> 1024; w=32 ->
    4096), hard-capped by both 2**w (the true subset bound for small
    windows) and :data:`MAX_WGL_CAPACITY`.  Longer event streams do not
    widen the frontier per step, so ``ev_bucket`` only nudges the floor
    up for big histories (avoids one guaranteed escalation round-trip on
    multi-thousand-op cells).

    Crucially this is a pure function of the (ev, w) bucket, so the
    derived capacity is constant per bucket and the compiled-engine
    cache key stays stable — deriving from raw history shape would leak
    the unbounded shape universe right back into the cache.

    The ``JEPSEN_TPU_WGL_CAPACITY`` env var overrides the derivation
    (resolved by the scheduler, not here), and per-request ``capacity``
    engine opts override both.
    """
    cap = pow2_at_least(4 * w_bucket * w_bucket, MIN_WGL_CAPACITY)
    if ev_bucket >= 4096:
        cap *= 2
    if w_bucket < 16:
        cap = min(cap, 2 ** w_bucket)
    return max(MIN_WGL_CAPACITY, min(cap, MAX_WGL_CAPACITY))


#: floor / ceiling of the streaming monitor's per-epoch dispatch ladder.
#: A monitored stream's epoch delivers a raw new-op count that varies
#: continuously; the device-resident frontier (engine/stream.py) pads each
#: epoch's event rows onto this pow2 ladder so the compiled epoch-advance
#: executable is keyed on a handful of chunk rungs, not on raw epoch sizes.
#: The ceiling keeps one epoch dispatch's scan bounded — a larger backlog
#: simply dispatches several ceiling-sized chunks.
MIN_EPOCH_EVENTS_BUCKET = 64
MAX_EPOCH_EVENTS_BUCKET = 2048


def epoch_events_bucket(n_new: int) -> int:
    """The stream engine's per-epoch event-chunk rung: pow2 at least the
    new-op count, clamped to [MIN_EPOCH_EVENTS_BUCKET,
    MAX_EPOCH_EVENTS_BUCKET].  Pure function of the new-op count alone —
    total history length must never reach an epoch dispatch shape, or the
    compiled-signature universe grows with stream lifetime (the exact
    leak TRACE02's stream leg guards)."""
    return min(pow2_at_least(max(1, n_new), MIN_EPOCH_EVENTS_BUCKET),
               MAX_EPOCH_EVENTS_BUCKET)


def wgl_bucket(h: History) -> Tuple[int, int]:
    return (events_bucket(h), width_bucket(h))


def elle_bucket(h: History) -> Tuple[int]:
    return (elle_n_bucket(h),)
