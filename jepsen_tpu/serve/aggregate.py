"""The aggregator: fold per-cell verdicts back into per-request results.

Merging follows the established verdict lattice (checker.core
.merge_valid: false beats unknown beats true) — the same never-degrade
rules every composed checker in the repo obeys.  In particular a
deadline-expired cell contributes ``unknown``, never ``false``: missing
a deadline says nothing about the history.

A request that decomposed into per-key cells aggregates into the
IndependentChecker result shape ({"valid", "key-count", "results",
"failures"}) so downstream consumers (store artifacts, the web UI's
validity coloring, run_tests exit codes) cannot tell a serviced check
from a direct one.  Single-cell requests return the engine result
itself, annotated.

Distributed fission (serve.fission_plane) adds a pre-pass: child cells
carrying a ``fission`` group membership recombine into one verdict per
group — under the exact unknown-never-false table from docs/fission.md
— *before* the ordinary per-key merge sees them, so a scattered cell
aggregates byte-compatibly with the whole cell it replaced.  The
distributed table is stricter than the engine's on evidence: a group
``False`` REQUIRES the refuting sub-problem's op and witness (the
fission plane's witness-recovery seam guarantees they were pursued);
an unwitnessed refutation degrades the group to unknown.  There is
also no fleet-side escalation ceiling: the engine's "ghosts: else →
monolithic escalation" row becomes unknown here (the worker-local
shrink recursion already ran inside each sub-problem).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from jepsen_tpu.checker.core import merge_valid
from jepsen_tpu.serve.request import Cell, Request


def aggregate(req: Request) -> Dict[str, Any]:
    slots = _grouped_slots(req)
    if len(slots) == 1 and slots[0][0] is None:
        return dict(slots[0][1] or {})
    results = {k: r for k, r in slots}  # decompose order = key order
    bad = {k: r for k, r in results.items()
           if (r or {}).get("valid") is not True}
    return {"valid": merge_valid([(r or {}).get("valid")
                                  for r in results.values()]),
            "key-count": len(slots),
            "results": results,
            "failures": sorted(bad, key=repr)}


def _grouped_slots(req: Request) -> List[Tuple[Any, Optional[Dict]]]:
    """The per-key (key, result) sequence the merge runs over, with each
    fission group recombined into the single slot its parent cell held.
    Non-fission cells pass through in decompose order."""
    slots: List[Tuple[Any, Optional[Dict]]] = []
    groups: Dict[str, Tuple[int, List[Cell]]] = {}
    for c in req.cells:
        if c.fission is None:
            slots.append((c.key, c.result))
            continue
        gid = c.fission["group"]
        if gid not in groups:
            groups[gid] = (len(slots), [])
            slots.append((c.key, None))  # placeholder at the parent's slot
        groups[gid][1].append(c)
    for gid, (pos, children) in groups.items():
        children.sort(key=lambda c: c.fission["index"])
        slots[pos] = (slots[pos][0], recombine_group(children))
    return slots


def recombine_group(children: List[Cell]) -> Dict[str, Any]:
    """Fold one fission group's child verdicts into the verdict of the
    cell that scattered (docs/fission.md, "Distributed recombination").

    components: any witnessed False → False (that child's op/witness);
    all True → True; else unknown.  ghosts: any True → True; all False
    with a witnessed all-elided branch → False (its op/witness); else
    unknown.  Cancelled and lost children contribute unknown, which the
    deciding rows dominate and the unknown rows absorb — no path
    fabricates False."""
    mode = children[0].fission["mode"]
    n = children[0].fission["subproblems"]
    results = [c.result or {} for c in children]
    explored = sum(int(r.get("configs-explored", 0) or 0) for r in results)
    meta = {"mode": mode, "distributed": True, "subproblems": n}
    if mode == "components":
        for i, r in enumerate(results):
            if r.get("valid") is False and "op" in r and "witness" in r:
                # witness: the refuting sub-problem's own op + witness travel with the group False (P-compositionality: a refuted projection refutes the whole)
                return {"valid": False, "analyzer": r.get("analyzer"),
                        "op": r["op"], "witness": r["witness"],
                        "configs-explored": explored,
                        "fission": {**meta, "refuting-subproblem": i}}
        if len(results) == n and all(r.get("valid") is True
                                     for r in results):
            return {"valid": True, "analyzer": "fleet-fission",
                    "configs-explored": explored, "fission": meta}
        return _indefinite(results, explored, meta,
                           "component conjunction indefinite")
    # ghosts: an exact disjunction over crashed-op outcomes
    for r in results:
        if r.get("valid") is True:
            return {"valid": True, "analyzer": "fleet-fission",
                    "configs-explored": explored, "fission": meta}
    r0 = results[0] if children[0].fission["index"] == 0 else {}
    if len(results) == n and all(r.get("valid") is False for r in results) \
            and "op" in r0 and "witness" in r0:
        # witness: all 2^ghosts branches refuted; the all-elided branch's op + witness are the canonical evidence
        return {"valid": False, "analyzer": r0.get("analyzer"),
                "op": r0["op"], "witness": r0["witness"],
                "configs-explored": explored, "fission": meta}
    return _indefinite(results, explored, meta,
                       "ghost case-split indefinite "
                       "(no fleet-side escalation ceiling)")


def _indefinite(results: List[Dict[str, Any]], explored: int,
                meta: Dict[str, Any], why: str) -> Dict[str, Any]:
    errs = [str(r.get("error")) for r in results if r.get("error")]
    return {"valid": "unknown", "analyzer": "fleet-fission",
            "error": f"{why}: {errs[0]}" if errs else why,
            "configs-explored": explored, "fission": dict(meta)}


def expired_result(kind: str) -> Dict[str, Any]:
    """The verdict for a cell whose deadline passed before dispatch —
    unknown with the same shape check_safe's budget path produces, so
    deadline semantics read identically service-side and direct."""
    return {"valid": "unknown", "deadline-expired": True,
            "analyzer": f"{kind}-serve",
            "error": "request deadline expired before dispatch"}
