"""The aggregator: fold per-cell verdicts back into per-request results.

Merging follows the established verdict lattice (checker.core
.merge_valid: false beats unknown beats true) — the same never-degrade
rules every composed checker in the repo obeys.  In particular a
deadline-expired cell contributes ``unknown``, never ``false``: missing
a deadline says nothing about the history.

A request that decomposed into per-key cells aggregates into the
IndependentChecker result shape ({"valid", "key-count", "results",
"failures"}) so downstream consumers (store artifacts, the web UI's
validity coloring, run_tests exit codes) cannot tell a serviced check
from a direct one.  Single-cell requests return the engine result
itself, annotated.
"""

from __future__ import annotations

from typing import Any, Dict

from jepsen_tpu.checker.core import merge_valid
from jepsen_tpu.serve.request import Request


def aggregate(req: Request) -> Dict[str, Any]:
    cells = req.cells
    if len(cells) == 1 and cells[0].key is None:
        return dict(cells[0].result or {})
    results = {c.key: c.result for c in cells}  # decompose order = key order
    bad = {k: r for k, r in results.items()
           if (r or {}).get("valid") is not True}
    return {"valid": merge_valid([(r or {}).get("valid")
                                  for r in results.values()]),
            "key-count": len(cells),
            "results": results,
            "failures": sorted(bad, key=repr)}


def expired_result(kind: str) -> Dict[str, Any]:
    """The verdict for a cell whose deadline passed before dispatch —
    unknown with the same shape check_safe's budget path produces, so
    deadline semantics read identically service-side and direct."""
    return {"valid": "unknown", "deadline-expired": True,
            "analyzer": f"{kind}-serve",
            "error": "request deadline expired before dispatch"}
