"""serve: the persistent batched checking service.

A checking run today is a cold one-shot: ``core.analyze`` builds its own
batch, pays its own XLA compile, and the device idles between runs.
This package keeps the device saturated instead: a persistent in-process
service accepts history-check requests from concurrent test runs, the
CLI, and the web UI, decomposes them into independent per-key cells
(P-compositionality — jepsen_tpu.independent's splitting), pads the
cells into a small ladder of engine shapes, and continuously batches
them onto the vmapped wgl (parallel.batch) and elle (elle_tpu.engine)
device engines, merging verdicts back per request under the established
never-degrade-to-false rules.

Module map: ``request`` (requests/cells/trace spans), ``decompose``
(per-key splitting), ``buckets`` (the shape ladder), ``scheduler`` (the
continuous-batch device loop: priority queue, admission, backpressure,
deadlines, host-tier degradation), ``aggregate`` (verdict merge),
``metrics`` (counters/gauges/histograms/traces for web.py's
``/metrics``, backed by the jepsen_tpu.obs instruments: distributed
trace contexts, pow2-ladder latency histograms, the process flight
recorder), ``service`` (the CheckService facade + core.analyze
routing), ``router`` (rendezvous hashing + per-worker circuit
breakers/health), ``fleet`` (the fault-tolerant multi-worker tier: N
worker services, retry/hedge, crash journal, the fleet-wide metrics
scrape and ``merged_trace``), ``chaos`` (the fleet's self-nemesis).
See docs/serving.md, docs/robustness.md and docs/observability.md.

``Fleet`` is imported lazily (``from jepsen_tpu.serve.fleet import
Fleet``) to keep the plain single-service import path light.
"""

from jepsen_tpu.serve.request import Cell, Request  # noqa: F401
from jepsen_tpu.serve.service import (  # noqa: F401
    CheckService, ServiceClosed, ServiceSaturated,
)

__all__ = ["Cell", "CheckService", "Request", "ServiceClosed",
           "ServiceSaturated"]
