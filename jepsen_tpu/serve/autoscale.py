"""Governor: the SLO-burn-driven autoscaler policy loop.

Watchtower (obs/telemetry.py, obs/slo.py) observes; the fleet can admit
and evict slots at runtime; this module closes the loop.  Each tick the
Governor reads three signals — active SLO breach episodes, queue
occupancy (open cells over the admission ceiling), and the oldest head
wait-age (the scheduler's aged-tier signal, surfaced via
``queue_occupancy``) — and decides **up**, **down**, or **hold**.

The policy is deliberately boring, because an autoscaler that reacts to
every alert IS an outage amplifier:

- **hysteresis** — scale up only after the hot condition has been
  continuously true for ``up_after_s``; scale down only after
  continuously quiet for ``down_after_s``.  A breach/recover oscillation
  (an alert storm) keeps resetting both clocks and produces nothing.
- **cooldown** — after ANY action, no further action for ``cooldown_s``:
  at most one scale action per cooldown window, by construction.
- **bounded** — never below ``min_workers``, never above
  ``max_workers``.
- **drain-clean scale-down** — the victim slot is marked draining (the
  router stops ranking it), and dies only once it is idle AND the fleet
  journal has zero pending cells (Fleet.decommission_worker).  A drain
  that cannot complete aborts and the slot returns to service.

Scale-up runs through the fleet when it can build slots in-process
(Fleet.add_worker); fleets whose workers live elsewhere (ProcFleet,
registry-backed Fleetport deployments) get a **structured scale
request** instead — a dict the deployment layer consumes from
``snapshot()["scale-requests"]`` (or a ``scale_request_sink`` callback)
to actually provision a machine, mirroring how the worker then joins by
REGISTER frame.

Every decision — including holds that changed the hysteresis state —
lands in a bounded ring exported on ``/metrics`` (the fleet snapshot's
``autoscale`` section) and in the flight recorder (category ``scale``),
so a post-incident export shows scale actions on the same axis as the
alerts that caused them.

Env knobs (read by ``AutoscalePolicy.from_env``)::

    JEPSEN_TPU_AUTOSCALE_MIN            floor, default 1
    JEPSEN_TPU_AUTOSCALE_MAX            ceiling, default 8
    JEPSEN_TPU_AUTOSCALE_COOLDOWN_S     action cooldown, default 30
    JEPSEN_TPU_AUTOSCALE_UP_S           hot sustain, default 5
    JEPSEN_TPU_AUTOSCALE_DOWN_S         quiet sustain, default 60
    JEPSEN_TPU_AUTOSCALE_INTERVAL_S     tick cadence, default 1
    JEPSEN_TPU_AUTOSCALE_QUEUE_HIGH     hot occupancy fraction, 0.8
    JEPSEN_TPU_AUTOSCALE_QUEUE_LOW      quiet occupancy fraction, 0.1
    JEPSEN_TPU_AUTOSCALE_WAIT_HIGH_S    hot oldest-wait-age, default 10
    JEPSEN_TPU_AUTOSCALE_DRAIN_TIMEOUT_S  scale-down drain bound, 30
"""

from __future__ import annotations

import os
import threading
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

from jepsen_tpu.obs.recorder import RECORDER
from jepsen_tpu.serve.metrics import mono_now

#: decision ring capacity
DECISION_CAPACITY = 256
#: pending structured scale requests kept for the deployment layer
REQUEST_CAPACITY = 64


def _env_num(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    try:
        return float(raw) if raw.strip() else default
    except ValueError:
        return default


@dataclass
class AutoscalePolicy:
    """The Governor's tuning — see the module docstring for semantics."""

    min_workers: int = 1
    max_workers: int = 8
    cooldown_s: float = 30.0
    up_after_s: float = 5.0
    down_after_s: float = 60.0
    interval_s: float = 1.0
    queue_high: float = 0.8
    queue_low: float = 0.1
    wait_high_s: float = 10.0
    drain_timeout_s: float = 30.0

    @classmethod
    def from_env(cls) -> "AutoscalePolicy":
        e = "JEPSEN_TPU_AUTOSCALE"
        return cls(
            min_workers=int(_env_num(f"{e}_MIN", 1)),
            max_workers=int(_env_num(f"{e}_MAX", 8)),
            cooldown_s=_env_num(f"{e}_COOLDOWN_S", 30.0),
            up_after_s=_env_num(f"{e}_UP_S", 5.0),
            down_after_s=_env_num(f"{e}_DOWN_S", 60.0),
            interval_s=_env_num(f"{e}_INTERVAL_S", 1.0),
            queue_high=_env_num(f"{e}_QUEUE_HIGH", 0.8),
            queue_low=_env_num(f"{e}_QUEUE_LOW", 0.1),
            wait_high_s=_env_num(f"{e}_WAIT_HIGH_S", 10.0),
            drain_timeout_s=_env_num(f"{e}_DRAIN_TIMEOUT_S", 30.0))

    def doc(self) -> Dict[str, Any]:
        return {"min-workers": self.min_workers,
                "max-workers": self.max_workers,
                "cooldown-s": self.cooldown_s,
                "up-after-s": self.up_after_s,
                "down-after-s": self.down_after_s,
                "interval-s": self.interval_s,
                "queue-high": self.queue_high,
                "queue-low": self.queue_low,
                "wait-high-s": self.wait_high_s,
                "drain-timeout-s": self.drain_timeout_s}


class Autoscaler:
    """The policy loop.  ``fleet`` may be None for pure policy testing —
    every action then becomes a structured scale request.  A custom
    ``signals_fn`` overrides the fleet-derived signal read (the
    alert-storm hysteresis tests drive the loop with a synthetic signal
    box and an explicit clock)."""

    def __init__(self, fleet=None,
                 policy: Optional[AutoscalePolicy] = None,
                 signals_fn: Optional[Callable[[], Dict[str, Any]]] = None,
                 scale_request_sink: Optional[
                     Callable[[Dict[str, Any]], None]] = None):
        self.fleet = fleet
        self.policy = policy or AutoscalePolicy.from_env()
        self._signals_fn = signals_fn
        self._sink = scale_request_sink
        # policy state only under this lock — signal reads and scale
        # actions (which take fleet/scheduler locks) happen outside it
        self._lock = threading.Lock()
        self._hot_since: Optional[float] = None
        self._quiet_since: Optional[float] = None
        self._last_action_t = float("-inf")
        self._decisions: deque = deque(maxlen=DECISION_CAPACITY)
        self._requests: deque = deque(maxlen=REQUEST_CAPACITY)
        self._counters = {"ups": 0, "downs": 0, "holds": 0,
                          "drain-aborts": 0, "requests-emitted": 0}
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        if fleet is not None:
            # the fleet snapshot exports our decision ring (/metrics)
            fleet.governor = self

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "Autoscaler":
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="governor")
            self._thread.start()
        return self

    def close(self) -> None:
        with self._lock:
            self._closed = True
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=2 * self.policy.interval_s + 1.0)

    def __enter__(self) -> "Autoscaler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def _is_closed(self) -> bool:
        with self._lock:
            return self._closed

    def _loop(self) -> None:
        import time
        while not self._is_closed():
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the loop must survive a
                pass           # torn signal read or a failed action
            time.sleep(self.policy.interval_s)

    # -- signals ----------------------------------------------------------
    def _signals(self) -> Dict[str, Any]:
        if self._signals_fn is not None:
            return dict(self._signals_fn())
        f = self.fleet
        if f is None:
            return {"breaches": 0, "occupancy": 0.0, "oldest-wait-s": 0.0,
                    "workers": 0, "journal-pending": 0}
        occ_info = f.queue_occupancy()
        depth = int(occ_info.get("depth", 0))
        return {
            "breaches": len(f.slo.snapshot().get("active-breaches", [])),
            "occupancy": round(depth / max(1, f.max_queue_cells), 4),
            "depth": depth,
            "oldest-wait-s": float(occ_info.get("oldest-wait-s", 0.0)),
            "workers": f.active_workers(),
            "journal-pending": f.journal_pending(),
        }

    # -- the decision -----------------------------------------------------
    def tick(self, now: Optional[float] = None) -> Optional[Dict[str, Any]]:
        """One policy evaluation.  Returns the decision dict when an
        action (or an emitted scale request) happened, None on hold."""
        now = mono_now() if now is None else now
        p = self.policy
        sig = self._signals()
        hot = (sig.get("breaches", 0) > 0
               or sig.get("occupancy", 0.0) >= p.queue_high
               or sig.get("oldest-wait-s", 0.0) >= p.wait_high_s)
        quiet = (sig.get("breaches", 0) == 0
                 and sig.get("occupancy", 0.0) <= p.queue_low
                 and sig.get("oldest-wait-s", 0.0) < p.wait_high_s)
        workers = int(sig.get("workers", 0))
        action = None
        with self._lock:
            if hot:
                self._quiet_since = None
                if self._hot_since is None:
                    self._hot_since = now
            elif quiet:
                self._hot_since = None
                if self._quiet_since is None:
                    self._quiet_since = now
            else:
                # neither hot nor quiet: both hysteresis clocks reset —
                # a half-recovered system earns neither direction
                self._hot_since = self._quiet_since = None
            if now - self._last_action_t >= p.cooldown_s:
                if (hot and self._hot_since is not None
                        and now - self._hot_since >= p.up_after_s
                        and workers < p.max_workers):
                    action = "up"
                elif (quiet and self._quiet_since is not None
                        and now - self._quiet_since >= p.down_after_s
                        and workers > p.min_workers):
                    action = "down"
            if action is None:
                self._counters["holds"] += 1
                return None
            # one action per cooldown window, and a fresh sustain is
            # required before the next — both clocks restart here
            self._last_action_t = now
            self._hot_since = self._quiet_since = None
        if action == "up":
            return self._scale_up(sig, now)
        return self._scale_down(sig, now)

    # -- actions ----------------------------------------------------------
    def _scale_up(self, sig: Dict[str, Any], now: float) -> Dict[str, Any]:
        workers = int(sig.get("workers", 0))
        f = self.fleet
        if f is not None and f.can_scale_locally():
            w = f.add_worker()
            decision = self._record({
                "t": round(now, 6), "action": "up", "mode": "spawn",
                "from": workers, "to": workers + 1, "worker": w.wid,
                "reason": self._reason(sig), "signals": sig})
            with self._lock:
                self._counters["ups"] += 1
            return decision
        req = {"t": round(now, 6), "action": "scale-up",
               "from": workers, "to": workers + 1,
               "reason": self._reason(sig), "signals": sig}
        with self._lock:
            self._requests.append(req)
            self._counters["requests-emitted"] += 1
            self._counters["ups"] += 1
        if self._sink is not None:
            try:
                self._sink(dict(req))
            except Exception:  # noqa: BLE001 — a broken sink must not
                pass           # kill the policy loop
        return self._record({**req, "action": "up", "mode": "request"})

    def _scale_down(self, sig: Dict[str, Any], now: float) -> Dict[str, Any]:
        workers = int(sig.get("workers", 0))
        f = self.fleet
        if f is None:
            req = {"t": round(now, 6), "action": "scale-down",
                   "from": workers, "to": workers - 1,
                   "reason": self._reason(sig), "signals": sig}
            with self._lock:
                self._requests.append(req)
                self._counters["requests-emitted"] += 1
                self._counters["downs"] += 1
            return self._record({**req, "action": "down",
                                 "mode": "request"})
        victim = self._pick_victim()
        if victim is None:
            return self._record({
                "t": round(now, 6), "action": "down", "mode": "skip",
                "from": workers, "to": workers,
                "reason": "no drainable worker", "signals": sig})
        res = f.decommission_worker(victim, timeout_s=p_drain(self.policy))
        with self._lock:
            if res.get("drained"):
                self._counters["downs"] += 1
            else:
                self._counters["drain-aborts"] += 1
        return self._record({
            "t": round(now, 6), "action": "down", "mode": "drain",
            "from": workers,
            "to": workers - 1 if res.get("drained") else workers,
            "worker": victim, "drained": bool(res.get("drained")),
            "journal-pending": res.get("journal-pending"),
            "reason": self._reason(sig), "signals": sig})

    def _pick_victim(self) -> Optional[int]:
        """Newest slot first (highest wid): wid 0 stays the stable
        anchor, and append-only wids mean the retired id never comes
        back."""
        f = self.fleet
        best = None
        for w in f.workers:
            if w.alive() and not w.draining and not w.retired:
                best = w.wid if best is None else max(best, w.wid)
        return best

    @staticmethod
    def _reason(sig: Dict[str, Any]) -> str:
        parts = []
        if sig.get("breaches", 0) > 0:
            parts.append(f"{sig['breaches']} SLO breach(es)")
        parts.append(f"occupancy {sig.get('occupancy', 0.0)}")
        parts.append(f"oldest-wait {sig.get('oldest-wait-s', 0.0)}s")
        return ", ".join(parts)

    def _record(self, decision: Dict[str, Any]) -> Dict[str, Any]:
        with self._lock:
            self._decisions.append(decision)
        RECORDER.record("scale", f"governor:{decision['action']}",
                        args=dict(decision))
        f = self.fleet
        if f is not None:
            f.metrics.inc(f"autoscale-{decision['action']}s")
        return decision

    # -- export -----------------------------------------------------------
    def scale_requests(self, clear: bool = False) -> list:
        """Pending structured scale requests for the deployment layer.
        ``clear=True`` consumes them (the deployment layer acked)."""
        with self._lock:
            out = [dict(r) for r in self._requests]
            if clear:
                self._requests.clear()
            return out

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"policy": self.policy.doc(),
                    "counters": dict(self._counters),
                    "decisions": [dict(d) for d in self._decisions],
                    "scale-requests": [dict(r) for r in self._requests]}


def p_drain(policy: AutoscalePolicy) -> float:
    return max(policy.drain_timeout_s, 0.0)
