"""The decomposer: split a request's history into independent cells.

Reuses jepsen_tpu.independent's splitting verbatim: a multi-key history
(every client op's value a ``(key, value)`` tuple, the independent-
workload wire shape) splits into one cell per key with the values
unwrapped — the same per-key sub-histories IndependentChecker would have
checked, so verdicts compose identically (P-compositionality: a history
is linearizable iff every per-key projection is).  Anything else — a
single-register history, an elle transaction history whose anomalies span
keys — stays one cell.

Cells share the request id; the aggregator reassembles them under the
established never-degrade-to-false merge (checker.core.merge_valid).
"""

from __future__ import annotations

from typing import List

from jepsen_tpu.history import NEMESIS
from jepsen_tpu.independent import history_keys, key_of, subhistory
from jepsen_tpu.serve import buckets
from jepsen_tpu.serve.request import Cell, KIND_ELLE, KIND_WGL, Request


def _engine_identity(req: Request):
    """Everything that changes what a dispatch computes must be part of
    the grouping key — cells sharing a bucket are checked by ONE engine
    call using the group head's spec."""
    if req.kind == KIND_WGL:
        m = req.spec["model"]
        # the fission flag changes the engine a lane runs through
        # (split-and-recombine vs pure ladder), so cells carrying
        # different flags must never share one dispatch group
        return (m.name, m.variant, req.spec.get("fission"))
    return (req.spec.get("workload", "list-append"),
            bool(req.spec.get("realtime", False)),
            req.spec.get("engine", "auto"),
            tuple(req.spec.get("consistency_models") or ()))


def _splittable(req: Request) -> bool:
    """True when every client op carries a key — the independent-workload
    shape.  A partially-keyed history never splits: dropping the keyless
    ops would silently change the verdict."""
    if req.kind != KIND_WGL:
        return False
    saw = False
    for op in req.history:
        if op.process == NEMESIS:
            continue
        if key_of(op) is None:
            return False
        saw = True
    return saw


def decompose(req: Request) -> List[Cell]:
    """Split ``req`` into cells (at least one), bucketed and ready to
    queue.  Sets ``req.cells`` as a side effect."""
    ident = _engine_identity(req)
    if _splittable(req):
        subs = [(k, subhistory(k, req.history))
                for k in history_keys(req.history)]
    else:
        subs = [(None, req.history)]
    cells = []
    for key, h in subs:
        shape = (buckets.wgl_bucket(h) if req.kind == KIND_WGL
                 else buckets.elle_bucket(h))
        cells.append(Cell(request=req, history=h, key=key,
                          bucket=(req.kind, ident) + shape))
    req.cells = cells
    return cells
