"""Requests, cells, and trace spans — the service's unit of work.

A *request* is one history plus a check spec (which engine kind, which
model/workload, a deadline).  The decomposer splits it into *cells* —
independent per-key sub-histories (P-compositionality, arXiv:1504.00204)
— which are what the scheduler actually queues, packs, and dispatches.
The aggregator folds cell verdicts back into one per-request result.

Every request carries a trace: monotonic spans from ``enqueue`` through
``pack``/``dispatch`` to ``verdict``, exported via the metrics endpoint
so queueing delay, packing delay, and device time are separable without
a profiler.

Distributed tracing rides on top (jepsen_tpu.obs.trace): the root
request mints a ``trace-id`` and root ``span-id`` at submit; a child
request created on another hop (wire client, worker process) adopts the
trace-id from the propagated context and records the sender's span-id
as its ``parent-span-id``.  Span times stay relative to the *local*
monotonic clock — each request also captures one wall anchor
(``anchor-unix-s``) at submit so export can place spans from different
processes on a shared absolute axis; the anchor never feeds deadline
logic.  Completed child payloads are absorbed into the parent's
``remote`` list, so the root's exported payload is the whole causal
tree.
"""

from __future__ import annotations

import itertools
import os
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from jepsen_tpu.history import History
from jepsen_tpu.obs import trace as obs_trace
from jepsen_tpu.serve.metrics import mono_now

_ids = itertools.count(1)

#: engine kinds the device loop knows how to batch
KIND_WGL = "wgl"
KIND_ELLE = "elle"
KINDS = (KIND_WGL, KIND_ELLE)


class Request:
    """One submitted history check, decomposed into cells by the service."""

    def __init__(self, history: History, kind: str, spec: Dict[str, Any],
                 deadline_s: Optional[float] = None,
                 trace: Optional[Dict[str, Any]] = None,
                 tenant: Optional[str] = None, priority: int = 0):
        if kind not in KINDS:
            raise ValueError(f"unknown request kind {kind!r}; known: {KINDS}")
        self.id = next(_ids)
        self.history = history
        self.kind = kind
        self.spec = spec            # kind-specific engine options
        # tenant identity and priority class ride *beside* the spec (like
        # the trace context) so engine option round-trips — build_spec,
        # journal recovery, wire submit kwargs — never see them
        self.tenant = tenant
        self.priority = int(priority)
        self.on_finish = None       # e.g. tenant quota release (tenants.py)
        self.submitted = mono_now()
        self.deadline = (self.submitted + deadline_s
                         if deadline_s is not None else None)
        self.cells: List["Cell"] = []
        self.spans: List[Dict[str, Any]] = []
        self.result: Optional[Dict[str, Any]] = None
        self._done = threading.Event()
        self._lock = threading.Lock()
        self._finishing = False
        # trace context: adopt a propagated context (child request on a
        # new hop) or mint a fresh root; the wall anchor is captured
        # once here and used only for export alignment
        ctx = obs_trace.parse_context(trace)
        self.trace_id = ctx[obs_trace.CTX_TRACE] or obs_trace.new_trace_id()
        self.parent_span_id = ctx[obs_trace.CTX_PARENT]
        self.span_id = obs_trace.new_span_id()
        self.anchor_unix_s = round(obs_trace.wall_anchor(), 6)
        self._remote: List[Dict[str, Any]] = []
        self.span("enqueue")

    # -- trace ------------------------------------------------------------
    def span(self, name: str) -> None:
        """Record a trace span (relative seconds since submit)."""
        self.spans.append({"span": name,
                           "t": round(mono_now() - self.submitted, 6)})

    def trace_context(self) -> Dict[str, str]:
        """The context to propagate on a child submit: same trace, this
        request's span as the parent."""
        return obs_trace.make_context(self.trace_id, self.span_id)

    def absorb_serve(self, result: Optional[Dict[str, Any]]) -> None:
        """Pull a child result's serve payload (and the remotes it
        already absorbed) into this request's remote-span list, so the
        causal tree survives aggregation and wire hops.  Payloads from
        a different trace (a dedup hit on a recycled worker cache) are
        dropped rather than grafted onto the wrong tree.  Idempotent by
        span-id: a payload absorbed once per attempt and again when the
        aggregated result flows through ``finish`` lands once."""
        serve = (result or {}).get("serve")
        if not isinstance(serve, dict):
            return
        entries: List[Dict[str, Any]] = []
        for r in serve.get("remote") or []:
            if isinstance(r, dict) and r.get("trace-id") == self.trace_id:
                entries.append(r)
        if serve.get("trace-id") == self.trace_id \
                and serve.get("span-id") != self.span_id:
            entries.append({k: serve.get(k) for k in
                            ("request-id", "trace-id", "span-id",
                             "parent-span-id", "anchor-unix-s", "pid",
                             "spans")})
        if not entries:
            return
        with self._lock:
            seen = {r.get("span-id") for r in self._remote}
            seen.add(self.span_id)
            for e in entries:
                if e.get("span-id") not in seen:
                    seen.add(e.get("span-id"))
                    self._remote.append(e)

    def trace_payload(self) -> Dict[str, Any]:
        """The exported trace for this request: its own identity and
        spans plus every absorbed child payload."""
        with self._lock:
            remote = list(self._remote)
        return {"request-id": self.id, "trace-id": self.trace_id,
                "span-id": self.span_id,
                "parent-span-id": self.parent_span_id,
                "anchor-unix-s": self.anchor_unix_s, "pid": os.getpid(),
                "spans": list(self.spans), "remote": remote}

    def remaining_s(self) -> Optional[float]:
        if self.deadline is None:
            return None
        return self.deadline - mono_now()

    def expired(self) -> bool:
        return self.deadline is not None and mono_now() > self.deadline

    # -- completion -------------------------------------------------------
    def cell_done(self) -> bool:
        """Called (under the service lock) as each cell resolves; True when
        this was the last one."""
        return all(c.result is not None for c in self.cells)

    def claim_finish(self) -> bool:
        """Atomically claim the right to aggregate and :meth:`finish` this
        request: True exactly once, when the last cell's result landed.
        The scheduler's single device loop never races itself here, but
        the fleet finalizes cells from many driver threads — without the
        claim, two final cells landing together would double-finish."""
        with self._lock:
            if self._finishing or not self.cell_done():
                return False
            self._finishing = True
            return True

    def finish(self, result: Dict[str, Any]) -> None:
        self.span("verdict")
        # a delivered result may already carry a serve payload (the
        # worker-side request's, arriving over the wire) — absorb it
        # into this request's tree before stamping our own
        self.absorb_serve(result)
        result.setdefault("serve", {})
        result["serve"].update({"cells": len(self.cells),
                                **self.trace_payload()})
        self.result = result
        self._done.set()
        # release side-effects (tenant quota slot) fire on *every* finish
        # path — normal aggregation and expiry-while-blocked alike — so an
        # admitted request can never leak its slot
        cb, self.on_finish = self.on_finish, None
        if cb is not None:
            try:
                cb()
            except Exception:  # noqa: BLE001 — release must not mask result
                pass

    def wait(self, timeout: Optional[float] = None) -> Dict[str, Any]:
        if not self._done.wait(timeout):
            raise TimeoutError(f"request {self.id} still in flight")
        assert self.result is not None
        return self.result

    def done(self) -> bool:
        return self._done.is_set()


@dataclass
class Cell:
    """One independently-checkable sub-history of a request."""

    request: Request
    history: History
    key: Any = None                 # None = the request was a single cell
    seq: int = 0                    # global admission order (FIFO tiebreak)
    bucket: Tuple = ()              # (kind, engine-identity, shape buckets)
    result: Optional[Dict[str, Any]] = field(default=None)
    enqueued: float = 0.0           # mono_now() at admission (aging clock)
    cid: str = ""                   # fleet cell id (journal key, route token)
    #: distributed-fission membership (serve.fission_plane): the group id,
    #: split mode, and index of this sub-problem; None for ordinary cells
    fission: Optional[Dict[str, Any]] = field(default=None)
    #: set by the fission plane when a sibling already decided the group —
    #: the drive loop stops re-dispatching; the worker is never interrupted
    cancelled: bool = False
    #: per-cell engine-spec overrides merged over submit_kwargs at dispatch
    #: (ghost-variant children pin fission off + a threshold-sized ceiling)
    spec_overrides: Dict[str, Any] = field(default_factory=dict)

    def sort_key(self) -> Tuple[int, float, int]:
        """Priority-class first (higher request priority sorts earlier),
        deadline within a class, FIFO within a deadline.  The
        scheduler's aged tier still outranks all of this, so a
        low-priority tenant is delayed, never starved."""
        d = self.request.deadline
        return (-self.request.priority,
                d if d is not None else float("inf"), self.seq)

    def route_token(self) -> str:
        """What the fleet router hashes: the key for per-key cells (same
        key → same worker → warm engine cache), the cell id otherwise (a
        keyless request still spreads across the fleet)."""
        if self.key is not None:
            return f"{self.request.kind}:{self.key!r}"
        return f"cell:{self.cid or self.seq}"
