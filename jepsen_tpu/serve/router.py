"""Routing policy for the serving fleet: who checks which cell.

Three small, separately-testable pieces:

- :class:`CircuitBreaker` — the classic closed → open → half-open state
  machine, per worker.  A worker that keeps failing stops receiving
  traffic (open) for a cooldown, then gets exactly one probe cell
  (half-open); the probe's outcome closes or re-opens the circuit.
  Without this, a poisoned worker converts every routed cell into a
  retry — the fleet survives, but pays 2x latency on a third of its
  traffic forever.

- :class:`WorkerHealth` — per-worker EWMAs of dispatch latency and error
  rate plus the last heartbeat, exported through ``GET /healthz`` so an
  external load balancer and the chaos harness read the same numbers the
  router acts on.

- :class:`Router` — rendezvous (highest-random-weight) hashing of cells
  onto workers.  Same key → same worker while the fleet is healthy (warm
  engine caches see repeat shapes); when a worker is dead or its circuit
  is open, each of its keys falls to its *own* next-highest sibling — the
  failover shuffles nothing else, unlike mod-N hashing where one death
  remaps almost every key.  P-compositionality is what makes this safe
  at all: cells are independently-checkable units, so relocating one
  changes no verdict.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Dict, Iterable, List, Optional, Sequence

from jepsen_tpu.serve.metrics import mono_now

#: circuit states (the healthz wire strings)
CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"


class CircuitBreaker:
    """Per-worker circuit: ``fail_threshold`` consecutive failures open
    it; after ``open_s`` one probe is allowed (half-open); the probe's
    success closes it, failure re-opens it for another cooldown."""

    def __init__(self, fail_threshold: int = 3, open_s: float = 1.0,
                 clock=mono_now):
        self.fail_threshold = max(1, fail_threshold)
        self.open_s = open_s
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive = 0
        self._opened_at: Optional[float] = None
        self._probing = False
        self.transitions: Dict[str, int] = {"opened": 0, "half-opened": 0,
                                            "closed": 0}

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        """May a cell be routed here right now?  Claims the half-open
        probe slot when it grants one (call only when actually routing)."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if (self._opened_at is not None
                        and self._clock() - self._opened_at >= self.open_s):
                    self._state = HALF_OPEN
                    self.transitions["half-opened"] += 1
                    self._probing = True
                    return True
                return False
            # HALF_OPEN: one outstanding probe at a time
            if self._probing:
                return False
            self._probing = True
            return True

    def record_success(self) -> None:
        with self._lock:
            self._consecutive = 0
            self._probing = False
            if self._state != CLOSED:
                self._state = CLOSED
                self.transitions["closed"] += 1

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive += 1
            probe_failed = self._probing
            self._probing = False
            if probe_failed or self._consecutive >= self.fail_threshold:
                if self._state != OPEN:
                    self.transitions["opened"] += 1
                self._state = OPEN
                self._opened_at = self._clock()

    def reset(self) -> None:
        """A restarted worker starts with a clean circuit."""
        with self._lock:
            self._state = CLOSED
            self._consecutive = 0
            self._opened_at = None
            self._probing = False


class WorkerHealth:
    """EWMAs of latency and error rate + the heartbeat clock, per worker.
    ``alpha`` weights the newest observation (0.3: ~10 observations of
    memory — fast enough to see a worker go bad mid-campaign, slow
    enough that one outlier doesn't flap the numbers)."""

    def __init__(self, alpha: float = 0.3):
        self.alpha = alpha
        self._lock = threading.Lock()
        self._latency_s: Optional[float] = None
        self._error_rate = 0.0
        self._last_beat: Optional[float] = None
        self._beats = 0

    def observe(self, latency_s: Optional[float] = None,
                error: bool = False) -> None:
        with self._lock:
            a = self.alpha
            if latency_s is not None:
                self._latency_s = (latency_s if self._latency_s is None
                                   else a * latency_s
                                   + (1 - a) * self._latency_s)
            self._error_rate = (a * (1.0 if error else 0.0)
                                + (1 - a) * self._error_rate)

    def beat(self) -> None:
        with self._lock:
            self._last_beat = mono_now()
            self._beats += 1

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            age = (round(mono_now() - self._last_beat, 3)
                   if self._last_beat is not None else None)
            return {"latency-ewma-s": (round(self._latency_s, 6)
                                       if self._latency_s is not None
                                       else None),
                    "error-ewma": round(self._error_rate, 4),
                    "heartbeats": self._beats,
                    "last-beat-age-s": age}


def rendezvous_score(token: str, worker_id: str) -> int:
    """Deterministic per-(cell, worker) weight.  blake2b, not ``hash()``:
    Python string hashing is salted per process, and the whole point is
    that every fleet member — and a restarted fleet replaying its
    journal — ranks workers identically."""
    h = hashlib.blake2b(f"{token}|{worker_id}".encode(), digest_size=8)
    return int.from_bytes(h.digest(), "big")


class Router:
    """Rendezvous-hash a routing token onto the healthiest eligible
    worker.  ``workers`` is the fleet's (index-stable) worker list; a
    worker is eligible when it is alive, not excluded, and its circuit
    admits traffic."""

    def __init__(self, workers: Sequence):
        self._workers = workers

    def ranked(self, token: str, exclude: Iterable[int] = (),
               cell=None) -> List:
        """Alive, non-excluded workers, best rendezvous score first
        (circuit state NOT yet consulted — allow() claims probe slots,
        so it runs only on the worker actually picked).

        With ``cell``, the walk is additionally **mesh-aware**: workers
        whose advertised placement (``FleetWorker.fits``) cannot take
        the cell's lane demand are filtered out — a 512-lane elle group
        ranks only the 4×2-mesh workers.  Placement is an optimization,
        never an availability loss: when NO eligible worker fits, the
        unfiltered ranking is used (a too-big cell on a small worker
        degrades to the service's own saturation/unknown handling
        rather than being unroutable).

        Workers marked ``draining`` (a scale-down in progress —
        serve/autoscale.py) take no new cells: they finish what they
        have while the rest of the fleet absorbs their share.

        Hydra sub-problems (cells carrying ``fission`` scatter
        metadata) get **placement spread**: all siblings of one split
        rank against the *group* token (so the whole swarm agrees on
        one deterministic worker ring) and each sibling starts the walk
        at its own ``index`` rotation into that ring.  k <= N
        sub-problems land on k distinct workers instead of convoying on
        the group winner; k > N wraps the ring — the natural rendezvous
        behaviour.  Failover order is preserved: a sibling whose head
        worker trips its circuit walks the same ring everyone agrees
        on, just from a different start."""
        ex = set(exclude)
        alive = [w for w in self._workers
                 if w.wid not in ex and w.alive()
                 and not getattr(w, "draining", False)]
        if cell is not None:
            fitting = [w for w in alive if w.fits(cell)]
            if fitting:
                alive = fitting
        fiss = getattr(cell, "fission", None) if cell is not None else None
        if isinstance(fiss, dict) and fiss.get("group") is not None \
                and fiss.get("index") is not None and len(alive) > 1:
            token = f"fission:{fiss['group']}"
        scored = [(rendezvous_score(token, str(w.wid)), w) for w in alive]
        scored.sort(key=lambda sw: sw[0], reverse=True)
        ring = [w for _, w in scored]
        if isinstance(fiss, dict) and fiss.get("group") is not None \
                and fiss.get("index") is not None and len(ring) > 1:
            rot = int(fiss["index"]) % len(ring)
            ring = ring[rot:] + ring[:rot]
        return ring

    def pick(self, token: str, exclude: Iterable[int] = (), cell=None):
        """The worker to route ``token`` to, or None when no alive worker
        currently admits traffic.  Walks the rendezvous ranking so an
        open circuit fails over to the key's next-highest sibling."""
        for w in self.ranked(token, exclude, cell=cell):
            if w.breaker.allow():
                return w
        return None
