"""Service observability: counters, gauges, occupancy, and trace export.

One thread-safe registry per service.  Everything lands in one
``snapshot()`` dict — the payload of web.py's ``/metrics`` endpoint and
the body of the queue-status page — so there is exactly one schema to
document (docs/serving.md) and assert on in the smoke test:

- counters: requests/cells through each lifecycle edge, deadline
  expiries, admission rejections, dispatches, host fallbacks;
- gauges: queue depth and in-flight requests, sampled live;
- occupancy: used vs padded lanes per dispatch, summed — the price of
  shape bucketing, as a ratio;
- engine-cache: hit/miss/eviction counters of the bounded compiled-
  engine LRU (parallel.batch) — a miss is a recompile, a group_reuse is
  the same executable serving another dispatch group of one batch;
- megabatch: the throughput path's staging/refill/readback counters
  (parallel.megabatch) — dispatches vs summary ints proves the O(1)
  per-dispatch readback, refills/lanes_refilled measure continuous
  lane occupancy;
- traces: the last few completed requests' span lists (enqueue -> pack
  -> dispatch -> verdict, relative seconds).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Dict, List, Optional

# Canonical home is jepsen_tpu.clock (the checker/control layers need it
# without importing serve); re-exported here because every serve/ and
# monitor/ module already imports it from metrics.
from jepsen_tpu.clock import mono_now  # noqa: F401


class Metrics:
    def __init__(self, trace_capacity: int = 64):
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {
            "requests-submitted": 0, "requests-completed": 0,
            "requests-rejected": 0, "cells-submitted": 0,
            "cells-completed": 0, "deadline-expired": 0,
            "dispatches": 0, "host-fallbacks": 0,
        }
        self._lanes_used = 0
        self._lanes_padded = 0
        self._dispatch_s = 0.0
        self._traces: deque = deque(maxlen=trace_capacity)
        self._depth_fn = None       # live queue-depth callback
        self._inflight_fn = None

    def bind(self, depth_fn, inflight_fn) -> None:
        self._depth_fn = depth_fn
        self._inflight_fn = inflight_fn

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def dispatch(self, lanes_used: int, lanes_padded: int,
                 seconds: float) -> None:
        with self._lock:
            self._counters["dispatches"] += 1
            self._lanes_used += lanes_used
            self._lanes_padded += lanes_padded
            self._dispatch_s += seconds

    def trace(self, request) -> None:
        with self._lock:
            self._traces.append({"request-id": request.id,
                                 "kind": request.kind,
                                 "valid": (request.result or {}).get("valid"),
                                 "spans": list(request.spans)})

    def snapshot(self) -> Dict[str, Any]:
        from jepsen_tpu.parallel.batch import engine_cache_stats
        from jepsen_tpu.parallel.megabatch import megabatch_stats
        with self._lock:
            counters = dict(self._counters)
            used, padded = self._lanes_used, self._lanes_padded
            dispatch_s = self._dispatch_s
            traces = list(self._traces)
        cache = engine_cache_stats()
        return {
            "counters": counters,
            "gauges": {
                "queue-depth": self._depth_fn() if self._depth_fn else 0,
                "inflight-requests":
                    self._inflight_fn() if self._inflight_fn else 0,
            },
            "occupancy": {
                "lanes-used": used,
                "lanes-padded": padded,
                "ratio": round(used / padded, 4) if padded else None,
                "dispatch-seconds": round(dispatch_s, 6),
            },
            "engine-cache": {**cache, "recompiles": cache["misses"]},
            "megabatch": megabatch_stats(),
            "traces": traces,
        }
