"""Service observability: counters, gauges, histograms, and trace export.

One thread-safe registry per service.  Everything lands in one
``snapshot()`` dict — the payload of web.py's ``/metrics`` endpoint and
the body of the queue-status page — so there is exactly one schema to
document (docs/observability.md) and assert on in the tests:

- counters: requests/cells through each lifecycle edge, deadline
  expiries, admission rejections, dispatches, host fallbacks;
- gauges: queue depth and in-flight requests, sampled live, plus the
  steady-state ``compiles-per-1k-dispatches`` ratio (process-wide
  compile events over barrier + megabatch dispatches — 0.0 once the
  shape ladder is warm);
- queue: per-bucket queue depth and oldest head wait-age (the
  autoscaler's occupancy signal, bound via ``bind_queue``);
- tenants: per-tenant lifecycle counters, verdict-edge p99, and the
  tenant table's quota/priority/accounting cut (``bind_tenants``) —
  names and numbers only, never token material;
- occupancy: used vs padded lanes per dispatch, summed — the price of
  shape bucketing, as a ratio;
- histograms: log-bucketed (pow2 ladder, jepsen_tpu.obs.hist) latency
  quantiles per lifecycle edge (``edge:enqueue->dispatch``,
  ``edge:dispatch->verdict``, adjacent pairs, ``dispatch``) merged with
  the process-wide compile-time histograms (``compile:<cache tag>...``);
- engine-cache: hit/miss/eviction counters of the shared bounded
  compiled-engine LRU (jepsen_tpu.engine.cache) — one cache for the
  "singlev"/"batchv"/"megav" key families, with a per-tag entry count
  so all three show up in ``/metrics``;
- megabatch: the throughput path's staging/refill/readback counters
  (parallel.megabatch);
- fission: the frontier-splitting counters (splits, component/ghost
  sub-problems, recombines, escalations — engine.fission) plus its
  sub-problem wall-clock histograms;
- flight-recorder: the process ring's enabled/recorded/buffered stats;
- traces: the last few completed requests' merged trace payloads
  (trace/span ids, wall anchor, spans, absorbed remote payloads).

Snapshot consistency: counters, occupancy, histograms, and traces are
each captured under their own lock, but the ``gauges`` section samples
the live ``_depth_fn``/``_inflight_fn`` callbacks *after* the counter
capture and *outside* this lock — deliberately.  The callbacks walk
scheduler/fleet state behind locks far earlier in the declared lock
order (lint/lock_order.py puts ``metrics`` at the leaf of the serve
chain), so sampling them under the metrics lock would be an inversion.
The cost is a documented tear: a snapshot's gauges can reflect a
slightly later instant than its counters (e.g. ``inflight-requests``
may exceed ``submitted - completed`` computed from the same snapshot).
Dashboards must treat gauges as point samples, not as derivable from
the counters; tests/test_serve.py pins this contract.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Dict, List, Optional

# Canonical home is jepsen_tpu.clock (the checker/control layers need it
# without importing serve); re-exported here because every serve/ and
# monitor/ module already imports it from metrics.
from jepsen_tpu.clock import mono_now  # noqa: F401
from jepsen_tpu.obs.hist import (HistogramSet, compile_event_count,
                                 compile_hist_stats, merge_skipped_count,
                                 monitor_epoch_hist_stats)


class Metrics:
    def __init__(self, trace_capacity: int = 64):
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {
            "requests-submitted": 0, "requests-completed": 0,
            "requests-rejected": 0, "cells-submitted": 0,
            "cells-completed": 0, "deadline-expired": 0,
            "dispatches": 0, "host-fallbacks": 0,
        }
        self._lanes_used = 0
        self._lanes_padded = 0
        self._dispatch_s = 0.0
        self._traces: deque = deque(maxlen=trace_capacity)
        self._depth_fn = None       # live queue-depth callback
        self._inflight_fn = None
        self._queue_fn = None       # live per-bucket occupancy callback
        self._tenants_fn = None     # live tenant-table counts callback
        self._tenants: Dict[str, Dict[str, int]] = {}  # per-tenant counters
        self.hists = HistogramSet()  # own lock; observed outside ours

    # The gauge bindings below are the sanctioned torn sites of this
    # module (docs/static_analysis.md "Sanctioned unsynchronized
    # sites"): each is written exactly once, during service
    # construction, and read lock-free by snapshot() so a slow gauge
    # callback can never stall the metrics lock.  A racing reader sees
    # either None (gauge omitted from that snapshot) or the bound
    # callable — both are within the tear contract documented in
    # docs/observability.md.

    def bind(self, depth_fn, inflight_fn) -> None:
        # lint: disable=RACE01(bound once at service construction, a racing snapshot tolerates None: documented gauge-tear contract)
        self._depth_fn = depth_fn
        # lint: disable=RACE01(bound once at service construction, a racing snapshot tolerates None: documented gauge-tear contract)
        self._inflight_fn = inflight_fn

    def bind_queue(self, queue_fn) -> None:
        """Wire the scheduler/fleet occupancy callback: per-bucket depth
        + oldest-wait-age, sampled live like the other gauges (outside
        the metrics lock — same tear contract)."""
        # lint: disable=RACE01(bound once at service construction, a racing snapshot tolerates None: documented gauge-tear contract)
        self._queue_fn = queue_fn

    def bind_tenants(self, tenants_fn) -> None:
        """Wire the tenant table's counts() callback (serve/tenants.py):
        quota/priority policy + open/admitted/rejected accounting,
        merged into the snapshot's per-tenant cut."""
        # lint: disable=RACE01(bound once at service construction, a racing snapshot tolerates None: documented gauge-tear contract)
        self._tenants_fn = tenants_fn

    def tenant_inc(self, tenant: Optional[str], name: str,
                   n: int = 1) -> None:
        if tenant is None:
            return
        with self._lock:
            t = self._tenants.setdefault(tenant, {})
            t[name] = t.get(name, 0) + n

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def counter(self, name: str, default: int = 0) -> int:
        """Locked point-read of one counter.  Gauge callbacks use this
        instead of reaching into ``_counters``: the metrics lock is
        never held while gauges are sampled (snapshot() samples outside
        it), so the read cannot deadlock against an export."""
        with self._lock:
            return self._counters.get(name, default)

    def dispatch(self, lanes_used: int, lanes_padded: int,
                 seconds: float) -> None:
        with self._lock:
            self._counters["dispatches"] += 1
            self._lanes_used += lanes_used
            self._lanes_padded += lanes_padded
            self._dispatch_s += seconds
        self.hists.observe("dispatch", seconds)

    def trace(self, request) -> None:
        payload = request.trace_payload()
        payload["kind"] = request.kind
        payload["valid"] = (request.result or {}).get("valid")
        tenant = getattr(request, "tenant", None)
        expired = bool((request.result or {}).get("deadline-expired"))
        with self._lock:
            self._traces.append(payload)
            # unknown verdicts are the checker punting (frontier blowup,
            # deadline, fission escalation) — counted here so the SLO
            # engine can burn on unknown-rate = Δunknown/Δcompleted
            if payload["valid"] == "unknown":
                self._counters["verdicts-unknown"] = \
                    self._counters.get("verdicts-unknown", 0) + 1
            if tenant is not None:
                t = self._tenants.setdefault(tenant, {})
                t["requests-completed"] = t.get("requests-completed", 0) + 1
                if payload["valid"] == "unknown":
                    t["verdicts-unknown"] = t.get("verdicts-unknown", 0) + 1
                if expired:
                    t["deadline-expired"] = t.get("deadline-expired", 0) + 1
        self._observe_edges(request.spans, tenant=tenant)

    def _observe_edges(self, spans: List[Dict[str, Any]],
                       tenant: Optional[str] = None) -> None:
        """Latency histograms per lifecycle edge: each adjacent span
        pair, plus the two headline edges (queueing+packing delay and
        device-to-verdict time).  Tenant-attributed requests additionally
        observe the headline verdict edge under a per-tenant histogram —
        the source of the tenant p99 cut and the tenant SLO burn."""
        times: Dict[str, float] = {}
        prev = None
        for s in spans:
            name, t = s.get("span"), s.get("t")
            if name is None or t is None:
                continue
            times.setdefault(name, t)   # first occurrence wins
            if prev is not None and t >= prev[1]:
                self.hists.observe(f"edge:{prev[0]}->{name}", t - prev[1])
            prev = (name, t)
        for a, b in (("enqueue", "dispatch"), ("dispatch", "verdict")):
            if a in times and b in times and times[b] >= times[a]:
                self.hists.observe(f"edge:{a}->{b}", times[b] - times[a])
                if tenant is not None and (a, b) == ("dispatch", "verdict"):
                    self.hists.observe(
                        f"tenant:{tenant}:edge:dispatch->verdict",
                        times[b] - times[a])

    def find_trace(self, request_id) -> Optional[Dict[str, Any]]:
        """The merged trace payload for a completed request still in the
        ring, or None (evicted / never seen)."""
        rid = str(request_id)
        with self._lock:
            for t in reversed(self._traces):
                if str(t.get("request-id")) == rid:
                    return dict(t)
        return None

    @staticmethod
    def _fission_section(fission) -> Dict[str, Any]:
        """One merged view of the whole fission story: the engine
        splitter counters, the shrink-recursion counters, the fleet
        plane's scattered/remote-subproblems/cancelled counters, and
        every tier's histograms (keys are disjoint by construction:
        engine ``fission:*``, shrink ``fission:shrink-*``, plane
        ``fleetfission:*``).  Lazy imports keep the metrics leaf free of
        serve-layer import cycles."""
        from jepsen_tpu.engine import shrink
        from jepsen_tpu.serve import fission_plane
        return {**fission.fission_stats(),
                **shrink.shrink_stats(),
                **fission_plane.plane_stats(),
                "histograms": {**fission.HISTS.snapshot(),
                               **shrink.HISTS.snapshot(),
                               **fission_plane.HISTS.snapshot()}}

    def snapshot(self) -> Dict[str, Any]:
        from jepsen_tpu.engine.cache import engine_cache_stats
        from jepsen_tpu.engine import fission
        from jepsen_tpu.obs.recorder import RECORDER
        from jepsen_tpu.obs.telemetry import process_gauges
        from jepsen_tpu.parallel.megabatch import megabatch_stats
        with self._lock:
            counters = dict(self._counters)
            used, padded = self._lanes_used, self._lanes_padded
            dispatch_s = self._dispatch_s
            traces = list(self._traces)
            tenant_counters = {t: dict(c) for t, c in self._tenants.items()}
        cache = engine_cache_stats()
        mega = megabatch_stats()
        # process-wide merge-corruption counter: how many malformed
        # per-histogram entries the fleet scrape path silently dropped
        counters["hist-merge-skipped"] = merge_skipped_count()
        # Steady-state compile pressure: compile events per 1000 engine
        # dispatches (scheduler barrier dispatches + megabatch chunk
        # dispatches), process-wide like the compile histograms that
        # feed it.  A warm ladder serves at 0.0; anything persistently
        # above it means a shape is leaking past the buckets.  None
        # until the first dispatch.
        disp = counters.get("dispatches", 0) + mega.get("dispatches", 0)
        compiles_1k = round(1000.0 * compile_event_count() / disp, 3) \
            if disp else None
        # gauges sample live state here — after counter capture, outside
        # our lock (the callbacks take scheduler/fleet locks that must
        # not nest inside the metrics leaf); see the module docstring
        # for the resulting tear contract
        queue = self._queue_fn() if self._queue_fn else \
            {"depth": 0, "buckets": {}, "oldest-wait-s": 0.0}
        hists = {**self.hists.snapshot(), **compile_hist_stats(),
                 **monitor_epoch_hist_stats()}
        pg = process_gauges()
        # worst per-stream monitor lag, in epochs (Monitor.flush sets one
        # `monitor-lag-epochs:<stream>` gauge per streaming monitor; the
        # scalar the SLO burns on is the max across streams)
        lag_epochs = max([int(v) for k, v in pg.items()
                          if k.startswith("monitor-lag-epochs:")] or [0])
        # per-tenant cut: lifecycle counters + the tenant verdict-edge
        # p99 + the tenant table's policy/accounting (quota, priority,
        # open, quota-rejections).  Names and numbers only — never token
        # material (SEC01's export-sink discipline).
        table = self._tenants_fn() if self._tenants_fn else {}
        tenants: Dict[str, Dict[str, Any]] = {}
        for name in sorted(set(tenant_counters) | set(table)):
            cut: Dict[str, Any] = dict(tenant_counters.get(name, {}))
            h = hists.get(f"tenant:{name}:edge:dispatch->verdict")
            cut["p99-dispatch-verdict-us"] = \
                round(h["p99"] * 1e6, 3) if h else None
            cut.update(table.get(name, {}))
            tenants[name] = cut
        return {
            "counters": counters,
            "gauges": {
                "queue-depth": self._depth_fn() if self._depth_fn else 0,
                "inflight-requests":
                    self._inflight_fn() if self._inflight_fn else 0,
                "compiles-per-1k-dispatches": compiles_1k,
                # monitor lag: ops the streaming checkers have accepted
                # but not yet folded into a verdict epoch (0 when no
                # monitor runs in this process) — set by Monitor.flush
                # through obs.telemetry.set_gauge
                "epochs-behind-live":
                    int(pg.get("epochs-behind-live", 0)),
                "monitor-lag-epochs": lag_epochs,
                # the autoscaler's wait-age input signal, sampled with
                # the other gauges (same tear contract)
                "queue-oldest-wait-s": queue.get("oldest-wait-s", 0.0),
            },
            "queue": queue,
            "tenants": tenants,
            "occupancy": {
                "lanes-used": used,
                "lanes-padded": padded,
                "ratio": round(used / padded, 4) if padded else None,
                "dispatch-seconds": round(dispatch_s, 6),
            },
            "histograms": hists,
            "engine-cache": {**cache, "recompiles": cache["misses"]},
            "megabatch": mega,
            "fission": self._fission_section(fission),
            "flight-recorder": RECORDER.stats(),
            "traces": traces,
        }
