"""Fleet: a fault-tolerant multi-worker serving tier.

One :class:`CheckService` is still one scheduler thread on one device: a
wedged dispatch, a crashed loop, or one slow lane group takes the whole
service down.  The fleet runs N worker CheckServices (in-process
replicas today, one per host tomorrow — the submit surface is already
process-shaped), each pinned to its own slice of the host's devices,
behind a router that:

- hash-routes cells by key (rendezvous hashing, serve/router.py) so a
  key's repeat shapes keep hitting the same warm engine cache;
- health-checks workers (heartbeat thread + per-worker latency/error
  EWMAs) and circuit-breaks a failing one (open → half-open probe →
  close);
- retries and hedges deadline-risky cells onto siblings under a
  control/retry.py :class:`RetryPolicy` with decorrelated jitter (a
  worker death must not synchronize the survivors' retries into a
  storm);
- journals in-flight cells (atomic_io) so a crash — of a worker or of
  the whole fleet process — re-enqueues, never drops and never
  fabricates, its pending work.

Verdict discipline is the repo's: on every unrecoverable path the cell
degrades to ``valid: "unknown"``; a fleet failure can never produce a
``false`` the single-service oracle would not.  P-compositionality
(arXiv:1504.00204) is what makes all of this sound: cells are
independently-checkable units whose merge is associative, so a cell may
be retried, relocated, or hedged without changing any verdict.

The self-nemesis proof lives in serve/chaos.py +
scripts/fleet_chaos_smoke.py.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as FutureTimeout
from typing import Any, Callable, Dict, List, Optional, Tuple

from jepsen_tpu import atomic_io
from jepsen_tpu.control.retry import RetryPolicy
from jepsen_tpu.net_proxy import PairProxy
from jepsen_tpu.history import History, Op
from jepsen_tpu.obs.hist import merge_hist_snapshots
from jepsen_tpu.obs.recorder import RECORDER
from jepsen_tpu.obs.slo import SloEngine, tenant_slo_specs
from jepsen_tpu.obs.telemetry import TelemetryStore, telemetry_interval_s
from jepsen_tpu.serve import buckets, fission_plane
from jepsen_tpu.serve.aggregate import aggregate, expired_result
from jepsen_tpu.serve.decompose import decompose
from jepsen_tpu.serve.metrics import Metrics, mono_now
from jepsen_tpu.serve.request import Cell, KIND_WGL, Request
from jepsen_tpu.serve.router import (
    CircuitBreaker, OPEN, Router, WorkerHealth,
)
from jepsen_tpu.serve.service import (
    CheckService, ServiceClosed, ServiceSaturated, build_spec,
    submit_kwargs,
)
from jepsen_tpu.serve.tenants import TenantTable

log = logging.getLogger("jepsen.serve.fleet")

#: completion-poll quantum while waiting on a worker-side request
POLL_S = 0.005
#: hedge trigger when a request carries no deadline
DEFAULT_HEDGE_S = 1.0
#: give-up bound for a no-deadline cell stuck on an unresponsive worker
NO_DEADLINE_WAIT_S = 120.0
#: default per-request budget — the fleet always runs with deadlines
#: unless the caller explicitly disables them (deadlines are what make
#: drop/delay faults recoverable instead of hangs)
DEFAULT_FLEET_DEADLINE_S = 60.0

#: worker-produced error strings that mean "the worker failed", not "the
#: history is undecidable" — these reroute to a sibling; every other
#: unknown is a legitimate verdict and is passed through.  Deliberately
#: narrow: retrying a budget-truncation unknown would loop forever.
_WORKER_FAILURE_ERRORS = (
    "scheduler dispatch crashed",
    "device and host tiers both failed",
    # transport.py's wire-failure verdicts: a lost/torn connection is a
    # worker(-link) failure by definition — the history never reached a
    # checker, so rerouting to a sibling is always sound
    "transport connection lost",
    "transport frame error",
)


def _device_sets(n: int) -> List[list]:
    """Partition the host's accelerators round-robin across N workers.
    Fewer devices than workers shares them (CPU CI: every worker pins
    the one CPU device); no jax at all degrades to unpinned."""
    try:
        import jax
        devs = list(jax.devices())
    except Exception:  # noqa: BLE001 — fleet works without a backend
        devs = []
    if not devs:
        return [[] for _ in range(n)]
    if len(devs) >= n:
        return [devs[i::n] for i in range(n)]
    return [[devs[i % len(devs)]] for i in range(n)]


class FleetWorker:
    """One worker slot: a CheckService plus its circuit, health, and
    device pin.  The slot survives its service — ``restart`` replaces
    the dead service in place, so the router's worker list stays
    index-stable across crashes."""

    def __init__(self, wid: int, make_service: Callable[[], CheckService],
                 devices: Optional[list] = None,
                 fail_threshold: int = 3, open_s: float = 1.0):
        self.wid = wid
        self.devices = devices or []
        self._make_service = make_service
        self.service = make_service()
        self.breaker = CircuitBreaker(fail_threshold=fail_threshold,
                                      open_s=open_s)
        self.health = WorkerHealth()
        self.generation = 0
        # scale-down lifecycle (serve/autoscale.py): a draining slot
        # takes no new cells (router filters it); a retired slot is dead
        # for good — the supervisor must not respawn it
        self.draining = False
        self.retired = False
        self._restart_lock = threading.Lock()

    def alive(self) -> bool:
        return self.service.alive()

    def fits(self, cell) -> bool:
        """Mesh/capability placement predicate for the router's ranked
        walk.  The base slot accepts every cell — today's fixed fleets
        are homogeneous; registry-backed slots (serve/fleetport.py)
        override this with the worker's advertised mesh capacity."""
        return True

    def kill(self) -> list:
        """Crash this worker (chaos fault / decommission): abrupt service
        kill, queued worker-side cells evicted.  The fleet's cell owners
        detect the death and reroute — nothing here touches fleet state."""
        return self.service.kill()

    def restart(self, only_if_dead: bool = False) -> bool:
        """Replace a dead service with a fresh one and reset the circuit
        (a restarted worker earns its traffic back through the normal
        closed-state accounting).  ``only_if_dead`` is the supervisor's
        guard — a chaos undo and the ProcFleet supervisor may both reach
        for the same corpse, and the restart lock plus the liveness
        re-check under it make exactly one of them actually respawn.
        Returns True iff THIS call replaced the service."""
        with self._restart_lock:
            if only_if_dead and self.alive():
                return False
            try:
                self.service.kill()
            except Exception:  # noqa: BLE001 — it's already dead
                pass
            self.service = self._make_service()
            self.generation += 1
            self.breaker.reset()
            return True

    def status(self) -> Dict[str, Any]:
        try:
            ping = self.service.ping()
        except Exception:  # noqa: BLE001
            ping = {"alive": False, "queue-depth": None,
                    "inflight-cells": None}
        return {"worker": self.wid,
                "alive": bool(ping.get("alive")),
                "circuit": self.breaker.state,
                "queue-depth": ping.get("queue-depth"),
                "inflight-cells": ping.get("inflight-cells"),
                "generation": self.generation,
                "draining": self.draining,
                "retired": self.retired,
                "devices": [str(d) for d in self.devices],
                **self.health.snapshot()}


class FleetJournal:
    """The in-flight cell journal: an atomically-replaced JSON snapshot
    of every cell the fleet has admitted but not finished, durable
    through the atomic_io rename + directory-fsync discipline.  A fleet
    (or host) crash re-enqueues this file's cells on restart —
    :meth:`recover` — so admitted work is never silently dropped; a cell
    whose deadline budget is already spent is returned under
    ``expired``, explicitly, rather than re-checked against a deadline
    it can no longer meet.

    Format (``fleet-journal.json``)::

        {"version": 1,
         "pending": {"<cid>": {"request-id": int, "kind": "wgl"|"elle",
                               "key": ..., "deadline-rem-s": float|null,
                               "spec": {...build_spec kwargs, model by
                                        name...},
                               "ops": [history.jsonl op dicts]}}}
    """

    VERSION = 1
    FILENAME = "fleet-journal.json"
    #: the recovery-claim lock file (exclusive_create; single winner)
    CLAIMNAME = "fleet-journal.claim"

    def __init__(self, journal_dir: str):
        self.dir = atomic_io.durable_mkdir(journal_dir)
        self.path = os.path.join(self.dir, self.FILENAME)
        self._jlock = threading.Lock()    # pending-map mutations
        self._wlock = threading.Lock()    # one disk writer at a time
        self._pending: Dict[str, Dict[str, Any]] = {}
        self.writes = 0

    @staticmethod
    def _spec_lite(req: Request) -> Dict[str, Any]:
        spec = dict(req.spec)
        if req.kind == KIND_WGL:
            spec["model"] = spec["model"].name
        return spec

    def record(self, req: Request, cells: List[Cell]) -> None:
        entries = {}
        for c in cells:
            # fission children journal their per-cell spec overrides so
            # whole-fleet-crash recovery re-checks each sub-problem under
            # the exact engine options it scattered with (the group
            # context is gone — recovered children run as independent
            # requests, which the unknown-never-false table tolerates)
            entries[c.cid] = {
                "request-id": req.id, "kind": req.kind, "key": c.key,
                "deadline-rem-s": req.remaining_s(),
                "spec": {**self._spec_lite(req), **c.spec_overrides},
                "ops": [op.to_dict() for op in c.history]}
        with self._jlock:
            self._pending.update(entries)
        self._flush()

    def complete(self, cid: str) -> None:
        with self._jlock:
            self._pending.pop(cid, None)
        self._flush()

    def pending_count(self) -> int:
        with self._jlock:
            return len(self._pending)

    def _flush(self) -> None:
        # Snapshot INSIDE the writer lock: whoever writes, writes the
        # freshest state — a slow earlier writer can't clobber a newer
        # snapshot with a stale one.
        with self._wlock:
            with self._jlock:
                payload = {"version": self.VERSION,
                           "pending": dict(self._pending)}
            atomic_io.atomic_write(
                self.path,
                lambda f: json.dump(payload, f, default=str))
            self.writes += 1

    @classmethod
    def recover(cls, journal_dir: str) -> Dict[str, List[Dict[str, Any]]]:
        """Read a (possibly crashed) fleet's journal back into
        resubmittable work items: ``{"pending": [...], "expired":
        [...]}``, each item ``{"cid", "key", "history", "kwargs"}`` where
        ``kwargs`` feed :meth:`Fleet.submit` directly.  Entries whose
        deadline budget was already spent when journaled land in
        ``expired`` — recovery never invents deadline headroom."""
        path = os.path.join(journal_dir, cls.FILENAME)
        out: Dict[str, List[Dict[str, Any]]] = {"pending": [], "expired": []}
        if not os.path.exists(path):
            return out
        with open(path) as f:
            data = json.load(f)
        for cid, e in sorted(data.get("pending", {}).items()):
            spec = dict(e.get("spec") or {})
            kwargs = {"kind": e.get("kind", KIND_WGL), **spec}
            rem = e.get("deadline-rem-s")
            if rem is not None:
                kwargs["deadline_s"] = max(0.0, rem)
            item = {"cid": cid, "key": e.get("key"),
                    "history": History([Op.from_dict(d)
                                        for d in e.get("ops", [])]),
                    "kwargs": kwargs}
            if rem is not None and rem <= 0:
                out["expired"].append(item)
            else:
                out["pending"].append(item)
        return out

    # -- the recovery claim -----------------------------------------------
    # Two supervisors recovering the SAME journal directory (a respawned
    # fleet racing a slow-to-die predecessor, or an operator's manual
    # recovery racing an automatic one) would each resubmit every pending
    # cell: not a correctness bug (claim_finish dedups the verdict) but a
    # 2x re-check of every pending history.  The claim file — created
    # with O_CREAT|O_EXCL via atomic_io.exclusive_create — makes recovery
    # single-winner: exactly one claimant resubmits, the loser reports
    # who beat it.  A claim whose recorded pid is dead is STALE (the
    # claimant crashed mid-recovery) and may be stolen; the steal itself
    # races through os.replace, where again only one renamer wins.

    @staticmethod
    def _pid_alive(pid: Any) -> bool:
        try:
            pid = int(pid)
        except (TypeError, ValueError):
            return False
        if pid <= 0:
            # os.kill(0/-N, 0) signals whole process GROUPS — never probe
            return False
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            return False
        except OSError:
            return True  # e.g. EPERM: exists, not ours
        return True

    @classmethod
    def _claim_path(cls, journal_dir: str) -> str:
        return os.path.join(journal_dir, cls.CLAIMNAME)

    @classmethod
    def claim_holder(cls, journal_dir: str) -> Optional[Dict[str, Any]]:
        """The current claim record ({"claimant", "pid"}) or None."""
        try:
            with open(cls._claim_path(journal_dir)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    @classmethod
    def claim_recovery(cls, journal_dir: str, claimant: str) -> bool:
        """Try to become THE recoverer of this journal directory.  True
        = we hold the claim (fresh win, our own re-claim, or a stale
        claim stolen); False = a live claimant beat us."""
        path = cls._claim_path(journal_dir)
        record = json.dumps({"claimant": claimant, "pid": os.getpid()})
        if atomic_io.exclusive_create(path, record):
            return True
        holder = cls.claim_holder(journal_dir)
        if holder is not None:
            if (holder.get("claimant") == claimant
                    and holder.get("pid") == os.getpid()):
                return True  # our own claim (idempotent re-claim)
            if cls._pid_alive(holder.get("pid")):
                return False
        # stale (dead pid) or unreadable: steal by renaming it aside —
        # os.replace is atomic, so of N stealers exactly one moves the
        # old claim and the rest lose the fresh exclusive_create below
        try:
            os.replace(path, path + ".stale")
        except FileNotFoundError:
            pass  # someone else already stole it; race them for the file
        except OSError:
            return False
        return atomic_io.exclusive_create(path, record)


class _FleetMetrics(Metrics):
    """The fleet's Metrics registry plus the fleet-wide scrape: a
    ``fleet`` snapshot section (per-worker status/circuits/journal), a
    ``workers`` section holding each worker's own ``Metrics.snapshot()``
    (fetched over the STATUS frame for out-of-process workers,
    best-effort — a partitioned worker scrapes as ``unreachable``, it
    never fails the document), and a ``histograms`` section that merges
    the fleet's own histograms with every reachable worker's, bucket by
    bucket (the pow2 ladders are identical in every process) — web.py's
    ``/metrics`` payload keeps one schema whether a CheckService, a
    Fleet, or a ProcFleet is attached."""

    def __init__(self, fleet: "Fleet"):
        super().__init__()
        self._fleet = fleet

    def snapshot(self) -> Dict[str, Any]:
        snap = super().snapshot()
        snap["fleet"] = self._fleet.fleet_status()
        worker_snaps = self._fleet.worker_snapshots()
        snap["histograms"] = merge_hist_snapshots(
            [snap.get("histograms")]
            + [(w or {}).get("histograms") for w in worker_snaps])
        workers = []
        for i, w in enumerate(worker_snaps):
            if w is None:
                workers.append({"worker": i, "unreachable": True})
                continue
            # traces stay fleet-side (the merged tree already absorbed
            # the worker spans); per-worker entries keep the numbers
            entry = {k: v for k, v in w.items()
                     if k not in ("traces", "fleet", "workers")}
            workers.append({"worker": i, **entry})
        snap["workers"] = workers
        # Watchtower sections (guarded: a snapshot taken while the fleet
        # is still constructing must not crash on the missing store)
        tele = getattr(self._fleet, "telemetry", None)
        if tele is not None:
            snap["telemetry"] = tele.snapshot()
        slo = getattr(self._fleet, "slo", None)
        if slo is not None:
            snap["slo"] = slo.snapshot()
        gov = getattr(self._fleet, "governor", None)
        if gov is not None:
            snap["autoscale"] = gov.snapshot()
        return snap


class Fleet:
    """N worker CheckServices behind a router — the CheckService facade
    (submit/check/try_route_analyze/metrics/close) at fleet scale, so
    ``test["service"]``, the web front end, and the CLI take a Fleet
    anywhere they take a service."""

    def __init__(self, workers: int = 3, *,
                 store_base: Optional[str] = None,
                 journal_dir: Optional[str] = None,
                 max_lanes: int = 64,
                 max_queue_cells: int = 4096,
                 default_deadline_s: Optional[float]
                 = DEFAULT_FLEET_DEADLINE_S,
                 mesh=None,
                 capacity: Optional[int] = None,
                 max_capacity: int = 65536,
                 hedge_s: Optional[float] = None,
                 heartbeat_s: float = 0.25,
                 retry_policy: Optional[RetryPolicy] = None,
                 breaker_fail_threshold: int = 3,
                 breaker_open_s: float = 1.0,
                 pin_devices: bool = True,
                 telemetry_s: Optional[float] = None):
        n = max(1, int(workers))
        self.n_workers = n
        self.max_lanes = max_lanes
        self.max_queue_cells = max_queue_cells
        self.default_deadline_s = default_deadline_s
        self.hedge_s = hedge_s
        self.heartbeat_s = heartbeat_s
        # resolved before _make_workers: proc slots ship the cadence to
        # their worker processes as a --telemetry-s argv flag
        self.telemetry_s = (telemetry_interval_s() if telemetry_s is None
                            else float(telemetry_s))
        self._t0 = mono_now()
        device_sets = _device_sets(n) if pin_devices else [[]] * n
        self.workers: List[FleetWorker] = self._make_workers(
            n, buckets.worker_lane_share(max_lanes, n), device_sets,
            store_base=store_base, mesh=mesh, capacity=capacity,
            max_capacity=max_capacity,
            fail_threshold=breaker_fail_threshold,
            open_s=breaker_open_s)
        self.router = Router(self.workers)
        self.metrics = _FleetMetrics(self)
        # Watchtower: the per-worker push ring + the SLO engine over it.
        # Proc workers push TELEMETRY frames into _note_worker_telemetry;
        # in-process workers (no wire) are scraped into the same store on
        # the heartbeat cadence, and the fleet process contributes its
        # own base snapshot as the "fleet" pseudo-worker.
        # Spawned workers spend real wall time booting before their
        # first push can exist; the fleet's ready timeout doubles as the
        # never-pushed staleness grace (ProcFleet sets it before calling
        # up here; in-process fleets have no boot gap and get none).
        self.telemetry = TelemetryStore(
            interval_s=self.telemetry_s if self.telemetry_s > 0 else None,
            startup_grace_s=getattr(self, "worker_ready_timeout_s", 0.0))
        self.slo = SloEngine(self.telemetry)
        for w in self.workers:
            self.telemetry.register(w.wid)
        self.telemetry.register("fleet")
        self._last_tele_sweep = 0.0
        # Multi-tenant QoS (serve/tenants.py): quotas/priorities from
        # JEPSEN_TPU_TENANT_*; tenants with configured SLO ceilings get
        # their own burn specs over the fleet pseudo-worker's pushes.
        self.tenants = TenantTable.from_env()
        for spec in tenant_slo_specs(self.tenants.slo_config(),
                                     self.telemetry_s):
            self.slo.add_spec(spec)
        # the Governor (serve/autoscale.py) attaches itself here so the
        # metrics snapshot can carry its decision ring
        self.governor = None
        # Decorrelated jitter by default: reroutes after a worker death
        # must not arrive at the survivor in lockstep (retry storm).
        self.retry_policy = retry_policy or RetryPolicy(
            tries=4, backoff_s=0.02, max_backoff_s=0.5, decorrelated=True)
        self._journal = (FleetJournal(journal_dir)
                         if journal_dir else None)
        self._pool = ThreadPoolExecutor(
            max_workers=max(8, 4 * n), thread_name_prefix="fleet-cell")
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._open_cells: Dict[str, Cell] = {}
        self._cids = itertools.count(1)
        self._submitted = 0
        self._closed = False
        self.metrics.bind(self.queue_depth, self._inflight)
        self.metrics.bind_queue(self.queue_occupancy)
        self.metrics.bind_tenants(self.tenants.counts)
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, daemon=True,
            name="fleet-heartbeat")
        self._hb_thread.start()

    def _make_workers(self, n: int, lanes_each: int,
                      device_sets: List[list], *,
                      store_base: Optional[str], mesh,
                      capacity: Optional[int], max_capacity: int,
                      fail_threshold: int,
                      open_s: float) -> List["FleetWorker"]:
        """Build the worker slots — ProcFleet overrides this to put each
        slot's service behind the wire instead of in-process."""

        def make_service(i: int) -> Callable[[], CheckService]:
            devs = device_sets[i] if i < len(device_sets) else []

            def make() -> CheckService:
                return CheckService(
                    max_queue_cells=self.max_queue_cells,
                    max_lanes=lanes_each,
                    store_base=store_base, mesh=mesh,
                    capacity=capacity, max_capacity=max_capacity,
                    device=devs[0] if devs else None)
            return make

        # kept for scale-up (add_worker): a slot built past the initial
        # N runs unpinned — on CPU CI that is every slot anyway, and a
        # scaled-up accelerator slot sharing device 0 still adds queue
        # capacity and host-tier throughput
        self._slot_factory = lambda wid: FleetWorker(
            wid, make_service(wid),
            devices=device_sets[wid] if wid < len(device_sets) else [],
            fail_threshold=fail_threshold, open_s=open_s)
        return [self._slot_factory(i) for i in range(n)]

    # -- submission -------------------------------------------------------
    def _inflight(self) -> int:
        snap = self.metrics._counters
        return max(0, self._submitted
                   - snap.get("requests-completed", 0))

    def submit(self, history: History, *,
               kind: str = KIND_WGL,
               deadline_s: Optional[float] = None,
               block: bool = True,
               timeout: Optional[float] = None,
               trace: Optional[Dict[str, Any]] = None,
               tenant: Optional[str] = None,
               **kw) -> Request:
        """Enqueue one history check across the fleet; same contract as
        CheckService.submit, including the admission-race rule: a request
        whose deadline expires while blocked on admission — its tenant's
        quota or fleet backpressure — resolves ``unknown`` — never
        dropped, never false.  ``trace`` and ``tenant`` ride beside the
        spec (never inside it — reroute and journal recovery round-trip
        the spec through build_spec)."""
        if self._is_closed():
            raise ServiceClosed("fleet is closed")
        spec = build_spec(kind, **kw)
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        req = Request(history, kind, spec, deadline_s=deadline_s,
                      trace=trace, tenant=tenant,
                      priority=self.tenants.priority(tenant))
        cells = decompose(req)
        # Hydra: over-threshold WGL cells scatter into fission child
        # cells HERE, before admission/journaling, so backpressure,
        # quotas, the journal, and the router all see the real
        # per-sub-problem work (serve/fission_plane.py)
        cells = fission_plane.scatter(req)
        for c in cells:
            c.cid = f"{req.id}.{next(self._cids)}"
        adm_deadline = req.deadline
        if timeout is not None:
            t_lim = mono_now() + timeout
            adm_deadline = t_lim if adm_deadline is None \
                else min(adm_deadline, t_lim)
        if not self.tenants.acquire(tenant, block=block,
                                    deadline=adm_deadline):
            if req.expired():
                return self._finish_expired(req, cells)
            self.metrics.inc("requests-rejected")
            raise ServiceSaturated(
                f"tenant {tenant!r} at quota; request of "
                f"{len(cells)} cell(s) rejected")
        req.on_finish = lambda t=tenant: self.tenants.release(t)
        if not self._admit(cells, block=block, timeout=timeout):
            if req.expired():
                return self._finish_expired(req, cells)
            self.tenants.release(tenant)
            req.on_finish = None
            self.metrics.inc("requests-rejected")
            raise ServiceSaturated(
                f"fleet at {self.queue_depth()}/{self.max_queue_cells} "
                f"open cells; request of {len(cells)} cell(s) rejected")
        self._count_submit(len(cells))
        if self._journal is not None:
            self._journal.record(req, cells)
        for c in cells:
            self._pool.submit(self._run_cell, c)
        return req

    def _count_submit(self, n_cells: int) -> None:
        with self._lock:
            self._submitted += 1
        self.metrics.inc("requests-submitted")
        self.metrics.inc("cells-submitted", n_cells)

    def _finish_expired(self, req: Request, cells: List[Cell]) -> Request:
        """Expiry-while-blocked (quota or backpressure): every cell
        resolves unknown and the handle comes back already done."""
        for c in cells:
            c.result = expired_result(req.kind)
        self.metrics.inc("deadline-expired", len(cells))
        self._count_submit(len(cells))
        self.metrics.inc("cells-completed", len(cells))
        self.metrics.inc("requests-completed")
        req.finish(aggregate(req))
        self.metrics.trace(req)
        return req

    def _admit(self, cells: List[Cell], block: bool,
               timeout: Optional[float]) -> bool:
        """Fleet-tier backpressure: all-or-nothing admission against the
        fleet-wide open-cell count, bounded by the request deadline."""
        req = cells[0].request
        deadline = None
        if timeout is not None:
            deadline = mono_now() + timeout
        rem = req.remaining_s()
        if rem is not None:
            d = mono_now() + rem
            deadline = d if deadline is None else min(deadline, d)
        with self._cond:
            while (not self._closed
                   and len(self._open_cells) + len(cells)
                   > self.max_queue_cells):
                if not block:
                    return False
                left = None if deadline is None else deadline - mono_now()
                if left is not None and left <= 0:
                    return False
                if not self._cond.wait(timeout=left if left is not None
                                       else 0.1):
                    return False
            if self._closed:
                raise ServiceClosed("fleet is closed")
            for c in cells:
                self._open_cells[c.cid] = c
            return True

    def check(self, history: History, *, timeout: Optional[float] = None,
              **kw) -> Dict[str, Any]:
        return self.submit(history, **kw).wait(timeout=timeout)

    # -- the per-cell driver ---------------------------------------------
    def _run_cell(self, cell: Cell, exclude: Tuple[int, ...] = ()) -> None:
        """One owner thread drives one cell to a verdict: route, wait,
        hedge, reroute, and finally — on every path — finalize.  The cell
        can end unresolved only if this thread dies, so the body is one
        try/except that degrades to unknown."""
        try:
            result = self._drive_cell(cell, exclude)
        except Exception as e:  # noqa: BLE001 — a driver bug must not
            log.exception("fleet cell driver crashed for %s", cell.cid)
            result = {"valid": "unknown", "analyzer": "fleet",
                      "error": f"fleet cell driver crashed: {e}"}
        self._finalize_cell(cell, result)

    def _drive_cell(self, cell: Cell,
                    exclude: Tuple[int, ...]) -> Dict[str, Any]:
        req = cell.request
        policy = self.retry_policy
        token = cell.route_token()
        excluded = set(exclude)
        attempts: List[Dict[str, Any]] = []
        prev_delay: Optional[float] = None
        tries = max(1, policy.tries)
        for attempt in range(tries):
            if cell.cancelled:
                return fission_plane.cancelled_result()
            if req.expired():
                self.metrics.inc("deadline-expired")
                return expired_result(req.kind)
            worker = self.router.pick(token, exclude=excluded, cell=cell)
            if worker is None:
                # Every alive worker's circuit is open (or everyone is
                # dead).  Wait out a cooldown — a half-open probe slot
                # may appear — then retry against the full fleet.
                self.metrics.inc("no-worker-available")
                attempts.append({"worker": None,
                                 "error": "no routable worker"})
                if attempt + 1 >= tries:
                    break
                prev_delay = policy.delay(attempt, prev=prev_delay)
                self._sleep_bounded(prev_delay, req)
                excluded = set(exclude)
                continue
            t0 = mono_now()
            res, failure, offender = self._attempt_on(worker, cell)
            took = mono_now() - t0
            offender = offender or worker
            if not failure:
                offender.breaker.record_success()
                offender.health.observe(latency_s=took)
                if res is None:  # pure expiry surfaced by the wait loop
                    res = expired_result(req.kind)
                res.setdefault("fleet", {})
                res["fleet"].update({"worker": offender.wid,
                                     "attempts": attempt + 1,
                                     "rerouted": attempt > 0})
                return res
            offender.breaker.record_failure()
            offender.health.observe(latency_s=took, error=True)
            self.metrics.inc("worker-failures")
            attempts.append({"worker": offender.wid, "error": failure})
            excluded.add(offender.wid)
            if len(excluded) >= len(self.workers_snapshot()):
                # Everyone has failed this cell once; a retry round
                # against recovered/restarted workers is still worth it.
                excluded = set(exclude)
            if attempt + 1 < tries:
                self.metrics.inc("cells-rerouted")
                RECORDER.record(
                    "retry", f"reroute:{cell.cid}", trace_id=req.trace_id,
                    span_id=req.span_id,
                    args={"attempt": attempt + 1, "worker": offender.wid,
                          "error": (failure or "")[:160]})
                prev_delay = policy.delay(attempt, prev=prev_delay)
                self._sleep_bounded(prev_delay, req)
        if req.expired():
            self.metrics.inc("deadline-expired")
            return expired_result(req.kind)
        return {"valid": "unknown", "analyzer": "fleet",
                "error": f"all {tries} fleet attempts failed",
                "fleet": {"attempts-log": attempts}}

    def _attempt_on(self, worker: FleetWorker,
                    cell: Cell) -> Tuple[Optional[Dict[str, Any]],
                                         Optional[str],
                                         Optional[FleetWorker]]:
        """One routed attempt: submit the cell to ``worker`` and wait,
        hedging to a sibling when the wait turns deadline-risky.  Returns
        ``(result, failure_reason, worker_of_record)``: ``failure_reason``
        is None on success (including a legitimate unknown) and a string
        when a worker — not the history — failed; ``worker_of_record`` is
        whoever actually produced the outcome (the hedge sibling when the
        hedge won), so the caller credits/penalizes the right breaker.  A
        hedge that lands on a broken sibling is penalized HERE and
        dropped — the still-running primary attempt is not abandoned for
        a sibling's failure."""
        req = cell.request
        # fleet-side dispatch mark: edge:dispatch->verdict in THIS
        # process's histograms is the full wire round trip + worker
        # time — the latency an injected slow link actually inflates
        # (worker-side spans never see the network)
        req.span("dispatch")
        try:
            wreq = worker.service.submit(cell.history, block=False,
                                         deadline_s=req.remaining_s(),
                                         trace=req.trace_context(),
                                         **self._cell_kwargs(cell))
        except (ServiceClosed, ServiceSaturated) as e:
            return None, f"{type(e).__name__}: {e}", worker
        except Exception as e:  # noqa: BLE001 — submit crashed = worker bug
            return None, f"submit crashed: {type(e).__name__}: {e}", worker
        hedge_at = self._hedge_after(req)
        hreq = None
        hedge_worker: Optional[FleetWorker] = None
        hedge_excluded = {worker.wid}
        t0 = mono_now()
        cap = req.remaining_s()
        cap = NO_DEADLINE_WAIT_S if cap is None else cap
        while True:
            if wreq.done():
                # a completed hedge loser still contributed spans — keep
                # them in the tree before abandoning the handle
                if hreq is not None and hreq.done():
                    req.absorb_serve(hreq.result)
                res, failure = self._classify(dict(wreq.result or {}), req)
                return res, failure, worker
            if hreq is not None and hreq.done():
                res, failure = self._classify(dict(hreq.result or {}), req)
                if failure:
                    req.absorb_serve(hreq.result)  # keep the failed
                    # sibling's spans — the trace shows the attempt
                    # The hedge landed on a broken sibling: penalize IT,
                    # drop the hedge, keep waiting on the primary (whose
                    # attempt is still live and may well succeed).
                    hedge_worker.breaker.record_failure()
                    hedge_worker.health.observe(error=True)
                    self.metrics.inc("worker-failures")
                    hedge_excluded.add(hedge_worker.wid)
                    hreq = None
                    hedge_worker = None
                    hedge_at = (mono_now() - t0) + 0.1
                else:
                    self.metrics.inc("hedge-wins")
                    if res is not None:
                        res.setdefault("fleet", {})["hedged-from"] = \
                            worker.wid
                    return res, None, hedge_worker
            now = mono_now()
            if cell.cancelled:
                # a sibling decided this cell's fission group; the worker
                # keeps computing (never interrupted) but its verdict no
                # longer matters — release the driver thread now
                return fission_plane.cancelled_result(), None, worker
            if req.expired():
                return None, None, worker  # pure expiry → unknown upstream
            if now - t0 > cap:
                return None, "worker unresponsive past wait cap", worker
            if not worker.alive() and (hreq is None
                                       or (hedge_worker is not None
                                           and not hedge_worker.alive())):
                return None, "worker died mid-check", worker
            if hreq is None and hedge_at is not None \
                    and now - t0 >= hedge_at:
                hedge_worker = self.router.pick(cell.route_token(),
                                                exclude=hedge_excluded,
                                                cell=cell)
                if hedge_worker is not None:
                    try:
                        hreq = hedge_worker.service.submit(
                            cell.history, block=False,
                            deadline_s=req.remaining_s(),
                            trace=req.trace_context(),
                            **self._cell_kwargs(cell))
                        self.metrics.inc("hedges")
                        RECORDER.record(
                            "retry", f"hedge:{cell.cid}",
                            trace_id=req.trace_id, span_id=req.span_id,
                            args={"primary": worker.wid,
                                  "hedge": hedge_worker.wid})
                    except Exception:  # noqa: BLE001 — sibling saturated
                        hreq = None
                        hedge_worker = None
                if hreq is None:
                    # No sibling available; re-arm the hedge for later.
                    hedge_at = (now - t0) + max(0.1, self._hedge_after(req)
                                                or DEFAULT_HEDGE_S)
            time.sleep(POLL_S)

    def _cell_kwargs(self, cell: Cell) -> Dict[str, Any]:
        """The worker submit kwargs for one cell: the request spec with
        the cell's fission overrides merged over it (ghost-variant
        children pin worker fission off and a threshold-sized ceiling;
        ordinary cells have no overrides and this IS submit_kwargs)."""
        kw = submit_kwargs(cell.request)
        kw.update(cell.spec_overrides)
        return kw

    def _classify(self, res: Dict[str, Any],
                  req: Request) -> Tuple[Optional[Dict[str, Any]],
                                         Optional[str]]:
        """Worker failure vs legitimate verdict.  Narrow on purpose: only
        error strings the scheduler emits when *it* (not the history)
        failed count as retriable — rerouting a budget-truncation or
        deadline unknown would re-check forever."""
        err = str(res.get("error") or "")
        if res.get("valid") == "unknown" and not req.expired() \
                and any(err.startswith(m) for m in _WORKER_FAILURE_ERRORS):
            return None, f"worker-tier failure: {err}"
        return res, None

    def _hedge_after(self, req: Request) -> Optional[float]:
        """When to fire the hedge: the configured knob, else half the
        remaining budget clamped to [50 ms, 2 s] (a late hedge is a
        useless hedge), else the no-deadline default."""
        if self.hedge_s is not None:
            return self.hedge_s
        rem = req.remaining_s()
        if rem is None:
            return DEFAULT_HEDGE_S
        return min(2.0, max(0.05, rem * 0.5))

    def _sleep_bounded(self, d: float, req: Request) -> None:
        """Backoff that never sleeps through the deadline."""
        rem = req.remaining_s()
        if rem is not None:
            d = max(0.0, min(d, rem))
        if d > 0:
            time.sleep(d)

    def _finalize_cell(self, cell: Cell, result: Dict[str, Any]) -> None:
        # Hydra's evidence seam: fission children get witness recovery
        # (pinned to the refuting worker) and trigger sibling cancel
        # before the verdict is committed; ordinary cells pass through.
        try:
            result = fission_plane.on_child_result(self, cell, result)
        except Exception as e:  # noqa: BLE001 — the seam must never lose
            log.exception("fission finalize seam failed for %s", cell.cid)
            result = {"valid": "unknown", "analyzer": "fleet-fission",
                      "error": f"fission finalize seam crashed: {e}"}
        cell.result = result
        self.metrics.inc("cells-completed")
        req = cell.request
        # fold the winning attempt's worker-side spans into the root's
        # tree before aggregation buries them under per-key results
        req.absorb_serve(result)
        if req.claim_finish():
            req.finish(aggregate(req))
            self.metrics.inc("requests-completed")
            self.metrics.trace(req)
        if self._journal is not None:
            self._journal.complete(cell.cid)
        with self._cond:
            self._open_cells.pop(cell.cid, None)
            self._cond.notify_all()

    # -- health -----------------------------------------------------------
    def _heartbeat_loop(self) -> None:
        while not self._is_closed():
            for w in self.workers_snapshot():
                if w.retired:
                    continue  # decommissioned slot: dead for good
                try:
                    p = w.service.ping()
                except Exception:  # noqa: BLE001
                    p = {"alive": False}
                w.health.beat()
                self.telemetry.observe_breaker(w.wid,
                                               w.breaker.state == OPEN)
                if not p.get("alive"):
                    self.metrics.inc("heartbeat-misses")
            try:
                self._telemetry_sweep()
            except Exception:  # noqa: BLE001 — telemetry must never
                log.exception("telemetry sweep failed")  # kill heartbeat
            time.sleep(self.heartbeat_s)

    # -- Watchtower -------------------------------------------------------
    def _note_worker_telemetry(self, wid: int,
                               payload: Dict[str, Any]) -> None:
        """Sink for one proc worker's TELEMETRY push (runs on that
        worker's wire reader thread).  Tags the slot's generation —
        worker processes don't know which respawn they are — then lands
        the push and evaluates the SLOs against it."""
        try:
            w = self.workers_snapshot()[wid]
        except (IndexError, TypeError):
            return
        payload = dict(payload or {})
        payload.setdefault("generation", w.generation)
        self.telemetry.record_push(wid, payload)
        self.slo.evaluate(wid)

    def _telemetry_sweep(self) -> None:
        """Heartbeat-cadence half of the telemetry plane: once per push
        interval, contribute the fleet process's own base snapshot as
        the ``fleet`` pseudo-worker, scrape in-process (wireless) worker
        services into the store, and run one SLO sweep over everyone —
        the sweep is what catches staleness, since a stale worker by
        definition sends no push to evaluate."""
        if self.telemetry_s <= 0:
            return
        now = mono_now()
        if now - self._last_tele_sweep < self.telemetry.interval_s:
            return
        self._last_tele_sweep = now
        snap = Metrics.snapshot(self.metrics)  # base sections only — the
        snap.pop("traces", None)               # full fleet snapshot would
        # re-scrape every worker per interval
        self.telemetry.record_push("fleet", {
            "pid": os.getpid(),
            "uptime-s": round(now - self._t0, 3),
            "interval-s": self.telemetry.interval_s,
            "metrics": snap}, now=now)
        for w in self.workers_snapshot():
            svc = w.service
            if w.retired:
                continue  # evicted from the store; must not re-register
            if hasattr(svc, "metrics_snapshot"):
                continue  # wire-backed: its process pushes for itself
            m = getattr(svc, "metrics", None)
            if m is None:
                continue
            try:
                ws = dict(m.snapshot())
            except Exception:  # noqa: BLE001 — mid-crash worker
                continue
            ws.pop("traces", None)
            self.telemetry.record_push(w.wid, {
                "pid": os.getpid(), "generation": w.generation,
                "interval-s": self.telemetry.interval_s,
                "metrics": ws}, now=now)
        self.slo.evaluate_all(now=now)

    def alerts(self) -> List[Dict[str, Any]]:
        """The SLO engine's fired-alert ring (web.py GET /alerts)."""
        return self.slo.alerts()

    def set_recorder(self, on: bool) -> Dict[str, Any]:
        """Arm/disarm the flight recorder at runtime — locally and, for
        wire-backed workers, remotely over the STATUS frame (POST
        /recorder).  Best-effort per worker; returns who acked."""
        if on:
            RECORDER.enable()
        else:
            RECORDER.disable()
        acks: Dict[str, bool] = {}
        for w in self.workers_snapshot():
            fn = getattr(w.service, "set_recorder", None)
            if fn is None:
                continue   # in-process worker: shares this RECORDER
            try:
                acks[str(w.wid)] = bool(fn(on))
            except Exception:  # noqa: BLE001 — unreachable worker
                acks[str(w.wid)] = False
        return {"enabled": RECORDER.enabled, "workers": acks,
                **RECORDER.stats()}

    def restart_worker(self, wid: int,
                       only_if_dead: bool = False) -> FleetWorker:
        """Bring a (dead) worker slot back with a fresh service; its
        journal-relevant state lives fleet-side, so nothing is replayed
        here — cells routed to the corpse already rerouted via their
        owner threads."""
        w = self.workers_snapshot()[wid]
        if w.restart(only_if_dead=only_if_dead):
            self.metrics.inc("worker-restarts")
        return w

    # -- Governor scale plane (serve/autoscale.py) ------------------------
    def can_scale_locally(self) -> bool:
        """Can this fleet spawn a worker slot in-process?  ProcFleet and
        registry-backed fleets answer False — the Governor emits a
        structured scale request for the deployment layer instead."""
        return getattr(self, "_slot_factory", None) is not None

    def active_workers(self) -> int:
        """Slots currently able to take traffic: alive, not draining,
        not retired — the autoscaler's worker-count signal."""
        return sum(1 for w in self.workers_snapshot()
                   if w.alive() and not w.draining and not w.retired)

    def journal_pending(self) -> int:
        return self._journal.pending_count() if self._journal else 0

    def queue_occupancy(self) -> Dict[str, Any]:
        """Fleet-tier occupancy: open cells by bucket plus the oldest
        open request's wait-age — the same shape CheckService exposes
        from its scheduler, so the autoscaler (and the prom rendering)
        read one schema at either tier."""
        now = mono_now()
        with self._lock:
            cells = list(self._open_cells.values())
        buckets_out: Dict[str, int] = {}
        oldest = 0.0
        for c in cells:
            b = str(c.bucket)
            buckets_out[b] = buckets_out.get(b, 0) + 1
            oldest = max(oldest, now - c.request.submitted)
        return {"depth": len(cells), "buckets": buckets_out,
                "oldest-wait-s": round(oldest, 6)}

    def add_worker(self) -> FleetWorker:
        """Scale up: append one fresh worker slot.  The router shares the
        live worker list, so the new slot starts taking rendezvous
        traffic immediately; its wid is append-only (never reused) to
        keep journal records and telemetry history unambiguous."""
        if not self.can_scale_locally():
            raise RuntimeError("fleet cannot spawn worker slots locally; "
                               "consume the Governor's scale requests "
                               "instead")
        with self._lock:
            if self._closed:
                raise ServiceClosed("fleet is closed")
            wid = len(self.workers)
            w = self._slot_factory(wid)
            self.workers.append(w)
            self.n_workers = len(self.workers)
        self.telemetry.register(w.wid)
        self.metrics.inc("workers-added")
        return w

    def decommission_worker(self, wid: int,
                            timeout_s: float = 30.0) -> Dict[str, Any]:
        """Scale down strictly by lease drain: mark the slot draining
        (the router stops ranking it), wait until it is idle AND the
        journal has zero pending cells, then retire and kill it.  A
        drain that cannot complete within ``timeout_s`` ABORTS — the
        slot un-drains and keeps serving, because killing a worker with
        journal-pending work would turn bounded unknowns into recovery
        churn.  Returns the decision evidence either way."""
        w = self.workers_snapshot()[wid]
        w.draining = True
        deadline = mono_now() + timeout_s
        drained = False
        while mono_now() < deadline and not self._is_closed():
            try:
                p = w.service.ping()
            except Exception:  # noqa: BLE001 — already dead is idle
                p = {"alive": False, "queue-depth": 0, "inflight-cells": 0}
            idle = (not p.get("alive")
                    or (p.get("queue-depth") == 0
                        and p.get("inflight-cells") == 0))
            if idle and self.journal_pending() == 0:
                drained = True
                break
            time.sleep(0.05)
        pending = self.journal_pending()
        if not drained:
            w.draining = False
            self.metrics.inc("decommission-aborts")
            return {"worker": wid, "drained": False,
                    "journal-pending": pending}
        w.retired = True
        try:
            w.kill()
        except Exception:  # noqa: BLE001 — racing a chaos kill is fine
            pass
        self.slo.forget(wid)
        self.telemetry.evict(wid)
        self.metrics.inc("workers-decommissioned")
        return {"worker": wid, "drained": True, "journal-pending": pending}

    def fleet_status(self) -> Dict[str, Any]:
        workers = self.workers_snapshot()
        return {"workers": [w.status() for w in workers],
                "journal": {"enabled": self._journal is not None,
                            "pending": (self._journal.pending_count()
                                        if self._journal else 0),
                            "writes": (self._journal.writes
                                       if self._journal else 0),
                            "path": (self._journal.path
                                     if self._journal else None)},
                "circuits": {w.wid: dict(w.breaker.transitions)
                             for w in workers}}

    def worker_snapshots(self) -> List[Optional[Dict[str, Any]]]:
        """Scrape every worker's ``Metrics.snapshot()`` — for in-process
        workers straight off the service, for proc workers over the
        STATUS frame (``metrics_snapshot``).  Best-effort per worker: a
        partitioned or dead worker contributes ``None``, never an
        exception — one bad link must not fail the fleet's /metrics
        document."""
        out: List[Optional[Dict[str, Any]]] = []
        for w in self.workers_snapshot():
            snap: Optional[Dict[str, Any]] = None
            try:
                svc = w.service
                ms = getattr(svc, "metrics_snapshot", None)
                if ms is not None:          # ProcWorkerService: over STATUS
                    snap = ms()
                else:
                    m = getattr(svc, "metrics", None)
                    if m is not None:
                        snap = m.snapshot()
            except Exception:  # noqa: BLE001 — a scrape never fails the doc
                snap = None
            out.append(snap)
        return out

    def merged_trace(self, request_id: str) -> Optional[Dict[str, Any]]:
        """The fully-assembled causal tree for a finished request: root
        spans from this fleet process plus every worker/hedge subtree
        absorbed off RESULT frames (see Request.absorb_serve)."""
        return self.metrics.find_trace(request_id)

    #: per-probe wall bound on the whole deep-healthz fan-out — one hung
    #: worker must cost the endpoint at most this, not its rpc timeout
    #: serially multiplied by the fleet size.  Env-overridable
    #: (JEPSEN_TPU_DEEP_HEALTHZ_S): a WAN-hop worker in a multi-host
    #: fleet cannot answer inside the loopback-tuned 2 s window.
    DEEP_HEALTHZ_TIMEOUT_S = 2.0

    @classmethod
    def deep_healthz_timeout_s(cls) -> float:
        """The deep-healthz fan-out budget: ``JEPSEN_TPU_DEEP_HEALTHZ_S``
        (seconds, > 0) or the 2 s default.  Read at call time so a
        running fleet picks up a re-tune without restart."""
        raw = os.environ.get("JEPSEN_TPU_DEEP_HEALTHZ_S", "")
        try:
            v = float(raw) if raw else cls.DEEP_HEALTHZ_TIMEOUT_S
        except ValueError:
            return cls.DEEP_HEALTHZ_TIMEOUT_S
        return v if v > 0 else cls.DEEP_HEALTHZ_TIMEOUT_S

    def healthz(self, deep: bool = False,
                deep_timeout_s: Optional[float] = None) -> Dict[str, Any]:
        """The load-balancer/chaos probe payload (web.py GET /healthz):
        fleet is ``ok`` while at least one worker is alive with a
        non-open circuit.  ``deep`` additionally asks each remote worker
        for its OWN healthz over the wire (``GET /healthz?deep=1``) —
        fanned out in parallel with one shared wall bound, so a single
        hung or partitioned worker degrades ITS entry to a timeout
        error instead of stalling the whole endpoint behind its RPC."""
        st = self.fleet_status()
        ok = any(w["alive"] and w["circuit"] != OPEN
                 for w in st["workers"])
        if deep:
            budget = (self.deep_healthz_timeout_s()
                      if deep_timeout_s is None else float(deep_timeout_s))
            targets = [(w, entry)
                       for w, entry in zip(self.workers_snapshot(),
                                           st["workers"])
                       if getattr(w.service, "healthz", None) is not None]
            if targets:
                pool = ThreadPoolExecutor(
                    max_workers=len(targets),
                    thread_name_prefix="fleet-deepz")
                futs = [(pool.submit(w.service.healthz), entry)
                        for w, entry in targets]
                deadline = mono_now() + budget
                for fut, entry in futs:
                    try:
                        entry["remote"] = fut.result(
                            timeout=max(0.0, deadline - mono_now()))
                    except FutureTimeout:
                        fut.cancel()
                        entry["remote"] = {
                            "ok": False,
                            "error": f"deep healthz timeout after "
                                     f"{budget:.2f}s"}
                    except Exception as e:  # noqa: BLE001 — bad link
                        entry["remote"] = {
                            "ok": False,
                            "error": f"{type(e).__name__}: {e}"}
                # never wait on stragglers: a hung probe thread is
                # abandoned to finish (or not) on its own
                pool.shutdown(wait=False)
        return {"ok": ok, "queue-depth": self.queue_depth(), **st}

    # -- journal recovery -------------------------------------------------
    @staticmethod
    def recover(journal_dir: str) -> Dict[str, List[Dict[str, Any]]]:
        """Read a crashed fleet's journal: see FleetJournal.recover."""
        return FleetJournal.recover(journal_dir)

    def resubmit_recovered(self, journal_dir: str,
                           claimant: Optional[str] = None
                           ) -> Dict[str, Any]:
        """Re-enqueue a crashed fleet's journaled cells onto THIS fleet.
        Pending cells are resubmitted with their remaining deadline
        budget; already-expired cells are NOT re-checked — they are
        reported so the caller can surface their ``unknown`` explicitly.

        Recovery is single-winner: the claim file (exclusive_create,
        stale-stealable when its pid is dead) guarantees that of N
        supervisors recovering the same directory exactly one resubmits
        each pending cell.  The loser returns immediately with
        ``claimed: False`` and who beat it.  Returns ``{"requests":
        [Request...], "expired": [items], "claimed": bool}``."""
        me = claimant or f"fleet-{id(self):x}"
        if not FleetJournal.claim_recovery(journal_dir, me):
            self.metrics.inc("journal-claim-lost")
            return {"requests": [], "expired": [], "claimed": False,
                    "claimed-by": FleetJournal.claim_holder(journal_dir)}
        rec = FleetJournal.recover(journal_dir)
        reqs = []
        for item in rec["pending"]:
            reqs.append(self.submit(item["history"], **item["kwargs"]))
        if rec["pending"]:
            self.metrics.inc("journal-recovered", len(rec["pending"]))
        if rec["expired"]:
            self.metrics.inc("journal-expired", len(rec["expired"]))
        return {"requests": reqs, "expired": rec["expired"],
                "claimed": True}

    # -- core.analyze routing (shared with CheckService) ------------------
    _routable = CheckService._routable
    try_route_analyze = CheckService.try_route_analyze

    # -- lifecycle --------------------------------------------------------
    def queue_depth(self) -> int:
        with self._lock:
            return len(self._open_cells)

    def workers_snapshot(self) -> List["FleetWorker"]:
        """Point-in-time copy of the slot list.  ``add_worker`` appends
        under the fleet lock, so the heartbeat/supervisor/export threads
        must not iterate the live list — they iterate this copy; the
        slot objects themselves carry their own breaker/health locks."""
        with self._lock:
            return list(self.workers)

    def _is_closed(self) -> bool:
        with self._lock:
            return self._closed

    def alive(self) -> bool:
        return not self._is_closed() and any(
            w.alive() for w in self.workers_snapshot())

    def drain(self, timeout: Optional[float] = None) -> bool:
        deadline = (mono_now() + timeout) if timeout is not None else None
        with self._cond:
            while self._open_cells:
                left = None if deadline is None else deadline - mono_now()
                if left is not None and left <= 0:
                    return False
                self._cond.wait(timeout=left if left is not None else 0.1)
            return True

    def close(self, timeout: Optional[float] = None) -> bool:
        """Stop admitting, drain every open cell (each admitted request
        still resolves), then shut the workers down."""
        with self._lock:
            if self._closed:
                return True
        ok = self.drain(timeout=timeout)
        with self._lock:
            self._closed = True
        self._pool.shutdown(wait=True)
        for w in self.workers_snapshot():
            try:
                w.service.close(timeout=timeout)
            except Exception:  # noqa: BLE001 — close the rest regardless
                log.exception("worker %d close failed", w.wid)
        if self._hb_thread.is_alive():
            self._hb_thread.join(timeout=2 * self.heartbeat_s + 1.0)
        return ok

    def kill(self) -> None:
        """Abrupt whole-fleet death (crash semantics, for recovery
        tests): no drain, workers killed, open cells left in the journal
        for :meth:`recover`."""
        with self._lock:
            self._closed = True
        for w in self.workers_snapshot():
            try:
                w.kill()
            except Exception:  # noqa: BLE001
                pass
        self._pool.shutdown(wait=False, cancel_futures=True)

    def __enter__(self) -> "Fleet":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# ---------------------------------------------------------------------------
# out-of-process workers on a real wire
# ---------------------------------------------------------------------------


class ProcWorker(FleetWorker):
    """A worker slot whose service lives across a socket: the
    :class:`~jepsen_tpu.serve.transport.ProcWorkerService` facade over a
    launcher (real subprocess or in-process thread server), dialed
    through this slot's stable :class:`~jepsen_tpu.net_proxy.PairProxy`
    link so the chaos harness owns the wire."""

    def __init__(self, wid: int, make_service, proxy: PairProxy,
                 devices: Optional[list] = None,
                 fail_threshold: int = 3, open_s: float = 1.0):
        self.proxy = proxy
        super().__init__(wid, make_service, devices=devices,
                         fail_threshold=fail_threshold, open_s=open_s)

    def status(self) -> Dict[str, Any]:
        st = super().status()
        st["link"] = {"proxy-port": self.proxy.port,
                      "severed": self.proxy.severed,
                      "delay-s": self.proxy.delay_s}
        remote = getattr(self.service, "remote_status", None)
        if remote is not None:
            try:
                st["proc"] = remote()
            except Exception:  # noqa: BLE001 — status never raises
                pass
        return st


class ProcFleet(Fleet):
    """The fleet with every worker out of process and every byte of the
    submit surface on a real wire.

    Each slot runs ``python -m jepsen_tpu.serve.worker_main`` as its own
    OS process (``spawn=True``; ``spawn=False`` hosts the identical
    protocol server on a thread for tier-1 CI), dialed through a
    per-slot PairProxy whose port is stable across worker respawns
    (``retarget``).  That link is what upgrades the chaos harness from
    scheduler-patching faults to true network faults: partition
    (RST + ECONNREFUSED), mid-frame cuts, slow links, reconnect storms.

    A supervisor thread respawns crashed worker *processes* into their
    slots — the process-tier analogue of ``restart_worker`` — while the
    per-cell drivers handle the requests the corpse stranded (transport
    unknowns → reroute), and the journal claim keeps a crashed
    *supervisor*'s recovery single-winner."""

    def __init__(self, workers: int = 3, *,
                 spawn: bool = True,
                 log_dir: Optional[str] = None,
                 supervise_s: float = 0.5,
                 worker_ready_timeout_s: float = 120.0,
                 **kw):
        self._spawn = spawn
        self._log_dir = log_dir
        self.supervise_s = supervise_s
        self.worker_ready_timeout_s = worker_ready_timeout_s
        self.proxies: List[PairProxy] = []
        self._sup_lock = threading.Lock()
        self._store_base = kw.get("store_base")
        # subprocess workers already pin nothing useful from the parent;
        # device pinning is the worker process's own business
        kw.setdefault("pin_devices", False)
        # resolved before super().__init__ because _make_workers (called
        # from there) builds WireClients that share the fleet's policy
        kw.setdefault("retry_policy", RetryPolicy(
            tries=4, backoff_s=0.02, max_backoff_s=0.5, decorrelated=True))
        self.retry_policy = kw["retry_policy"]
        super().__init__(workers, **kw)
        self._sup_thread = threading.Thread(
            target=self._supervise_loop, daemon=True,
            name="procfleet-supervisor")
        self._sup_thread.start()

    def _make_workers(self, n: int, lanes_each: int,
                      device_sets: List[list], *,
                      store_base: Optional[str], mesh,
                      capacity: Optional[int], max_capacity: int,
                      fail_threshold: int,
                      open_s: float) -> List[FleetWorker]:
        lanes = buckets.proc_worker_lanes(self.max_lanes, n)
        if self._log_dir is None:
            import tempfile
            self._log_dir = tempfile.mkdtemp(prefix="procfleet-logs-")
        workers: List[FleetWorker] = []
        for i in range(n):
            # the target is retargeted at the worker's real port once
            # its launcher reports ready; port 1 can never accept, so a
            # dial before readiness fails fast instead of hanging
            proxy = PairProxy("fleet", f"worker-{i}", ("127.0.0.1", 1))
            self.proxies.append(proxy)
            workers.append(ProcWorker(
                i, self._make_proc_service(i, lanes, proxy,
                                           store_base=store_base,
                                           capacity=capacity,
                                           max_capacity=max_capacity),
                proxy, devices=[],
                fail_threshold=fail_threshold, open_s=open_s))
        return workers

    def _make_proc_service(self, i: int, lanes: int, proxy: PairProxy, *,
                           store_base: Optional[str],
                           capacity: Optional[int], max_capacity: int):
        from jepsen_tpu.serve.transport import ProcWorkerService
        from jepsen_tpu.serve.worker_main import (SubprocessWorker,
                                                  ThreadWorker)
        name = f"proc-worker-{i}"
        spawn = self._spawn
        log_dir = self._log_dir
        ready_s = self.worker_ready_timeout_s
        mqc = self.max_queue_cells

        tele_s = self.telemetry_s

        def make():
            if spawn:
                launcher = SubprocessWorker(
                    name, os.path.join(log_dir, f"{name}.log"),
                    args={"max-lanes": lanes, "max-queue": mqc,
                          "store-base": store_base,
                          "capacity": capacity,
                          "max-capacity": max_capacity,
                          "telemetry-s": tele_s},
                    ready_timeout_s=ready_s)
            else:
                launcher = ThreadWorker(
                    name,
                    lambda: CheckService(max_queue_cells=mqc,
                                         max_lanes=lanes,
                                         store_base=store_base,
                                         capacity=capacity,
                                         max_capacity=max_capacity),
                    telemetry_s=tele_s)
            svc = ProcWorkerService(launcher, proxy,
                                    retry_policy=self.retry_policy,
                                    name=name)
            # TELEMETRY pushes from this slot land wid-tagged in the
            # fleet's store (the sink survives respawns: every fresh
            # service from this factory re-registers it)
            svc.on_telemetry = \
                lambda payload: self._note_worker_telemetry(i, payload)
            return svc
        return make

    # -- the supervisor ----------------------------------------------------
    def _supervise_loop(self) -> None:
        while not self._is_closed():
            for w in self.workers_snapshot():
                try:
                    if self._maybe_respawn(w):
                        self.metrics.inc("supervisor-respawns")
                except Exception:  # noqa: BLE001 — a failed respawn
                    log.exception("supervisor respawn of worker %d "
                                  "failed", w.wid)  # retries next sweep
            time.sleep(self.supervise_s)

    def _maybe_respawn(self, w: FleetWorker) -> bool:
        """Respawn ``w`` iff its process is dead and the fleet is open.
        The sup lock + ``only_if_dead`` make the supervisor, a chaos
        undo, and a manual ``restart_worker`` mutually exclusive: one
        respawner wins, the rest observe the fresh service.  Retired
        slots (scale-down, decommission_worker) stay dead: respawning
        one would undo the Governor's drain."""
        if w.alive() or w.retired:
            return False
        with self._sup_lock:
            # fleet lock under the sup lock is manifest-descending
            if self._is_closed() or w.alive() or w.retired:
                return False
            if w.restart(only_if_dead=True):
                self.metrics.inc("worker-restarts")
                return True
            return False

    # -- lifecycle ---------------------------------------------------------
    def close(self, timeout: Optional[float] = None) -> bool:
        ok = super().close(timeout=timeout)
        self._join_supervisor()
        # an in-flight respawn may have installed a fresh service after
        # super().close() swept the old ones: final sweep under the sup
        # lock catches it (ProcWorkerService.close is idempotent)
        with self._sup_lock:
            for w in self.workers_snapshot():
                try:
                    w.service.close(timeout=5.0)
                except Exception:  # noqa: BLE001
                    pass
        for p in self.proxies:
            p.close()
        return ok

    def kill(self) -> None:
        super().kill()
        self._join_supervisor()
        with self._sup_lock:
            for w in self.workers_snapshot():
                try:
                    w.service.kill()
                except Exception:  # noqa: BLE001
                    pass
        for p in self.proxies:
            p.close()

    def _join_supervisor(self) -> None:
        t = getattr(self, "_sup_thread", None)
        if t is not None and t.is_alive():
            t.join(timeout=2 * self.supervise_s + 1.0)
