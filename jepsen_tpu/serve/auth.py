"""Frame authentication for the multi-host wire: HMAC envelopes.

On one host the wire's trust boundary is the loopback interface; the
moment workers REGISTER from other machines (serve/fleetport.py) every
frame crosses a real network, and an unauthenticated control plane
would accept SUBMITs, REGISTERs, and lease renewals from anyone who can
reach the port.  The envelope is deliberately small: a shared secret
(``JEPSEN_TPU_FLEET_TOKEN``) and an HMAC-SHA256 over the frame's
canonical JSON, carried in an ``auth`` field beside the payload.

Discipline:

- **constant-time verify** — :func:`verify_frame` compares digests with
  ``hmac.compare_digest`` only; a byte-at-a-time comparison would leak
  the mac through timing.
- **the token never travels and is never logged** — only the keyed
  digest crosses the wire; no function in this module (or any caller)
  may put the token into a log record, an ERROR frame, a trace span, or
  a telemetry payload.  Export surfaces carry at most
  ``auth-enabled: true``.
- **no token = auth off** — an unset/empty env var keeps the wire
  exactly as it was (single-host CI, loopback fleets).  Mixed
  deployments fail closed: a verifying side with a token rejects
  unsigned frames with a typed ERROR (``error-class: AuthError``) and a
  hangup.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import os
from typing import Any, Dict, Optional

#: the env var holding the shared fleet secret
TOKEN_ENV = "JEPSEN_TPU_FLEET_TOKEN"

#: the frame field carrying the mac (stripped before digesting)
AUTH_FIELD = "auth"


class AuthError(Exception):
    """A frame failed authentication (missing or wrong mac).  The
    message never contains token material — only which peer and why."""


def fleet_token(env: Optional[Dict[str, str]] = None) -> Optional[str]:
    """The configured shared secret, or None when auth is disabled.
    Read at call time, not import time, so tests and long-lived
    processes see a freshly-set env var."""
    raw = (env if env is not None else os.environ).get(TOKEN_ENV, "")
    raw = raw.strip()
    return raw or None


def canonical_frame_bytes(frame: Dict[str, Any]) -> bytes:
    """The digest input: the frame minus its ``auth`` field, serialized
    canonically (sorted keys, minimal separators) so both ends of the
    wire — which each hold a *parsed* dict, not the original bytes —
    compute the identical preimage."""
    body = {k: v for k, v in frame.items() if k != AUTH_FIELD}
    return json.dumps(body, sort_keys=True, separators=(",", ":"),
                      default=str).encode("utf-8")


def frame_mac(frame: Dict[str, Any], token: str) -> str:
    return hmac.new(token.encode("utf-8"), canonical_frame_bytes(frame),
                    hashlib.sha256).hexdigest()


def sign_frame(frame: Dict[str, Any],
               token: Optional[str]) -> Dict[str, Any]:
    """A copy of ``frame`` carrying its mac; the frame itself when auth
    is disabled (no token)."""
    if not token:
        return frame
    out = dict(frame)
    out[AUTH_FIELD] = frame_mac(out, token)
    return out


def verify_frame(frame: Dict[str, Any], token: Optional[str]) -> bool:
    """Constant-time mac check.  No token configured = every frame
    passes (auth off); with a token, a frame with a missing, non-string,
    or wrong mac fails."""
    if not token:
        return True
    mac = frame.get(AUTH_FIELD)
    if not isinstance(mac, str):
        return False
    return hmac.compare_digest(mac, frame_mac(frame, token))


def require_frame(frame: Dict[str, Any], token: Optional[str],
                  peer: str = "peer") -> None:
    """Verify or raise :class:`AuthError` — the server-side gate.  The
    error text names the peer and the failure mode only; it is safe to
    put on the wire as a typed ERROR frame."""
    if not verify_frame(frame, token):
        what = ("unauthenticated frame"
                if not isinstance(frame.get(AUTH_FIELD), str)
                else "bad frame mac")
        raise AuthError(f"{what} from {peer}")
