"""Frame authentication for the multi-host wire: HMAC envelopes.

On one host the wire's trust boundary is the loopback interface; the
moment workers REGISTER from other machines (serve/fleetport.py) every
frame crosses a real network, and an unauthenticated control plane
would accept SUBMITs, REGISTERs, and lease renewals from anyone who can
reach the port.  The envelope is deliberately small: a shared secret
(``JEPSEN_TPU_FLEET_TOKEN``) and an HMAC-SHA256 over the frame's
canonical JSON, carried in an ``auth`` field beside the payload.

Discipline:

- **constant-time verify** — :func:`verify_frame` compares digests with
  ``hmac.compare_digest`` only; a byte-at-a-time comparison would leak
  the mac through timing.
- **the token never travels and is never logged** — only the keyed
  digest crosses the wire; no function in this module (or any caller)
  may put the token into a log record, an ERROR frame, a trace span, or
  a telemetry payload.  Export surfaces carry at most
  ``auth-enabled: true``.
- **no token = auth off** — an unset/empty env var keeps the wire
  exactly as it was (single-host CI, loopback fleets).  Mixed
  deployments fail closed: a verifying side with a token rejects
  unsigned frames with a typed ERROR (``error-class: AuthError``) and a
  hangup.

Multi-tenancy rides on the same envelope: ``JEPSEN_TPU_TENANT_TOKENS``
holds per-tenant secrets (``name:secret,name:secret``); a frame that
names a ``tenant`` is verified against *that tenant's* token instead of
the fleet secret (:func:`resolve_frame_token`), so a tenant can submit
work without ever holding the fleet-wide credential.  A claimed tenant
with no issued token fails closed while tenant auth is configured.
Tenant tokens obey the same discipline as the fleet token: never
travel, never logged, never in any export surface.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import os
from typing import Any, Dict, Optional, Tuple

#: the env var holding the shared fleet secret
TOKEN_ENV = "JEPSEN_TPU_FLEET_TOKEN"

#: the env var holding per-tenant secrets: ``name:secret,name:secret``
TENANT_TOKENS_ENV = "JEPSEN_TPU_TENANT_TOKENS"

#: the frame field carrying the mac (stripped before digesting)
AUTH_FIELD = "auth"

#: the frame field naming the submitting tenant (part of the digest —
#: a mac minted for tenant A cannot be replayed as tenant B)
TENANT_FIELD = "tenant"


class AuthError(Exception):
    """A frame failed authentication (missing or wrong mac).  The
    message never contains token material — only which peer and why."""


def fleet_token(env: Optional[Dict[str, str]] = None) -> Optional[str]:
    """The configured shared secret, or None when auth is disabled.
    Read at call time, not import time, so tests and long-lived
    processes see a freshly-set env var."""
    raw = (env if env is not None else os.environ).get(TOKEN_ENV, "")
    raw = raw.strip()
    return raw or None


def tenant_tokens(env: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """Per-tenant secrets parsed from ``JEPSEN_TPU_TENANT_TOKENS``
    (``name:secret,name:secret``).  Empty dict = tenant auth off.
    Malformed entries (no colon, empty name or secret) are skipped
    rather than raising — a bad entry must not take the wire down.
    Read at call time, like :func:`fleet_token`."""
    raw = (env if env is not None else os.environ).get(TENANT_TOKENS_ENV, "")
    out: Dict[str, str] = {}
    for part in raw.split(","):
        name, _, secret = part.strip().partition(":")
        name, secret = name.strip(), secret.strip()
        if name and secret:
            out[name] = secret
    return out


def tenant_names(env: Optional[Dict[str, str]] = None) -> Tuple[str, ...]:
    """The tenant *names* with issued tokens — safe to export (names are
    identity, not credential)."""
    return tuple(sorted(tenant_tokens(env)))


def resolve_frame_token(frame: Dict[str, Any],
                        env: Optional[Dict[str, str]] = None,
                        ) -> Tuple[Optional[str], bool]:
    """The secret this frame must verify against, and whether the frame
    is resolvable at all.  A frame naming a ``tenant`` while tenant
    tokens are configured resolves to that tenant's token — or to
    ``(None, False)`` when the tenant has no issued token, which the
    caller must treat as a hard reject (fail closed: an unknown tenant
    must not fall back to fleet-level or unauthenticated acceptance).
    Everything else resolves to the fleet token (None = auth off)."""
    tenant = frame.get(TENANT_FIELD)
    toks = tenant_tokens(env)
    if tenant is not None and toks:
        tok = toks.get(str(tenant))
        return tok, tok is not None
    return fleet_token(env), True


def canonical_frame_bytes(frame: Dict[str, Any]) -> bytes:
    """The digest input: the frame minus its ``auth`` field, serialized
    canonically (sorted keys, minimal separators) so both ends of the
    wire — which each hold a *parsed* dict, not the original bytes —
    compute the identical preimage."""
    body = {k: v for k, v in frame.items() if k != AUTH_FIELD}
    return json.dumps(body, sort_keys=True, separators=(",", ":"),
                      default=str).encode("utf-8")


def frame_mac(frame: Dict[str, Any], token: str) -> str:
    return hmac.new(token.encode("utf-8"), canonical_frame_bytes(frame),
                    hashlib.sha256).hexdigest()


def sign_frame(frame: Dict[str, Any],
               token: Optional[str]) -> Dict[str, Any]:
    """A copy of ``frame`` carrying its mac; the frame itself when auth
    is disabled (no token)."""
    if not token:
        return frame
    out = dict(frame)
    out[AUTH_FIELD] = frame_mac(out, token)
    return out


def verify_frame(frame: Dict[str, Any], token: Optional[str]) -> bool:
    """Constant-time mac check.  No token configured = every frame
    passes (auth off); with a token, a frame with a missing, non-string,
    or wrong mac fails."""
    if not token:
        return True
    mac = frame.get(AUTH_FIELD)
    if not isinstance(mac, str):
        return False
    return hmac.compare_digest(mac, frame_mac(frame, token))


def require_frame(frame: Dict[str, Any], token: Optional[str],
                  peer: str = "peer") -> None:
    """Verify or raise :class:`AuthError` — the server-side gate.  The
    error text names the peer and the failure mode only; it is safe to
    put on the wire as a typed ERROR frame."""
    if not verify_frame(frame, token):
        what = ("unauthenticated frame"
                if not isinstance(frame.get(AUTH_FIELD), str)
                else "bad frame mac")
        raise AuthError(f"{what} from {peer}")
