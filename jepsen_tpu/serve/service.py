"""CheckService: the persistent in-process batched checking service.

One service owns one scheduler (one device loop) and accepts history-
check requests from any number of threads — concurrent test runs,
cli.py's ``submit`` command via the web endpoint, the web UI.  Requests
are decomposed into per-key cells, shape-bucketed, and continuously
batched onto the vmapped wgl / elle_tpu engines; verdicts come back
through the aggregator under the established never-degrade-to-false
rules.  See docs/serving.md.

Usage::

    with CheckService(store_base="store") as svc:
        req = svc.submit(history, kind="wgl", model="cas-register")
        result = req.wait()
        # or one-shot:
        result = svc.check(history, kind="elle", workload="list-append")

``core.analyze`` routes through a service automatically when the test
map carries one under ``test["service"]`` (see try_route_analyze), which
is how ``cli.test_all_cmd`` shares one device across a campaign.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Union

from jepsen_tpu.history import History
from jepsen_tpu.serve.aggregate import aggregate, expired_result
from jepsen_tpu.serve.decompose import decompose
from jepsen_tpu.serve.metrics import Metrics, mono_now
from jepsen_tpu.serve.request import KIND_ELLE, KIND_WGL, Request
from jepsen_tpu.serve.scheduler import Scheduler
from jepsen_tpu.serve.tenants import TenantTable


class ServiceSaturated(RuntimeError):
    """Admission control rejected the request (queue at max depth)."""


class ServiceClosed(RuntimeError):
    """The service is shut down; no new requests are admitted."""


def build_spec(kind: str, *, model=None, workload: str = "list-append",
               realtime: bool = False, consistency_models=None,
               engine: str = "auto", **engine_opts) -> Dict[str, Any]:
    """Normalize submit kwargs into a request spec — shared by
    CheckService.submit and the fleet's router (serve.fleet), so the two
    admission paths cannot drift on what a spec means."""
    if kind == KIND_WGL:
        if isinstance(model, str) or model is None:
            from jepsen_tpu.models import get_model
            model = get_model(model or "cas-register")
        return {"model": model, **engine_opts}
    if kind == KIND_ELLE:
        return {"workload": workload, "realtime": realtime,
                "consistency_models": consistency_models,
                "engine": engine, **engine_opts}
    raise ValueError(f"unknown kind {kind!r}")


def submit_kwargs(req: Request) -> Dict[str, Any]:
    """Invert :func:`build_spec`: the kwargs that re-submit ``req``'s
    spec to another service — the fleet's reroute/hedge path and journal
    recovery both re-enqueue cells this way.  (build_spec is idempotent
    on its own output, so round-tripping is safe.)"""
    return {"kind": req.kind, **req.spec}


class _ServiceRouted:
    """Checker adapter: ``check`` submits to the service (used for the
    serviceable children of a composed checker, so Compose's merge and
    crash handling stay authoritative).  Falls back to the wrapped
    checker's direct path if routing declines."""

    def __init__(self, service: "CheckService", inner):
        self.service = service
        self.inner = inner

    def check(self, test, history, opts=None):
        routed = self.service.try_route_analyze(test, self.inner, history,
                                                opts)
        if routed is not None:
            return routed
        # Compose already wraps this call in check_safe — crashes and
        # budgets are handled one level up; don't double-wrap.
        return self.inner.check(test, history, opts)


class CheckService:
    def __init__(self,
                 max_queue_cells: int = 4096,
                 max_lanes: int = 64,
                 default_deadline_s: Optional[float] = None,
                 store_base: Optional[str] = None,
                 mesh=None,
                 capacity: Optional[int] = None,
                 max_capacity: int = 65536,
                 age_s: Optional[float] = None,
                 device=None):
        # Shared init: repeated service processes skip XLA compiles.
        from jepsen_tpu.ops.cache import init_compilation_cache
        from jepsen_tpu.serve.scheduler import DEFAULT_AGE_S
        init_compilation_cache(store_base)
        self.max_queue_cells = max_queue_cells
        self.default_deadline_s = default_deadline_s
        self.metrics = Metrics()
        # capacity None = per-bucket derived wgl start capacity (see
        # buckets.wgl_start_capacity; JEPSEN_TPU_WGL_CAPACITY overrides)
        self._sched = Scheduler(self.metrics, mesh=mesh,
                                max_lanes=max_lanes, capacity=capacity,
                                max_capacity=max_capacity,
                                age_s=age_s if age_s is not None
                                else DEFAULT_AGE_S,
                                device=device)
        self._closed = False
        self._lock = threading.Lock()
        self._submitted = 0
        # multi-tenant QoS: quotas/priorities from JEPSEN_TPU_TENANT_*
        # (serve/tenants.py); tenantless submits bypass the table
        self.tenants = TenantTable.from_env()
        self.metrics.bind(self._sched.depth, self._inflight)
        self.metrics.bind_queue(self._sched.occupancy)
        self.metrics.bind_tenants(self.tenants.counts)
        self._sched.start()

    def _inflight(self) -> int:
        # bound gauge; counter() takes the metrics lock briefly, which
        # is safe here because snapshot() samples gauges outside it
        completed = self.metrics.counter("requests-completed")
        return max(0, self._submitted - completed)

    # -- submission -------------------------------------------------------
    def submit(self, history: History, *,
               kind: str = KIND_WGL,
               model: Union[str, Any, None] = None,
               workload: str = "list-append",
               realtime: bool = False,
               consistency_models=None,
               engine: str = "auto",
               deadline_s: Optional[float] = None,
               block: bool = True,
               timeout: Optional[float] = None,
               trace: Optional[Dict[str, Any]] = None,
               tenant: Optional[str] = None,
               **engine_opts) -> Request:
        """Enqueue one history check; returns a :class:`Request` handle
        (``.wait()`` for the verdict).  ``block=False`` raises
        :class:`ServiceSaturated` instead of waiting out backpressure.

        ``trace`` is a propagated trace context (obs.trace wire dict)
        from an upstream hop — the fleet's root request, a remote
        client.  It rides beside the spec (never inside it, so reroute/
        journal round-trips through build_spec don't see it) and makes
        this request a child span of the sender's.  ``tenant`` rides the
        same way: it names the submitting tenant for quota accounting,
        priority class, and the per-tenant metrics cut (serve/tenants.py).

        A request whose deadline expires *while blocked on admission* —
        whether on its tenant's quota or on global backpressure —
        resolves ``unknown`` (the returned handle is already done) rather
        than raising: backpressure is indistinguishable from a slow
        device to the caller, and the deadline contract is "unknown,
        never dropped, never false" on every path — including the
        admission path."""
        if self._closed:
            raise ServiceClosed("service is closed")
        spec = build_spec(kind, model=model, workload=workload,
                          realtime=realtime,
                          consistency_models=consistency_models,
                          engine=engine, **engine_opts)
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        req = Request(history, kind, spec, deadline_s=deadline_s,
                      trace=trace, tenant=tenant,
                      priority=self.tenants.priority(tenant))
        cells = decompose(req)
        # A blocked offer never outlives the deadline: the expiring
        # request must surface unknown, not sit in admission forever.
        rem = req.remaining_s()
        if rem is not None:
            timeout = rem if timeout is None else min(timeout, rem)
        # Tenant quota gate (before global backpressure): a blocked
        # acquire is bounded by the same deadline/timeout as the offer,
        # and the same expiry contract applies — over quota at deadline
        # is unknown, never false, never dropped.
        adm_deadline = req.deadline
        if timeout is not None:
            t_lim = mono_now() + timeout
            adm_deadline = t_lim if adm_deadline is None \
                else min(adm_deadline, t_lim)
        if not self.tenants.acquire(tenant, block=block,
                                    deadline=adm_deadline):
            if req.expired():
                return self._finish_expired(req, cells)
            self.metrics.inc("requests-rejected")
            raise ServiceSaturated(
                f"tenant {tenant!r} at quota; request of "
                f"{len(cells)} cell(s) rejected")
        # the slot frees on *every* finish path (request.finish fires it)
        req.on_finish = lambda t=tenant: self.tenants.release(t)
        if not self._sched.offer(cells, block=block,
                                 max_depth=self.max_queue_cells,
                                 timeout=timeout):
            if req.expired():
                return self._finish_expired(req, cells)
            self.tenants.release(tenant)
            req.on_finish = None
            self.metrics.inc("requests-rejected")
            raise ServiceSaturated(
                f"queue at {self._sched.depth()}/{self.max_queue_cells} "
                f"cells; request of {len(cells)} cell(s) rejected")
        with self._lock:
            self._submitted += 1
        self.metrics.inc("requests-submitted")
        self.metrics.inc("cells-submitted", len(cells))
        return req

    def _finish_expired(self, req: Request, cells) -> Request:
        """The expiry-while-blocked path: resolve every cell unknown and
        hand back a completed request — shared by the tenant-quota and
        global-backpressure admission gates."""
        for c in cells:
            c.result = expired_result(req.kind)
        self.metrics.inc("deadline-expired", len(cells))
        with self._lock:
            self._submitted += 1
        self.metrics.inc("requests-submitted")
        self.metrics.inc("cells-submitted", len(cells))
        self.metrics.inc("cells-completed", len(cells))
        self.metrics.inc("requests-completed")
        req.finish(aggregate(req))
        self.metrics.trace(req)
        return req

    def check(self, history: History, *, timeout: Optional[float] = None,
              **kw) -> Dict[str, Any]:
        """Submit and wait: the synchronous convenience path."""
        return self.submit(history, **kw).wait(timeout=timeout)

    # -- core.analyze routing ---------------------------------------------
    def _routable(self, checker) -> bool:
        """Cheap predicate: would :meth:`try_route_analyze` service this
        checker?  (No submission, no side effects.)"""
        from jepsen_tpu.checker.linearizable import Linearizable
        from jepsen_tpu.independent import IndependentChecker
        inner = checker.inner if isinstance(checker, IndependentChecker) \
            else checker
        if isinstance(inner, Linearizable):
            return (inner._jax_model() is not None
                    and inner.algorithm in (None, "tpu"))
        try:
            from jepsen_tpu.checker.elle import ElleChecker
        except Exception:  # noqa: BLE001
            return False
        return (isinstance(checker, ElleChecker)
                and checker.engine in ("auto", "tpu"))

    def try_route_analyze(self, test, checker, history: History,
                          opts=None) -> Optional[Dict[str, Any]]:
        """Route a test's analysis through the service when its checker
        maps onto a device engine; None = not serviceable (caller runs the
        direct path).  Deadlines reuse the test's ``checker_budget_s`` —
        the same knob check_safe honors — so budget semantics don't fork
        between the direct and serviced paths.

        A composed checker (the shape every suite builds: stats +
        workload + perf) routes per child: serviceable children submit to
        the service, the rest run directly, and Compose's own merge /
        concurrency / budget semantics apply unchanged."""
        from jepsen_tpu.checker.core import Compose
        from jepsen_tpu.checker.linearizable import Linearizable
        if isinstance(checker, Compose):
            if not any(self._routable(c) for c in checker.checkers.values()):
                return None
            shim = Compose(
                {n: _ServiceRouted(self, c) if self._routable(c) else c
                 for n, c in checker.checkers.items()},
                budget_s=checker.budget_s)
            return shim.check(test, history, opts)
        budget = (opts or {}).get("budget_s") \
            or (test or {}).get("checker_budget_s")
        inner = checker
        from jepsen_tpu.independent import IndependentChecker
        if isinstance(checker, IndependentChecker):
            inner = checker.inner
        if isinstance(inner, Linearizable):
            jm = inner._jax_model()
            if jm is None or inner.algorithm not in (None, "tpu"):
                return None
            req = self.submit(history, kind=KIND_WGL, model=jm,
                              deadline_s=budget,
                              **{k: v for k, v in inner.engine_opts.items()
                                 if k in ("capacity", "max_capacity")})
            return req.wait()
        try:
            from jepsen_tpu.checker.elle import ElleChecker
        except Exception:  # noqa: BLE001
            return None
        if isinstance(checker, ElleChecker):
            if checker.engine not in ("auto", "tpu"):
                return None
            req = self.submit(history, kind=KIND_ELLE,
                              workload=checker.workload,
                              realtime=checker.realtime,
                              consistency_models=checker.consistency_models,
                              deadline_s=checker.budget_s or budget)
            res = req.wait()
            from jepsen_tpu.elle import render
            render.write_artifacts(test, res, opts)
            return res
        return None

    def merged_trace(self, request_id) -> Optional[Dict[str, Any]]:
        """The merged trace payload of a completed request (``GET
        /trace/<request-id>`` and ``cli trace`` read this); None when
        the id is unknown or already evicted from the trace ring."""
        return self.metrics.find_trace(request_id)

    # -- lifecycle --------------------------------------------------------
    def queue_depth(self) -> int:
        return self._sched.depth()

    def alive(self) -> bool:
        """Liveness: the device loop is running and admissions are open."""
        return not self._closed and self._sched.alive()

    def ping(self) -> Dict[str, Any]:
        """The heartbeat payload: cheap, lock-light, never dispatches.
        The fleet's health checker and ``GET /healthz`` both read this."""
        from jepsen_tpu.engine.fission import fission_threshold
        return {"alive": self.alive(),
                "queue-depth": self._sched.depth(),
                "inflight-cells": self._sched.inflight(),
                "inflight-requests": self._inflight(),
                # sizing advertisement: the capacity rung past which THIS
                # worker splits instead of escalating (docs/deployment.md
                # "Sizing fleet fission") — the fleet edge reads it to
                # sanity-check per-worker vs fleet-aggregate capacity
                "fission-threshold": fission_threshold()}

    def healthz(self) -> Dict[str, Any]:
        """Single-service health probe (the degenerate one-worker fleet
        view, so load balancers see ONE schema either way)."""
        p = self.ping()
        return {"ok": p["alive"], "workers": [
            {"worker": 0, "alive": p["alive"], "circuit": "closed",
             "queue-depth": p["queue-depth"],
             "inflight-cells": p["inflight-cells"]}]}

    def drain(self, timeout: Optional[float] = None) -> bool:
        return self._sched.drain(timeout=timeout)

    def kill(self) -> list:
        """Abrupt shutdown (worker-crash semantics, no drain): stop the
        loop, evict and return the still-queued cells unresolved.  The
        fleet reroutes them; in-flight requests hang until a sibling's
        hedge resolves them — exactly a crashed process's behaviour."""
        with self._lock:
            self._closed = True
        return self._sched.kill()

    def close(self, timeout: Optional[float] = None) -> bool:
        """Stop admitting, drain the queue (every admitted request still
        resolves), stop the device loop."""
        with self._lock:
            if self._closed:
                return True
            self._closed = True
        return self._sched.stop(drain=True, timeout=timeout)

    def __enter__(self) -> "CheckService":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
