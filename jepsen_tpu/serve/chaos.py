"""The fleet's self-nemesis: fault injection against our own serving tier.

The paper's discipline, turned inward.  We test databases by injecting
faults and checking that histories still verify; the fleet is itself a
distributed system (N workers, a router, retries, a journal), so it gets
the same treatment: a nemesis that kills and pauses workers, delays and
drops their responses, and poisons one worker's device dispatches
mid-campaign — while a parity harness (scripts/fleet_chaos_smoke.py)
asserts the surviving fleet still produces, lane for lane, the verdicts
a cold single-service oracle produces, and recovers within a bounded
time.

Every fault registers its undo in the same :class:`FaultRegistry` the
real nemeses use (nemesis/registry.py): the moment a fault goes live its
heal closure is on the ledger, so a harness that crashes mid-chaos still
heals everything in LIFO order via ``heal_all`` — no test exits with a
worker secretly poisoned.

Faults are implemented by instance-patching the target worker's
scheduler (the in-process analogue of SIGKILL / SIGSTOP / netem delay /
packet drop / disk corruption):

- ``kill_worker``    — abrupt service death, queued cells evicted
  (undo restarts the worker slot);
- ``pause_worker``   — every dispatch stalls ``stall_s`` first (a
  SIGSTOPped or GC-wedged process as seen by its clients);
- ``delay_responses``— verdicts land late by ``delay_s`` (slow network
  path back to the router);
- ``drop_responses`` — a verdict is silently discarded with probability
  ``p`` (lost response packet: the cell completed nowhere, the fleet's
  hedge must cover it);
- ``poison_dispatch``— both device *and* host dispatch tiers raise (bad
  device state / corrupted executable): the worker's cells resolve as
  worker-failure unknowns, the breaker opens, the router reroutes.

Against a :class:`~jepsen_tpu.serve.fleet.ProcFleet` — whose workers
are real processes dialed through per-slot
:class:`~jepsen_tpu.net_proxy.PairProxy` links — a second fault family
targets the *wire itself*, the one layer in-process patching could
never reach:

- ``partition_worker`` — sever the link: live connections RST, new
  dials ECONNREFUSED (the undo heals the listener, and the clients'
  decorrelated reconnect storm is part of what's under test);
- ``cut_links``      — RST live connections mid-frame, listener
  untouched: a frame is torn in flight, the very next dial succeeds;
- ``slow_link``      — per-chunk forwarding stall (netem delay on the
  actual byte stream, not a patched callback).

The scheduler-patching faults require in-process workers and the link
faults require proxied ones; asking the wrong family raises
``ValueError`` with directions rather than silently no-opping.

Undo closures are idempotent; a fault injected on a worker that has
since been restarted heals as a no-op (the patches died with the old
service object).
"""

from __future__ import annotations

import itertools
import random
import time
from typing import Any, Dict, Optional

from jepsen_tpu.nemesis.registry import FaultRegistry
from jepsen_tpu.obs.recorder import RECORDER


def _unpatch(obj: Any, name: str) -> None:
    """Drop an instance-level patch, restoring the class method.
    Idempotent — healing a healed worker is a no-op."""
    obj.__dict__.pop(name, None)


class ChaosNemesis:
    """Fault injector for one :class:`~jepsen_tpu.serve.fleet.Fleet`.

    Usage::

        reg = FaultRegistry()
        chaos = ChaosNemesis(fleet, registry=reg)
        chaos.kill_worker(0)          # mid-campaign
        ...
        chaos.heal("fleet:kill:0")    # restart it
        chaos.heal_all()              # or unwind everything, LIFO
    """

    def __init__(self, fleet, registry: Optional[FaultRegistry] = None,
                 seed: int = 0):
        self.fleet = fleet
        self.registry = registry if registry is not None else FaultRegistry()
        self._rng = random.Random(seed)
        self.injected: Dict[str, str] = {}  # key -> description (ledger)
        self._undos: Dict[str, Any] = {}
        self._cut_seq = itertools.count(1)

    # -- target resolution -------------------------------------------------
    def _sched_of(self, wid: int):
        """The worker's in-process scheduler, for the patching faults.
        A ProcFleet worker's scheduler lives in another PROCESS — patch
        faults cannot reach it; use the link faults instead."""
        svc = self.fleet.workers[wid].service
        sched = getattr(svc, "_sched", None)
        if sched is None:
            raise ValueError(
                f"worker {wid} is out-of-process: its scheduler is not "
                f"patchable from here — use partition_worker / "
                f"cut_links / slow_link to fault its wire instead")
        return sched

    def _proxy_of(self, wid: int):
        """The worker's PairProxy link, for the wire faults."""
        proxy = getattr(self.fleet.workers[wid], "proxy", None)
        if proxy is None:
            raise ValueError(
                f"worker {wid} has no proxy link (in-process fleet) — "
                f"use pause/delay/drop/poison scheduler faults instead")
        return proxy

    # -- bookkeeping ------------------------------------------------------
    def _register(self, key: str, undo, description: str) -> str:
        self.registry.register(key, undo, description)
        self.injected[key] = description
        self._undos[key] = undo
        RECORDER.record("chaos", f"inject:{key}",
                        args={"description": description})
        return key

    def heal(self, key: str) -> bool:
        """Heal one fault now (and resolve its registry entry, so
        heal_all won't re-run its undo)."""
        undo = self._undos.get(key)
        if undo is None or not self.registry.resolve(key):
            return False
        undo()
        RECORDER.record("chaos", f"heal:{key}")
        return True

    def heal_all(self) -> Dict[str, str]:
        return self.registry.heal_all()

    # -- faults -----------------------------------------------------------
    def kill_worker(self, wid: int) -> str:
        """SIGKILL analogue: abrupt worker death.  Queued cells are
        evicted unresolved — the fleet's drivers detect the death and
        reroute; the undo restarts the worker slot with a fresh service."""
        worker = self.fleet.workers[wid]
        worker.kill()
        self.fleet.metrics.inc("chaos-kills")

        def undo():
            self.fleet.restart_worker(wid)

        return self._register(f"fleet:kill:{wid}", undo,
                              f"worker {wid} killed")

    def pause_worker(self, wid: int, stall_s: float = 0.5) -> str:
        """SIGSTOP analogue: every dispatch on this worker stalls
        ``stall_s`` before running.  The worker stays alive (heartbeats
        pass) but its latency EWMA climbs and deadline-risky cells hedge
        to siblings."""
        sched = self._sched_of(wid)
        orig = sched._process

        def paused(cells):
            time.sleep(stall_s)
            return orig(cells)

        sched._process = paused
        self.fleet.metrics.inc("chaos-pauses")
        return self._register(f"fleet:pause:{wid}",
                              lambda: _unpatch(sched, "_process"),
                              f"worker {wid} paused {stall_s}s/dispatch")

    def delay_responses(self, wid: int, delay_s: float = 0.25) -> str:
        """netem-delay analogue: verdicts from this worker land late."""
        sched = self._sched_of(wid)
        orig = sched._finalize

        def delayed(cell, result):
            time.sleep(delay_s)
            return orig(cell, result)

        sched._finalize = delayed
        self.fleet.metrics.inc("chaos-delays")
        return self._register(f"fleet:delay:{wid}",
                              lambda: _unpatch(sched, "_finalize"),
                              f"worker {wid} responses +{delay_s}s")

    def drop_responses(self, wid: int, p: float = 1.0) -> str:
        """Packet-loss analogue: a finished cell's verdict is silently
        discarded with probability ``p`` — as far as anyone can tell, the
        check completed nowhere.  The cell's fleet driver must cover this
        with a hedge (it cannot distinguish a dropped response from a
        slow worker; nobody can — that's the point)."""
        sched = self._sched_of(wid)
        orig = sched._finalize
        rng = self._rng

        def dropped(cell, result):
            if rng.random() < p:
                self.fleet.metrics.inc("chaos-dropped-responses")
                return None
            return orig(cell, result)

        sched._finalize = dropped
        self.fleet.metrics.inc("chaos-drops")
        return self._register(f"fleet:drop:{wid}",
                              lambda: _unpatch(sched, "_finalize"),
                              f"worker {wid} responses dropped p={p}")

    def poison_dispatch(self, wid: int) -> str:
        """Corrupted-device analogue: every dispatch on this worker fails
        at BOTH tiers (device engine and host fallback), so its cells
        resolve as worker-failure unknowns.  This is the fault that
        proves the verdict lattice: the poisoned worker must never turn
        a checkable history into ``false`` — the router reroutes, the
        breaker opens, and the verdict comes from a healthy sibling."""
        sched = self._sched_of(wid)

        def bad_dispatch(*a, **kw):
            raise RuntimeError("chaos: poisoned device dispatch")

        def bad_fallback(*a, **kw):
            raise RuntimeError("chaos: poisoned host fallback")

        sched._dispatch_wgl = bad_dispatch
        sched._dispatch_elle = bad_dispatch
        sched._host_fallback = bad_fallback
        self.fleet.metrics.inc("chaos-poisons")

        def undo():
            _unpatch(sched, "_dispatch_wgl")
            _unpatch(sched, "_dispatch_elle")
            _unpatch(sched, "_host_fallback")

        return self._register(f"fleet:poison:{wid}", undo,
                              f"worker {wid} dispatches poisoned")

    def strip_witness(self, wid: int) -> str:
        """Evidence-loss analogue: this worker's refutations come back
        WITHOUT their witness (a truncated wire frame, an exhausted
        witness budget).  Exercises Hydra's witness-recovery seam: a
        distributed refutation must be re-witnessed on the refuting
        worker — and if that worker then dies, the group must resolve
        unknown, never a fabricated false (serve/fission_plane.py)."""
        sched = self._sched_of(wid)
        orig_wgl = sched._dispatch_wgl
        orig_fb = sched._host_fallback

        def strip(rs):
            for r in rs:
                if isinstance(r, dict) and r.get("valid") is False:
                    r.pop("witness", None)
            return rs

        sched._dispatch_wgl = lambda *a, **kw: strip(orig_wgl(*a, **kw))
        sched._host_fallback = lambda *a, **kw: strip(orig_fb(*a, **kw))
        self.fleet.metrics.inc("chaos-witness-strips")

        def undo():
            _unpatch(sched, "_dispatch_wgl")
            _unpatch(sched, "_host_fallback")

        return self._register(f"fleet:strip-witness:{wid}", undo,
                              f"worker {wid} refutations stripped of "
                              f"witnesses")

    # -- lease faults (Fleetport registries) ------------------------------
    def expire_lease(self, name_or_wid) -> str:
        """Lease-expiry fault: the multi-host eviction path, with no
        local signal anywhere.  Renewals from the target worker are
        blocked (its pushes keep arriving — a blocked renewal must not
        resurrect the lease) and the lease is backdated to expired-now,
        so the fleetport's reaper evicts it on the next sweep exactly as
        if the worker had gone silent.  The worker process itself is
        never touched: it keeps running, correctly, on the far side of a
        revoked membership.  The undo unblocks renewals — the worker's
        own registration loop re-registers it as a new generation."""
        registry = getattr(self.fleet, "registry", None)
        if registry is None or not hasattr(registry, "force_expire"):
            raise ValueError(
                "this fleet has no lease registry (fixed worker set) — "
                "lease faults need a serve/fleetport.py Fleetport; use "
                "kill_worker / partition_worker against fixed fleets")
        if isinstance(name_or_wid, int):
            names = [n for n in registry.names()
                     if getattr(registry.get(n), "wid", None)
                     == name_or_wid]
            if not names:
                raise ValueError(
                    f"no live registered worker holds wid {name_or_wid}")
            name = names[0]
        else:
            name = str(name_or_wid)
        registry.block_renewals(name)
        if not registry.force_expire(name):
            registry.unblock_renewals(name)
            raise ValueError(f"worker {name!r} is not a live member")
        self.fleet.metrics.inc("chaos-lease-expiries")

        def undo():
            registry.unblock_renewals(name)

        return self._register(f"fleet:lease:{name}", undo,
                              f"worker {name} lease force-expired, "
                              f"renewals blocked")

    # -- link faults (ProcFleet wires) ------------------------------------
    def partition_worker(self, wid: int) -> str:
        """Network partition: sever this worker's proxy link.  Live
        connections are RST mid-flight and new dials get ECONNREFUSED —
        the worker process keeps running, correctly, on the far side of
        a dead wire (the distinction the in-process chaos could never
        draw).  The undo heals the listener; what happens next — the
        decorrelated reconnect storm, the breaker's half-open probe, the
        re-sent SUBMITs deduped by id — is the recovery under test."""
        proxy = self._proxy_of(wid)
        proxy.sever()
        self.fleet.metrics.inc("chaos-partitions")
        return self._register(f"fleet:partition:{wid}", proxy.heal,
                              f"worker {wid} link severed")

    def cut_links(self, wid: int) -> str:
        """Mid-frame cut: RST this link's live connections, listener
        untouched.  A frame in flight is torn — the worker's reader sees
        a FrameError and drops only that connection; the client re-dials
        immediately and re-sends unacked SUBMITs under the same ids.
        Repeatable (each cut gets a unique registry key); the undo is a
        no-op — there is nothing to heal, the next dial already works."""
        proxy = self._proxy_of(wid)
        n = proxy.reset_conns()
        self.fleet.metrics.inc("chaos-conn-cuts")
        return self._register(
            f"fleet:cut:{wid}:{next(self._cut_seq)}",
            lambda: None,
            f"worker {wid} link: {n} live connection(s) RST mid-frame")

    def slow_link(self, wid: int, delay_s: float = 0.1) -> str:
        """netem-delay on the actual byte stream: every chunk the proxy
        forwards on this link stalls ``delay_s``.  Unlike
        ``delay_responses`` (a patched callback inside the worker), this
        slows SUBMITs *and* RESULTs *and* heartbeat RPCs — the whole
        wire, both directions, exactly what a congested path does."""
        proxy = self._proxy_of(wid)
        proxy.delay_s = delay_s
        self.fleet.metrics.inc("chaos-slow-links")

        def undo():
            proxy.delay_s = 0.0

        return self._register(f"fleet:slow-link:{wid}", undo,
                              f"worker {wid} link +{delay_s}s/chunk")
