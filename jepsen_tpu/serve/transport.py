"""The fleet's wire protocol: length-prefixed JSON frames over TCP.

PR 7's fleet proved the serving tier survives its own nemesis — but its
workers were in-process replicas, so the one fault class the paper is
*about* (partitions, resets, slow links between real processes on a
real network) was never exercised.  This module is the client half of
putting the submit surface on a socket: a :class:`WireClient` that
dials one out-of-process worker (through a
:class:`~jepsen_tpu.net_proxy.PairProxy` link, so the chaos harness can
sever/shape/tear the wire), and a :class:`ProcWorkerService` facade
that makes the remote worker look exactly like a local
:class:`~jepsen_tpu.serve.service.CheckService` to the fleet's
routing/hedging/journal machinery.  The server half lives in
serve/worker_main.py.

Framing: 4-byte big-endian payload length, then UTF-8 JSON.  Every
frame is a dict with a ``type`` (SUBMIT/ACK/RESULT/STATUS/HEALTHZ/
DRAIN/REPLY/ERROR) and, when it belongs to a call, an ``id``.  A
length prefix over a byte stream makes every failure mode explicit:

- clean EOF *between* frames is a graceful close (``read_frame`` →
  None);
- EOF *inside* a header or payload is a torn frame
  (:class:`FrameError`) — a mid-frame cut, never silently half-parsed;
- a length past :data:`MAX_FRAME_BYTES` is rejected before a byte of
  payload is read (:class:`OversizedFrame`) — a corrupt or hostile
  header cannot make the receiver allocate unbounded memory.

Protocol invariants (the same discipline the rest of serve/ carries):

- **monotonic-deadline propagation** — monotonic clocks do not cross
  process boundaries, so a SUBMIT carries ``deadline-rem-s`` (remaining
  seconds at send time) and the worker re-anchors it on its own
  monotonic clock.  A re-sent SUBMIT re-uses the original remaining
  figure, which only *under*states headroom — the safe direction.  A
  frame that arrives already spent resolves ``unknown`` immediately,
  worker-side, without a dispatch.
- **idempotent request ids** — the worker dedups SUBMIT by id (live
  requests re-attach, finished ones re-deliver the cached RESULT), and
  the client funnels every RESULT through one
  :class:`~jepsen_tpu.serve.request.Request` whose
  ``claim_finish()`` makes duplicate delivery after a reconnect a
  structural no-op: a cell can never double-finish.
- **verdicts degrade, never invent** — every transport failure path
  (dial refused, connection lost mid-wait, torn frame) surfaces as
  ``valid: "unknown"`` with a ``transport ...`` error string the fleet
  classifies as a *worker* failure (reroute to a sibling), never as a
  fabricated ``false``.
- **reconnect storms decorrelate** — re-dials and SUBMIT re-sends back
  off under a control/retry.py :class:`RetryPolicy` with decorrelated
  jitter, so a healed partition is not greeted by every client's
  retries arriving in lockstep.
- **trace context propagates** — a SUBMIT carries a ``trace`` dict
  (obs.trace wire fields: trace-id + parent-span-id); the worker's
  request adopts it and re-anchors span times on its own monotonic
  clock, the ACK echoes it, the RESULT's serve payload carries the
  worker-side spans back, and RPC REPLYs echo any ``trace`` on the
  request frame — so a hedge→reroute across processes assembles into
  one causal tree (docs/observability.md).
"""

from __future__ import annotations

import itertools
import json
import logging
import socket
import threading
import time
from typing import Any, Dict, Optional, Tuple

from jepsen_tpu.clock import mono_now
from jepsen_tpu.control.retry import RetryPolicy
from jepsen_tpu.history import History
from jepsen_tpu.serve.auth import (AuthError, fleet_token, sign_frame,
                                   verify_frame)
from jepsen_tpu.serve.request import Cell, KIND_WGL, Request
from jepsen_tpu.serve.service import ServiceClosed, ServiceSaturated

log = logging.getLogger("jepsen.serve.transport")

#: hard cap on one frame's JSON payload — a 16 MiB history is ~50k ops,
#: far past anything the serve tier admits; bigger lengths are garbage
MAX_FRAME_BYTES = 16 * 1024 * 1024

_HDR = 4  # big-endian payload length

# frame types
F_SUBMIT = "submit"      # client -> worker: one cell-check
F_ACK = "ack"            # worker -> client: SUBMIT admitted (or dup)
F_RESULT = "result"      # worker -> client: the verdict for an id
F_STATUS = "status"      # client -> worker: ping RPC
F_HEALTHZ = "healthz"    # client -> worker: health RPC
F_DRAIN = "drain"        # client -> worker: drain RPC
F_REPLY = "reply"        # worker -> client: RPC reply payload
F_ERROR = "error"        # worker -> client: call failed worker-side
F_TELEMETRY = "telemetry"  # worker -> client: unsolicited metrics push
F_REGISTER = "register"  # worker -> fleetport: join the fleet (host:port,
#                          devices, mesh, capability buckets); REPLY
#                          carries the assigned wid + lease duration


class TransportError(RuntimeError):
    """Base class: something on the wire (not the history) went wrong."""


class FrameError(TransportError):
    """A torn or undecodable frame: EOF inside a header/payload (the
    mid-frame cut signature), non-JSON bytes, or an untyped object."""


class OversizedFrame(TransportError):
    """A frame length past the cap — rejected before reading payload."""


class ConnectionLost(TransportError):
    """The TCP connection died (RST, refused dial, EOF mid-protocol)."""


def encode_frame(frame: Dict[str, Any],
                 max_frame: int = MAX_FRAME_BYTES) -> bytes:
    """Serialize one frame (header + JSON payload), refusing to *send*
    anything the peer would reject as oversized."""
    payload = json.dumps(frame, default=str).encode("utf-8")
    if len(payload) > max_frame:
        raise OversizedFrame(
            f"{len(payload)}-byte frame exceeds the {max_frame}-byte cap")
    return len(payload).to_bytes(_HDR, "big") + payload


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return buf  # short: EOF mid-read
        buf += chunk
    return buf


def read_frame(sock: socket.socket,
               max_frame: int = MAX_FRAME_BYTES) -> Optional[Dict[str, Any]]:
    """Read one frame.  None = clean EOF at a frame boundary (graceful
    close).  Raises :class:`FrameError` for EOF inside a frame (torn),
    :class:`OversizedFrame` for a length past the cap (the payload is
    NOT consumed — the stream is poisoned and must be closed), and lets
    socket errors (RST etc.) propagate as OSError."""
    hdr = _recv_exact(sock, _HDR)
    if not hdr:
        return None
    if len(hdr) < _HDR:
        raise FrameError(f"torn header: {len(hdr)}/{_HDR} bytes then EOF")
    n = int.from_bytes(hdr, "big")
    if n > max_frame:
        raise OversizedFrame(
            f"{n}-byte frame exceeds the {max_frame}-byte cap")
    if n == 0:
        raise FrameError("zero-length frame")
    payload = _recv_exact(sock, n)
    if len(payload) < n:
        raise FrameError(f"torn payload: {len(payload)}/{n} bytes then EOF")
    try:
        obj = json.loads(payload.decode("utf-8"))
    except (UnicodeDecodeError, ValueError) as e:
        raise FrameError(f"undecodable frame: {e}") from e
    if not isinstance(obj, dict) or "type" not in obj:
        raise FrameError("frame is not a typed object")
    return obj


def lite_spec(spec: Dict[str, Any]) -> Dict[str, Any]:
    """Wire-safe spec: the wgl DeviceModel object travels by *name*
    (build_spec on the worker resolves it back via the model registry —
    the same round-trip the fleet journal already proves), everything
    else in a spec is JSON already."""
    out = dict(spec)
    m = out.get("model")
    if m is not None and not isinstance(m, str):
        out["model"] = m.name
    return out


def transport_unknown(reason: str) -> Dict[str, Any]:
    """The verdict a wire failure degrades to.  The ``transport ...``
    error prefix is on the fleet's worker-failure allowlist, so the
    cell reroutes to a sibling — never a fabricated ``false``."""
    return {"valid": "unknown", "analyzer": "transport", "error": reason}


class RemoteCall:
    """Client-side handle for one wire SUBMIT, quacking like the
    :class:`Request` a local ``CheckService.submit`` returns (``done()``
    / ``result`` / ``wait()`` — all the fleet's wait loop touches).

    Backed by a *real* Request with one synthetic cell, so RESULT
    delivery funnels through ``Request.claim_finish()``: the first
    delivery (RESULT frame, duplicate RESULT after a reconnect, or the
    transport-failure path racing a late RESULT) finishes the call and
    every later one is a structural no-op — a cell can never
    double-finish, which is the idempotency half of the wire contract."""

    def __init__(self, history: History, kind: str, spec: Dict[str, Any],
                 deadline_s: Optional[float] = None,
                 trace: Optional[Dict[str, Any]] = None):
        self.request = Request(history, kind, spec, deadline_s=deadline_s,
                               trace=trace)
        self.request.cells = [Cell(self.request, history)]

    def deliver(self, result: Dict[str, Any]) -> bool:
        """Land a verdict; True iff THIS delivery finished the call."""
        res = dict(result or {})
        self.request.cells[0].result = res
        if self.request.claim_finish():
            self.request.finish(dict(res))
            return True
        return False

    def done(self) -> bool:
        return self.request.done()

    @property
    def result(self) -> Optional[Dict[str, Any]]:
        return self.request.result

    def wait(self, timeout: Optional[float] = None) -> Dict[str, Any]:
        return self.request.wait(timeout=timeout)


class _Pending:
    """One in-flight call on a WireClient: a submit (``call`` set) or an
    RPC (``call`` None, reply lands in ``reply``)."""

    __slots__ = ("call", "acked", "reply", "error")

    def __init__(self, call: Optional[RemoteCall] = None):
        self.call = call
        self.acked = threading.Event()
        self.reply: Any = None
        self.error: Optional[Dict[str, Any]] = None


def _raise_remote(err: Dict[str, Any], peer: str) -> None:
    """Re-raise a worker-side ERROR frame as the matching local
    exception class, so the fleet's submit path sees the same
    ServiceSaturated/ServiceClosed it would from an in-process worker."""
    cls = {"ServiceSaturated": ServiceSaturated,
           "ServiceClosed": ServiceClosed,
           "OversizedFrame": OversizedFrame,
           "AuthError": AuthError}.get(
               str(err.get("error-class")), TransportError)
    raise cls(f"{peer}: {err.get('error')}")


_rpc_ids = itertools.count(1)


class WireClient:
    """One client endpoint for one worker: a single TCP connection
    (re-dialed on demand), a reader thread demuxing frames by id, and
    the pending-call table.  Thread-safe; the fleet's many cell-driver
    threads submit through one client per worker slot."""

    def __init__(self, addr: Tuple[str, int], *,
                 policy: Optional[RetryPolicy] = None,
                 name: str = "",
                 connect_timeout_s: float = 5.0,
                 ack_timeout_s: float = 10.0,
                 max_frame: int = MAX_FRAME_BYTES,
                 token: Optional[str] = None):
        self.addr = tuple(addr)
        self.name = name or f"{addr[0]}:{addr[1]}"
        # frame auth: sign everything outbound, verify everything
        # inbound, when a fleet token is configured (serve/auth.py).
        # The token itself never appears in logs or error strings.
        self._token = token if token is not None else fleet_token()
        # Decorrelated jitter: a healed partition must not see every
        # waiting client re-dial and re-send in lockstep.
        self.policy = policy or RetryPolicy(
            tries=3, backoff_s=0.02, max_backoff_s=0.3, decorrelated=True)
        self.connect_timeout_s = connect_timeout_s
        self.ack_timeout_s = ack_timeout_s
        self.max_frame = max_frame
        self._lock = threading.Lock()       # conn + pending table
        self._send_lock = threading.Lock()  # frame writes are atomic
        self._sock: Optional[socket.socket] = None
        self._pending: Dict[str, _Pending] = {}
        self._closed = False
        self.reconnects = 0
        # Watchtower sink: unsolicited TELEMETRY frames are not replies
        # to anything in the pending table — they go to whoever owns
        # this client (the fleet's TelemetryStore).  Settable after
        # construction; None drops pushes on the floor.
        self.on_telemetry = None

    # -- connection --------------------------------------------------------
    def _ensure_conn(self) -> socket.socket:
        with self._lock:
            if self._closed:
                raise TransportError(f"wire client {self.name} is closed")
            if self._sock is not None:
                return self._sock
            # snapshot the target under the lock; retarget() may still
            # swap it mid-dial, in which case this dial's socket loses
            # to the retarget's _conn_lost and the next call re-dials
            addr = self.addr
        # dial OUTSIDE the lock: a slow or refused connect must not
        # stall every thread touching the pending table
        try:
            sock = socket.create_connection(
                addr, timeout=self.connect_timeout_s)
        except OSError as e:
            raise ConnectionLost(
                f"transport connection lost: dial {self.name} failed: "
                f"{e}") from e
        sock.settimeout(None)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        with self._lock:
            if self._closed:
                sock.close()
                raise TransportError(f"wire client {self.name} is closed")
            if self._sock is not None:  # lost a dial race; use the winner
                sock.close()
                return self._sock
            self._sock = sock
            self.reconnects += 1
        threading.Thread(target=self._read_loop, args=(sock,),
                         daemon=True,
                         name=f"wire-read-{self.name}").start()
        return sock

    def _read_loop(self, sock: socket.socket) -> None:
        try:
            while True:
                frame = read_frame(sock, self.max_frame)
                if frame is None:
                    raise ConnectionLost(
                        f"peer {self.name} closed the stream")
                if not verify_frame(frame, self._token):
                    # an unauthenticated frame poisons the stream the
                    # same way a torn one does: drop the connection,
                    # fail over the pending calls (reroute), re-dial
                    raise ConnectionLost(
                        f"transport connection lost: unauthenticated "
                        f"frame from {self.name}")
                self._on_frame(frame)
        except (TransportError, OSError) as e:
            self._conn_lost(sock, e)

    def _on_frame(self, frame: Dict[str, Any]) -> None:
        fid = frame.get("id")
        ftype = frame.get("type")
        if ftype == F_TELEMETRY:
            # push, not reply: never touches the pending table, and a
            # sink failure must not kill the reader thread
            cb = self.on_telemetry
            if cb is not None:
                try:
                    cb(frame.get("payload") or {})
                except Exception:  # noqa: BLE001
                    log.debug("telemetry sink failed", exc_info=True)
            return
        terminal = ftype in (F_RESULT, F_REPLY, F_ERROR)
        with self._lock:
            p = self._pending.get(fid)
            if p is not None and terminal:
                self._pending.pop(fid, None)
        if p is None:
            # unsolicited or duplicate delivery: the call already
            # resolved (or was abandoned) — dropping here is safe
            # because RemoteCall.deliver is itself idempotent
            return
        if ftype == F_ACK:
            p.acked.set()
        elif ftype == F_RESULT:
            if p.call is not None:
                p.call.deliver(frame.get("result") or {})
            p.reply = frame.get("result")
            p.acked.set()
        elif ftype == F_REPLY:
            p.reply = frame.get("payload")
            p.acked.set()
        elif ftype == F_ERROR:
            p.error = frame
            p.acked.set()

    def _conn_lost(self, sock: socket.socket, exc: Exception) -> None:
        """The reader (or a failed send) declares this connection dead:
        acked submits fail over to the fleet (transport-unknown verdicts
        → reroute), RPCs error out, and UNacked submits stay pending —
        their submit loop owns the retry (same id, so the worker dedups
        if the original actually arrived)."""
        failed_calls = []
        failed_rpcs = []
        with self._lock:
            if self._sock is sock:
                self._sock = None
            for fid in list(self._pending):
                p = self._pending[fid]
                if p.call is not None and p.acked.is_set():
                    failed_calls.append(self._pending.pop(fid))
                elif p.call is None:
                    failed_rpcs.append(self._pending.pop(fid))
        try:
            sock.close()
        except OSError:
            pass
        reason = (f"transport connection lost to {self.name}: "
                  f"{type(exc).__name__}: {exc}")
        for p in failed_calls:
            p.call.deliver(transport_unknown(reason))
        for p in failed_rpcs:
            p.error = {"error": reason, "error-class": "ConnectionLost"}
            p.acked.set()

    # -- calls -------------------------------------------------------------
    def submit(self, cid: str, frame: Dict[str, Any], call: RemoteCall,
               deadline_s: Optional[float] = None) -> None:
        """Register and send one SUBMIT, re-sending the SAME id across
        reconnects (the worker dedups) under decorrelated-jitter backoff
        until the worker ACKs.  Raises when every attempt fails — the
        fleet then penalizes this worker's breaker and reroutes."""
        p = _Pending(call=call)
        with self._lock:
            self._pending[cid] = p
        deadline = (mono_now() + deadline_s
                    if deadline_s is not None else None)
        tries = max(1, self.policy.tries)
        prev: Optional[float] = None
        last_err = "never attempted"
        attempted = 0
        try:
            for attempt in range(tries):
                attempted = attempt + 1
                try:
                    self._send(frame)
                    wait = self.ack_timeout_s
                    if deadline is not None:
                        wait = min(wait, max(0.0, deadline - mono_now()))
                    if p.acked.wait(timeout=wait):
                        if p.error is not None:
                            _raise_remote(p.error, self.name)
                        return
                    last_err = f"no ACK within {wait:.1f}s"
                except ConnectionLost as e:
                    last_err = str(e)
                # the ack may have raced the failure we just saw
                if p.acked.is_set():
                    if p.error is not None:
                        _raise_remote(p.error, self.name)
                    return
                if deadline is not None and mono_now() >= deadline:
                    break
                if attempt + 1 < tries:
                    prev = self.policy.delay(attempt, prev=prev)
                    d = prev
                    if deadline is not None:
                        d = min(d, max(0.0, deadline - mono_now()))
                    if d > 0:
                        time.sleep(d)
        except BaseException:
            with self._lock:
                self._pending.pop(cid, None)
            raise
        with self._lock:
            self._pending.pop(cid, None)
        raise ConnectionLost(
            f"transport connection lost: SUBMIT {cid} to {self.name} "
            f"unacknowledged after {attempted} attempt(s): {last_err}")

    def call(self, ftype: str, extra: Optional[Dict[str, Any]] = None,
             timeout_s: float = 5.0) -> Any:
        """One RPC round trip (STATUS/HEALTHZ/DRAIN): send, wait for the
        REPLY payload.  No retries — RPC callers (ping, healthz) treat a
        failure as 'unreachable right now' and say so."""
        fid = f"rpc-{next(_rpc_ids)}"
        frame = {"type": ftype, "id": fid, **(extra or {})}
        p = _Pending(call=None)
        with self._lock:
            self._pending[fid] = p
        try:
            self._send(frame)
            if not p.acked.wait(timeout=timeout_s):
                raise TransportError(
                    f"{ftype} RPC to {self.name} timed out "
                    f"after {timeout_s:.1f}s")
            if p.error is not None:
                _raise_remote(p.error, self.name)
            return p.reply
        finally:
            with self._lock:
                self._pending.pop(fid, None)

    def push(self, frame: Dict[str, Any]) -> None:
        """Send one unsolicited frame (no id, no reply expected) — the
        worker-side registration client uses this for its TELEMETRY
        lease renewals.  Raises :class:`ConnectionLost` when the wire is
        down; the caller owns the re-register/backoff loop."""
        self._send(frame)

    def retarget(self, addr: Tuple[str, int]) -> None:
        """Point future dials at a new (host, port) — a worker that
        respawned on a different address (non-loopback hosts do not get
        the same ephemeral port back).  The live connection, if any, is
        dropped so the very next call dials the new address; its pending
        calls fail over exactly as on a connection loss (acked submits
        degrade to transport-unknown → reroute, unacked ones re-send)."""
        with self._lock:
            if tuple(addr) == self.addr:
                return
            self.addr = tuple(addr)
            # lint: disable=ATOM01(_conn_lost re-validates under the lock: it only clears _sock if it still IS this captured socket, so a connection established in the gap survives)
            sock = self._sock
        if sock is not None:
            self._conn_lost(sock, ConnectionLost(
                f"retargeted to {addr[0]}:{addr[1]}"))

    def target(self) -> Tuple[str, int]:
        """The (host, port) future dials will use, read under the state
        lock — the supervisor compares this against a respawned
        worker's address to decide whether to retarget."""
        with self._lock:
            return tuple(self.addr)

    def _send(self, frame: Dict[str, Any]) -> None:
        sock = self._ensure_conn()
        data = encode_frame(sign_frame(frame, self._token),
                            self.max_frame)
        with self._send_lock:
            try:
                sock.sendall(data)
            except OSError as e:
                raised = e
            else:
                return
        self._conn_lost(sock, raised)
        raise ConnectionLost(
            f"transport connection lost: send to {self.name} failed: "
            f"{raised}")

    # -- lifecycle ---------------------------------------------------------
    def close(self) -> None:
        with self._lock:
            self._closed = True
            sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
            # the reader thread observes the close and fails over any
            # still-pending calls via _conn_lost


_submit_ids = itertools.count(1)


class ProcWorkerService:
    """The CheckService facade over one out-of-process worker: submit /
    ping / healthz / drain / alive / kill / close, all over the wire,
    so :class:`~jepsen_tpu.serve.fleet.Fleet`'s drivers (route, wait,
    hedge, reroute, journal) run against a remote process unchanged.

    The worker's lifecycle belongs to a *launcher* (worker_main's
    SubprocessWorker for real OS processes, ThreadWorker for the
    in-process test tier — both speak the identical protocol over real
    sockets), and the wire runs through a PairProxy link when one is
    given, which is what hands the chaos harness true network faults."""

    def __init__(self, launcher, proxy=None, *,
                 retry_policy: Optional[RetryPolicy] = None,
                 ack_timeout_s: float = 10.0,
                 rpc_timeout_s: float = 5.0,
                 max_frame: int = MAX_FRAME_BYTES,
                 name: str = ""):
        self.launcher = launcher
        self.proxy = proxy
        self.name = name or getattr(launcher, "name", "proc-worker")
        self.rpc_timeout_s = rpc_timeout_s
        self._policy = retry_policy
        self._ack_timeout_s = ack_timeout_s
        self._max_frame = max_frame
        self._ready_lock = threading.Lock()
        self._client: Optional[WireClient] = None
        self._closed = False
        # Watchtower sink for this worker's TELEMETRY pushes; the fleet
        # sets it (wid-tagged) before the first dial.  Read through a
        # closure at dispatch time, so setting it after the wire exists
        # also works.
        self.on_telemetry = None

    def _dispatch_telemetry(self, payload: Dict[str, Any]) -> None:
        cb = self.on_telemetry
        if cb is not None:
            cb(payload)

    def _wire(self) -> WireClient:
        """The (lazily-dialed) client, created once the launcher reports
        ready; when a proxy link exists it is retargeted at the worker's
        real (host, port) and the client dials the PROXY — every byte
        crosses the chaos-controllable wire.  The worker's address comes
        from the launcher (``host`` attribute + ``await_ready`` port),
        never a hardcoded loopback: a worker on another machine — or one
        that respawned onto a new ephemeral port — is dialed where it
        actually listens, and an existing client follows the move via
        ``retarget``."""
        with self._ready_lock:
            if self._closed:
                raise ServiceClosed(f"{self.name} is closed")
            port = self.launcher.await_ready()
            host = getattr(self.launcher, "host", None) or "127.0.0.1"
            addr = (host, port)
            if self.proxy is not None:
                self.proxy.retarget(addr)
                # the proxy listens locally; ITS address is the dial
                addr = ("127.0.0.1", self.proxy.port)
            if self._client is None:
                self._client = WireClient(
                    addr, policy=self._policy, name=self.name,
                    ack_timeout_s=self._ack_timeout_s,
                    max_frame=self._max_frame)
                # lint: disable=RACE01(bound immediately after construction, before the first dial can spawn the reader thread. A racing reader sees None and drops that frame - telemetry is lossy push by contract)
                self._client.on_telemetry = self._dispatch_telemetry
            elif self._client.target() != addr:
                self._client.retarget(addr)
            return self._client

    # -- the CheckService surface -----------------------------------------
    def submit(self, history: History, *,
               kind: str = KIND_WGL,
               deadline_s: Optional[float] = None,
               block: bool = True,
               timeout: Optional[float] = None,
               trace: Optional[Dict[str, Any]] = None,
               **spec) -> RemoteCall:
        """Ship one cell-check over the wire; returns a request-shaped
        handle.  ``block``/``timeout`` are accepted for facade parity —
        remote backpressure surfaces as a worker-side ServiceSaturated
        ERROR frame either way, which the fleet treats exactly like a
        local saturated worker.

        ``trace`` is a propagated trace context: the client-side handle
        adopts it (child of the sender's span) and the SUBMIT frame
        ships the handle's own context, so the worker-side request
        parents to this hop — the tree stays connected across the
        wire."""
        if self._closed:
            raise ServiceClosed(f"{self.name} is closed")
        client = self._wire()
        spec_l = lite_spec(spec)
        call = RemoteCall(history, kind, spec_l, deadline_s=deadline_s,
                          trace=trace)
        cid = f"{self.name}.{next(_submit_ids)}.{call.request.id}"
        frame = {"type": F_SUBMIT, "id": cid, "kind": kind,
                 "spec": spec_l, "deadline-rem-s": deadline_s,
                 "trace": call.request.trace_context(),
                 "ops": [op.to_dict() for op in history]}
        client.submit(cid, frame, call, deadline_s=deadline_s)
        return call

    def check(self, history: History, *,
              timeout: Optional[float] = None, **kw) -> Dict[str, Any]:
        return self.submit(history, **kw).wait(timeout=timeout)

    def ping(self) -> Dict[str, Any]:
        """Heartbeat.  ``alive`` reports the *process* (a partitioned
        worker is alive but unreachable — the breaker, not the
        supervisor, owns that distinction); ``reachable`` reports the
        wire."""
        if not self.launcher.alive():
            return {"alive": False, "reachable": False,
                    "queue-depth": None, "inflight-cells": None}
        try:
            payload = self._wire().call(F_STATUS,
                                        timeout_s=self.rpc_timeout_s)
            return {**(payload or {}), "alive": self.launcher.alive(),
                    "reachable": True}
        except Exception as e:  # noqa: BLE001 — unreachable ≠ dead
            return {"alive": self.launcher.alive(), "reachable": False,
                    "queue-depth": None, "inflight-cells": None,
                    "error": f"{type(e).__name__}: {e}"}

    def metrics_snapshot(self) -> Optional[Dict[str, Any]]:
        """The remote worker's full ``Metrics.snapshot()`` over the
        STATUS frame (``metrics: true`` flag) — the fleet-wide scrape
        reads this to merge per-worker counters and histograms into one
        ``/metrics`` document.  None when the worker is unreachable (a
        scrape never fails because one worker was partitioned)."""
        if not self.launcher.alive():
            return None
        try:
            payload = self._wire().call(F_STATUS, {"metrics": True},
                                        timeout_s=self.rpc_timeout_s)
        except Exception:  # noqa: BLE001 — unreachable ≠ dead
            return None
        snap = (payload or {}).get("metrics")
        return snap if isinstance(snap, dict) else None

    def healthz(self) -> Dict[str, Any]:
        """The remote worker's own healthz, for deep fleet aggregation."""
        try:
            payload = self._wire().call(F_HEALTHZ,
                                        timeout_s=self.rpc_timeout_s)
            return dict(payload or {"ok": False})
        except Exception as e:  # noqa: BLE001
            return {"ok": False, "reachable": False,
                    "error": f"{type(e).__name__}: {e}"}

    def set_recorder(self, on: bool) -> bool:
        """Arm/disarm the remote worker's flight recorder over the
        STATUS frame (the runtime half of ``POST /recorder``).  False
        when the worker is unreachable — arming is best-effort, like
        every other scrape-path RPC."""
        try:
            self._wire().call(F_STATUS, {"recorder": bool(on)},
                              timeout_s=self.rpc_timeout_s)
            return True
        except Exception:  # noqa: BLE001 — unreachable ≠ dead
            return False

    def remote_status(self) -> Dict[str, Any]:
        """Launcher-side facts (pid/port/log) for fleet_status()."""
        st = getattr(self.launcher, "status", None)
        out = dict(st() if st is not None else {})
        client = self._client
        if client is not None:
            out["reconnects"] = client.reconnects
        return out

    def drain(self, timeout: Optional[float] = None) -> bool:
        budget = 30.0 if timeout is None else float(timeout)
        try:
            ok = self._wire().call(F_DRAIN, {"timeout-s": timeout},
                                   timeout_s=budget + 5.0)
            return bool(ok)
        except Exception:  # noqa: BLE001 — an unreachable worker did
            return False   # not drain
    def queue_depth(self) -> int:
        p = self.ping()
        return int(p.get("queue-depth") or 0)

    def alive(self) -> bool:
        return not self._closed and self.launcher.alive()

    def kill(self) -> list:
        """Crash semantics: SIGKILL the worker's process group, drop the
        wire.  Worker-side queued cells die with it — the fleet's
        drivers see the death and reroute, exactly the in-process
        contract (which returns the evicted cells; a killed *process*
        cannot, so this returns [])."""
        with self._ready_lock:
            self._closed = True
            client = self._client
        if client is not None:
            client.close()
        self.launcher.kill()
        return []

    def close(self, timeout: Optional[float] = None) -> bool:
        """Graceful shutdown: remote drain, then SIGTERM (the worker
        closes its service cleanly), escalating to SIGKILL on a hang."""
        with self._ready_lock:
            if self._closed:
                return True
            self._closed = True
            client = self._client
        ok = True
        if client is not None:
            budget = 30.0 if timeout is None else float(timeout)
            try:
                ok = bool(client.call(F_DRAIN, {"timeout-s": timeout},
                                      timeout_s=budget + 5.0))
            except Exception:  # noqa: BLE001 — unreachable: not drained
                ok = False
            client.close()
        self.launcher.terminate(timeout_s=10.0)
        return ok

    def __enter__(self) -> "ProcWorkerService":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
