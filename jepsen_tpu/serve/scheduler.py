"""The continuous-batch scheduler: one device loop draining a cell queue.

The device is a single serially-dispatched resource, so the scheduler is
one thread: each iteration it picks the most urgent shape bucket
(tenant priority class, then earliest deadline, FIFO within a deadline
class), packs up to a lane
bucket's worth of that bucket's cells into ONE vmapped dispatch — wgl
cells through parallel.batch.check_batch, elle cells through
elle_tpu.engine.check_batch — and loops.  New cells admitted while a
dispatch is on the device are seen at the very next iteration: requests
continuously join batches instead of waiting for a convoy to finish
(continuous batching, the same scheduler shape as an inference server).

Guarantees:

- cells whose request deadline has already passed are resolved
  ``unknown`` (never dispatched, never ``false``) — deadline semantics
  match check_safe's budget degradation;
- a device failure downgrades the affected cells to the host tier
  (wgl_cpu / elle engine="cpu") with a ``fallback`` annotation, exactly
  like checker.linearizable's degradation chain — a device error never
  decides a verdict;
- lane padding (to power-of-two lane buckets, for engine-cache
  stability) is measured: every dispatch reports used vs padded lanes to
  the metrics registry.
"""

from __future__ import annotations

import logging
import os
import threading
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from jepsen_tpu.obs.recorder import RECORDER
from jepsen_tpu.serve import buckets
from jepsen_tpu.serve.aggregate import aggregate, expired_result
from jepsen_tpu.serve.metrics import mono_now
from jepsen_tpu.serve.request import Cell, KIND_ELLE, KIND_WGL

log = logging.getLogger("jepsen.serve")

#: a bucket whose head cell has queued this long outranks deadline order
DEFAULT_AGE_S = 5.0


class Scheduler:
    def __init__(self, metrics, mesh=None, max_lanes: int = 64,
                 capacity: Optional[int] = None, max_capacity: int = 65536,
                 age_s: Optional[float] = DEFAULT_AGE_S, device=None):
        self.metrics = metrics
        self.mesh = mesh
        # A fleet worker's device pin: dispatches run under
        # jax.default_device(device) so N in-process workers partition the
        # host's devices instead of convoying on device 0.  None = the
        # backend default (the solo-service behaviour).
        self.device = device
        self.max_lanes = max(1, min(max_lanes, buckets.MAX_LANE_BUCKET))
        # None = derive the start capacity from each dispatch's bucket
        # shape (buckets.wgl_start_capacity); an int pins the old fixed
        # knob for every dispatch.
        self.capacity = capacity
        self.max_capacity = max_capacity
        self.age_s = age_s
        self._groups: Dict[Tuple, deque] = {}
        self._depth = 0
        self._seq = 0               # admission order (FIFO tiebreak)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._stop = False
        self._inflight = 0
        self._idle_listeners: List[Any] = []
        # monitor lane: thunks the streaming monitors want run on the
        # device-loop thread, between batch dispatches — the monitor's
        # epoch-advance chunks share the device with request traffic
        # without a second dispatch thread racing it (see monitor_call)
        self._monitor_lane: deque = deque()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="serve-scheduler")
        self._started = False

    # -- queue ------------------------------------------------------------
    def start(self) -> None:
        if not self._started:
            self._started = True
            self._thread.start()

    def offer(self, cells: List[Cell], block: bool, max_depth: int,
              timeout: Optional[float]) -> bool:
        """Admit a request's cells (all or nothing).  Blocks while the
        queue is above ``max_depth`` (backpressure); False = rejected."""
        deadline = (mono_now() + timeout) if timeout is not None \
            else None
        with self._cond:
            while not self._stop and self._depth + len(cells) > max_depth:
                if not block:
                    return False
                rem = None if deadline is None \
                    else deadline - mono_now()
                if rem is not None and rem <= 0:
                    return False
                if not self._cond.wait(timeout=rem if rem is not None
                                       else 0.1):
                    return False
            if self._stop:
                return False
            t_in = mono_now()
            for c in cells:
                c.seq = self._seq = self._seq + 1
                c.enqueued = t_in
                self._groups.setdefault(c.bucket, deque()).append(c)
            self._depth += len(cells)
            self._cond.notify_all()
            return True

    def depth(self) -> int:
        with self._lock:
            return self._depth

    def occupancy(self) -> Dict[str, Any]:
        """The autoscaler's input signals as first-class data: per-bucket
        queue depth and the oldest head wait-age (the same age the aged
        tier of :meth:`_take_group` acts on).  Rides in the metrics
        snapshot — and therefore in every telemetry push frame — via
        Metrics.bind_queue."""
        now = mono_now()
        with self._lock:
            buckets: Dict[str, int] = {}
            oldest = 0.0
            for key, dq in self._groups.items():
                if not dq:
                    continue
                buckets[str(key)] = len(dq)
                oldest = max(oldest, now - dq[0].enqueued)
            return {"depth": self._depth, "buckets": buckets,
                    "oldest-wait-s": round(oldest, 6)}

    def add_idle_listener(self, fn) -> None:
        """Drain hook: ``fn()`` fires on the device-loop thread (outside
        the lock) each time the scheduler goes idle — queue empty and
        nothing in flight.  The wire worker (serve/worker_main.py) stamps
        idle-age into its STATUS replies this way instead of polling the
        condition variable; a listener must be cheap and must not block,
        since it runs between dispatches."""
        with self._lock:
            self._idle_listeners.append(fn)

    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def alive(self) -> bool:
        """Is the device loop still able to make progress?  False once the
        thread died (a crash the loop's own try/except failed to contain)
        or a stop/kill landed — the fleet's heartbeat probes this."""
        with self._lock:
            return self._alive_locked()

    def _alive_locked(self) -> bool:
        """:meth:`alive` for callers already inside the scheduler lock
        (monitor_call's admission check)."""
        return (self._started and not self._stop
                and self._thread.is_alive())

    def monitor_call(self, fn, timeout: float = 300.0) -> Any:
        """Run ``fn()`` on the device-loop thread, between batch
        dispatches, and return its result (re-raising its exception).

        The streaming monitors (engine/stream.py) route their epoch
        chunk dispatches here when a service owns the device: the device
        is one serially-dispatched resource, so monitor work must
        interleave with request batches on the ONE loop thread instead
        of racing them from the monitor's thread.  Monitor thunks run
        before the next batch pick — an epoch chunk is small (one
        bucketed dispatch), so lane traffic cannot starve requests.

        When the loop is not running (never started, stopped, crashed),
        ``fn`` runs inline on the caller — the monitor still advances,
        just without interleaving.  The generous default timeout covers
        a first-call XLA compile landing in front of the thunk."""
        box: Dict[str, Any] = {}
        done = threading.Event()
        with self._cond:
            live = self._alive_locked()
            if live:
                self._monitor_lane.append((fn, box, done))
                self._cond.notify_all()
        if not live:
            return fn()
        if not done.wait(timeout):
            raise TimeoutError("monitor-lane dispatch timed out")
        if "error" in box:
            raise box["error"]
        return box["result"]

    def _drain_monitor_lane(self) -> List[Tuple[Any, Dict[str, Any],
                                                threading.Event]]:
        """Snapshot-and-clear the lane (caller holds the lock)."""
        lane = list(self._monitor_lane)
        self._monitor_lane.clear()
        return lane

    def evict_pending(self) -> List[Cell]:
        """Drain hook: pop every *queued* (not yet dispatched) cell and
        hand it back to the caller unresolved.  The fleet uses this to
        decommission a worker — its queue moves to a sibling instead of
        waiting out the corpse.  Cells already in a device dispatch are
        not evictable; they either resolve normally or hang with the
        worker (the router's hedge covers that window)."""
        with self._cond:
            out: List[Cell] = []
            for dq in self._groups.values():
                out.extend(dq)
                dq.clear()
            self._groups.clear()
            self._depth = 0
            self._cond.notify_all()
        return sorted(out, key=lambda c: c.seq)

    def kill(self) -> List[Cell]:
        """Abrupt death (the chaos harness's worker-crash fault): stop the
        loop WITHOUT draining and evict the queue.  In-flight dispatches
        may still finalize (a real crash can land before or after the ack;
        both must be survivable) — everything still queued is returned
        unresolved, exactly what a restart would recover from the
        journal."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        return self.evict_pending()

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Wait until the queue is empty and no dispatch is in flight."""
        deadline = (mono_now() + timeout) if timeout is not None \
            else None
        with self._cond:
            while self._depth > 0 or self._inflight:
                rem = None if deadline is None \
                    else deadline - mono_now()
                if rem is not None and rem <= 0:
                    return False
                self._cond.wait(timeout=rem if rem is not None else 0.1)
            return True

    def stop(self, drain: bool = True,
             timeout: Optional[float] = None) -> bool:
        """Stop the loop; with ``drain`` (default) the queue is emptied
        first — every admitted request still gets its verdict."""
        ok = self.drain(timeout) if drain else True
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._started:
            self._thread.join(timeout=30.0)
        return ok

    # -- the device loop --------------------------------------------------
    def _mega_eligible(self, bucket: Tuple) -> bool:
        """Small-bucket wgl cells route through the megabatch refill path
        (parallel.megabatch) when it is enabled: their steady-state
        traffic is thousands of short per-key lanes, exactly the shape
        the continuous-refill pipeline wins on.  Large event buckets and
        mesh-sharded dispatches keep the barrier path.

        Which model families qualify is the carry-descriptor registry
        (``engine.plugins.has_carry_descriptor``) — any family that
        registered its packed-carry descriptor bin-packs, not a
        hard-coded register list.  A family without one is never
        rejected: it simply falls back to the ``check_batch`` barrier
        path this method gates."""
        from jepsen_tpu.parallel.megabatch import megabatch_enabled
        if not (self.mesh is None and megabatch_enabled()
                and len(bucket) >= 4 and bucket[0] == KIND_WGL
                and bucket[2] <= buckets.MEGA_EVENTS_MAX):
            return False
        from jepsen_tpu.engine.plugins import has_carry_descriptor
        ident = bucket[1]
        name = ident[0] if isinstance(ident, tuple) and ident else ident
        return has_carry_descriptor(str(name))

    def _group_limit(self, bucket: Tuple) -> int:
        """Lanes to pop for one dispatch of this bucket: the megabatch
        path packs up to the mega lane ladder (grouped vmaps reusing one
        executable), the barrier path stays at max_lanes."""
        if self._mega_eligible(bucket):
            return buckets.mega_lane_bucket(buckets.MAX_MEGA_LANES)
        return self.max_lanes

    def _take_group(self) -> List[Cell]:
        """Pop the most urgent bucket's head cells (up to the bucket's
        group limit — max_lanes, or the mega lane ladder for megabatch-
        eligible buckets).

        Priority-then-deadline with aging: the plain pick is the
        smallest (-priority, deadline, seq) head — a tenant's priority
        class outranks deadline order (serve/tenants.py), deadline
        orders within a class — but a steady stream of urgent cells
        could then starve a far-deadline bucket forever — its compiled
        engine goes cold and the eventual dispatch pays a recompile.  So
        any bucket whose head has been queued longer than ``age_s``
        enters an aged tier that outranks deadline order (oldest wait
        first); picks decided by the aged tier are counted as
        ``aged_picks`` in the metrics snapshot."""
        best = None
        aged = None
        now = mono_now()
        for key, dq in self._groups.items():
            if not dq:
                continue
            k = dq[0].sort_key()
            if best is None or k < best[0]:
                best = (k, key)
            if self.age_s is not None:
                waited = now - dq[0].enqueued
                if waited >= self.age_s and (aged is None
                                             or waited > aged[0]):
                    aged = (waited, key)
        if best is None:
            return []
        if aged is not None and aged[1] != best[1]:
            best = (None, aged[1])
            self.metrics.inc("aged_picks")
        dq = self._groups[best[1]]
        limit = self._group_limit(best[1])
        out = []
        while dq and len(out) < limit:
            out.append(dq.popleft())
        if not dq:
            del self._groups[best[1]]
        self._depth -= len(out)
        return out

    def _loop(self) -> None:
        while True:
            with self._cond:
                while (self._depth == 0 and not self._monitor_lane
                       and not self._stop):
                    self._cond.wait(timeout=0.1)
                if self._stop and self._depth == 0:
                    # waiters must not hang on a dead loop: fail the
                    # lane so monitor_call raises instead of timing out
                    for _fn, box, done in self._drain_monitor_lane():
                        box["error"] = RuntimeError("scheduler stopped")
                        done.set()
                    return
                lane = self._drain_monitor_lane()
                cells = self._take_group()
                self._inflight = len(cells)
                self._cond.notify_all()  # depth dropped: wake producers
            # monitor thunks run outside the lock, before the batch —
            # an epoch chunk ahead of a dispatch, never inside either
            for fn, box, done in lane:
                try:
                    box["result"] = fn()
                    self.metrics.inc("monitor-epoch-dispatches")
                except Exception as e:  # noqa: BLE001 — caller re-raises
                    box["error"] = e
                finally:
                    done.set()
            if not cells:
                continue
            try:
                self._process(cells)
            except Exception:  # noqa: BLE001 — the loop must survive
                log.exception("scheduler dispatch failed terminally")
                for c in cells:
                    if c.result is None:
                        self._finalize(c, {
                            "valid": "unknown", "analyzer": "serve",
                            "error": "scheduler dispatch crashed"})
            finally:
                with self._cond:
                    self._inflight = 0
                    self._cond.notify_all()
                    listeners = (list(self._idle_listeners)
                                 if self._depth == 0 else [])
                # idle listeners fire outside the lock: a slow or buggy
                # listener must neither wedge producers nor kill the loop
                for fn in listeners:
                    try:
                        fn()
                    except Exception:  # noqa: BLE001
                        log.exception("scheduler idle listener failed")

    def _process(self, cells: List[Cell]) -> None:
        live: List[Cell] = []
        for c in cells:
            if c.request.expired():
                self.metrics.inc("deadline-expired")
                self._finalize(c, expired_result(c.request.kind))
            else:
                live.append(c)
        if not live:
            return
        for c in live:
            c.request.span("pack")
        t0 = mono_now()
        lanes = [c.history for c in live]
        kind = live[0].request.kind
        mega = kind == KIND_WGL and self._mega_eligible(live[0].bucket)
        if mega:
            # The megabatch packer buckets and pads lanes internally
            # (its width ladder is part of the engine-cache key); no
            # caller-side lane padding needed.
            pad = len(lanes)
            padded = lanes
        else:
            pad = buckets.lane_bucket(len(lanes), self.max_lanes)
            padded = lanes + [lanes[0]] * (pad - len(lanes))
        for c in live:
            c.request.span("dispatch")

        def run_dispatch():
            if kind == KIND_WGL:
                return self._dispatch_wgl(live, padded, mega=mega)
            return self._dispatch_elle(live, padded)

        try:
            if self.device is not None:
                import jax
                with jax.default_device(self.device):
                    rs = run_dispatch()
            else:
                rs = run_dispatch()
        except Exception as e:  # noqa: BLE001 — device trouble, degrade
            log.warning("device dispatch failed (%s: %s); host fallback "
                        "for %d cell(s)", type(e).__name__, e, len(live))
            self.metrics.inc("host-fallbacks", len(live))
            rs = self._host_fallback(live, e)
        dt = mono_now() - t0
        self.metrics.dispatch(len(live), pad, dt)
        RECORDER.record(
            "dispatch", f"batch:{kind}:x{len(live)}",
            dur_s=dt,
            trace_id=live[0].request.trace_id,
            span_id=live[0].request.span_id,
            args={"lanes": len(live), "pad": pad, "mega": mega})
        for c, r in zip(live, rs):
            self._finalize(c, r)

    def _start_capacity(self, live: List[Cell], ev_bucket: int,
                        w_bucket: int) -> int:
        """Resolve the wgl start capacity: per-request ``capacity`` engine
        opts win, then the ``JEPSEN_TPU_WGL_CAPACITY`` env override, then
        a service-level fixed knob, then the bucket-shape derivation
        (buckets.wgl_start_capacity — the default).  Overflowing lanes
        still escalate automatically, so this only sets where the ladder
        starts."""
        explicit = [int(s.request.spec["capacity"]) for s in live
                    if s.request.spec.get("capacity") is not None]
        if explicit:
            return max(explicit)
        env = os.environ.get("JEPSEN_TPU_WGL_CAPACITY")
        if env:
            return max(1, int(env))
        if self.capacity is not None:
            return int(self.capacity)
        return buckets.wgl_start_capacity(ev_bucket, w_bucket)

    def _dispatch_wgl(self, live: List[Cell], padded: List[Any],
                      mega: bool = False) -> List[Dict[str, Any]]:
        from jepsen_tpu.parallel.batch import _batch_chunk, check_batch
        spec0 = live[0].request.spec
        _, _, ev_bucket, w_bucket = live[0].bucket
        cap = self._start_capacity(live, ev_bucket, w_bucket)
        max_cap = max(int(s.request.spec.get("max_capacity",
                                             self.max_capacity))
                      for s in live)
        if mega:
            from jepsen_tpu.parallel.megabatch import check_megabatch
            self.metrics.inc("megabatch-dispatches")
            self.metrics.inc("megabatch-lanes", len(padded))
            rs = check_megabatch(
                spec0["model"], padded, capacity=cap,
                max_capacity=max_cap, window_floor=w_bucket,
                ev_floor=ev_bucket,
                lanes=buckets.mega_lane_bucket(len(padded)))
        else:
            rs = check_batch(spec0["model"], padded, mesh=self.mesh,
                             capacity=cap, max_capacity=max_cap,
                             chunk=_batch_chunk(len(padded), ev_bucket),
                             window_floor=w_bucket,
                             fission=spec0.get("fission"))
        return [self._explain_witness(c, r) for c, r in zip(live, rs)]

    def _explain_witness(self, cell: Cell, r):
        """Device lanes flag, the CPU recovers (engine.witness): the
        batched engines refute with the op alone, so when the submitter
        asked for an explanation the knossos-style witness is re-derived
        here, before the verdict leaves the dispatch path — the same
        discipline wgl_tpu.check applies directly.  The fission plane's
        witness-recovery re-checks depend on this seam: an explain=True
        re-submit to the refuting worker must come back witnessed.  A
        budget overrun degrades the witness to an error note, never the
        earned verdict."""
        if not (isinstance(r, dict) and r.get("valid") is False
                and "witness" not in r and isinstance(r.get("op"), dict)
                and cell.request.spec.get("explain")):
            return r
        from jepsen_tpu.engine.witness import cpu_witness
        model = cell.request.spec.get("model")
        idx = r["op"].get("index")
        failed = next((o for o in cell.history if o.index == idx), None)
        if model is None or failed is None:
            return r
        out = dict(r)
        # witness: CPU re-derivation on the refuted prefix rides the flagged op
        out["witness"] = cpu_witness(model, cell.history, failed)
        return out

    def _dispatch_elle(self, live: List[Cell],
                       padded: List[Any]) -> List[Dict[str, Any]]:
        from jepsen_tpu.elle_tpu.engine import check_batch
        spec0 = live[0].request.spec
        (_, _, n_bucket) = live[0].bucket
        remaining = [c.request.remaining_s() for c in live]
        known = [r for r in remaining if r is not None]
        budget = max(0.0, min(known)) if known else None
        rs = check_batch(padded,
                         workload=spec0.get("workload", "list-append"),
                         realtime=bool(spec0.get("realtime", False)),
                         consistency_models=spec0.get("consistency_models"),
                         engine=spec0.get("engine", "auto"),
                         mesh=self.mesh, budget_s=budget,
                         n_pad_floor=n_bucket)
        return rs[:len(live)]

    def _host_fallback(self, live: List[Cell],
                       exc: Exception) -> List[Dict[str, Any]]:
        """Per-cell host-tier re-check after a device dispatch failure."""
        out = []
        chain = [{"solver": f"{live[0].request.kind}-serve",
                  "error": str(exc), "error-type": type(exc).__name__}]
        for c in live:
            try:
                if c.request.kind == KIND_WGL:
                    from jepsen_tpu.checker import wgl_cpu
                    cm = c.request.spec["model"].cpu_model()
                    if cm is None:
                        r = {"valid": "unknown",
                             "error": "device failed; no host-tier model"}
                    else:
                        r = wgl_cpu.check(cm, c.history)
                else:
                    from jepsen_tpu.elle_tpu.engine import check_batch
                    r = check_batch(
                        [c.history], engine="cpu",
                        workload=c.request.spec.get("workload",
                                                    "list-append"),
                        realtime=bool(c.request.spec.get("realtime",
                                                         False)),
                        consistency_models=c.request.spec.get(
                            "consistency_models"),
                        budget_s=c.request.remaining_s())[0]
            except Exception as e2:  # noqa: BLE001
                r = {"valid": "unknown",
                     "error": f"device and host tiers both failed: "
                              f"{exc}; {e2}"}
            r.setdefault("fallback", {"from": f"{c.request.kind}-device",
                                      "to": "host", "error": str(exc),
                                      "error-type": type(exc).__name__})
            r["fallback-chain"] = chain
            out.append(r)
        return out

    def _finalize(self, cell: Cell, result: Dict[str, Any]) -> None:
        cell.result = result
        self.metrics.inc("cells-completed")
        req = cell.request
        if not req.claim_finish():
            return
        req.finish(aggregate(req))
        self.metrics.inc("requests-completed")
        self.metrics.trace(req)
