"""Multi-tenant QoS: per-tenant admission quotas and deadline priorities.

One tenant's 10k-op monster must not starve everyone's 200-op streams.
The table holds the *policy* — how many requests a tenant may have open
at once (quota) and how urgently its cells sort in the scheduler's
group pick (priority) — and the *accounting* — open requests, admitted,
quota rejections.  Admission sites (CheckService.submit, Fleet.submit)
gate on :meth:`TenantTable.acquire` before offering work, and release
on request finish, so the quota bounds a tenant's share of the queue
end to end.

Invariants, inherited from the admission plane:

- an over-quota *blocked* submit whose deadline expires resolves
  ``unknown`` (never ``false``, never dropped) — the caller reuses the
  existing expiry-while-blocked path;
- an over-quota non-blocking submit raises ``ServiceSaturated`` with a
  quota reason, counted per tenant;
- the table never holds token material — tenant *secrets* live only in
  serve/auth.py and are resolved at verification time.

Configuration (all read at construction; programmatic
:meth:`configure` overrides):

- ``JEPSEN_TPU_TENANT_QUOTA`` — default max open requests for any named
  tenant (unset = unlimited);
- ``JEPSEN_TPU_TENANT_QUOTA_<NAME>`` — per-tenant quota override;
- ``JEPSEN_TPU_TENANT_PRIORITY_<NAME>`` — integer priority class
  (higher = more urgent; default 0);
- ``JEPSEN_TPU_TENANT_SLO_P99_US_<NAME>``,
  ``JEPSEN_TPU_TENANT_SLO_UNKNOWN_RATE_<NAME>``,
  ``JEPSEN_TPU_TENANT_SLO_WINDOW_S_<NAME>`` — per-tenant SLO ceilings
  and burn window, consumed by obs/slo.py tenant specs.

``<NAME>`` is the tenant name upper-cased with ``-`` → ``_``.  Requests
with no tenant (single-tenant deployments) bypass the table entirely —
unlimited, priority 0, exactly the pre-tenancy behavior.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from jepsen_tpu.serve.metrics import mono_now

_QUOTA_ENV = "JEPSEN_TPU_TENANT_QUOTA"
_PRIORITY_ENV = "JEPSEN_TPU_TENANT_PRIORITY"
_SLO_ENVS = {"p99_us": "JEPSEN_TPU_TENANT_SLO_P99_US",
             "unknown_rate": "JEPSEN_TPU_TENANT_SLO_UNKNOWN_RATE",
             "window_s": "JEPSEN_TPU_TENANT_SLO_WINDOW_S"}


def _env_name(tenant: str) -> str:
    return tenant.upper().replace("-", "_")


@dataclass
class TenantSpec:
    """Policy for one tenant.  ``quota`` is max open requests (None =
    unlimited); ``priority`` is an integer class, higher = more urgent;
    ``slo`` holds optional per-tenant ceilings (p99_us, unknown_rate,
    window_s) for obs/slo.py."""

    name: str
    quota: Optional[int] = None
    priority: int = 0
    slo: Dict[str, float] = field(default_factory=dict)


class TenantTable:
    """Quota/priority policy plus open-request accounting, shared by
    every admission site of one service or fleet."""

    def __init__(self, specs: Optional[Dict[str, TenantSpec]] = None,
                 default_quota: Optional[int] = None):
        self._specs: Dict[str, TenantSpec] = dict(specs or {})
        self._default_quota = default_quota
        self._open: Dict[str, int] = {}
        self._admitted: Dict[str, int] = {}
        self._rejected: Dict[str, int] = {}
        self._cond = threading.Condition(threading.Lock())

    # -- configuration ----------------------------------------------------
    @classmethod
    def from_env(cls, env: Optional[Dict[str, str]] = None) -> "TenantTable":
        """Parse tenant policy from the environment.  Tenant names are
        discovered from issued tokens (auth.tenant_names) and from any
        per-tenant env key; unknown tenants stay unlimited/priority 0."""
        e = env if env is not None else os.environ
        default_quota = _int_or_none(e.get(_QUOTA_ENV))
        names = set()
        from jepsen_tpu.serve.auth import tenant_names
        names.update(tenant_names(env))
        prefixes = ([_QUOTA_ENV + "_", _PRIORITY_ENV + "_"]
                    + [v + "_" for v in _SLO_ENVS.values()])
        for key in e:
            for p in prefixes:
                if key.startswith(p):
                    names.add(key[len(p):].lower().replace("_", "-"))
        specs: Dict[str, TenantSpec] = {}
        for name in sorted(names):
            n = _env_name(name)
            slo = {}
            for field_name, env_base in _SLO_ENVS.items():
                v = _float_or_none(e.get(f"{env_base}_{n}"))
                if v is not None:
                    slo[field_name] = v
            specs[name] = TenantSpec(
                name=name,
                quota=_int_or_none(e.get(f"{_QUOTA_ENV}_{n}"),
                                   default_quota),
                priority=_int_or_none(e.get(f"{_PRIORITY_ENV}_{n}"), 0) or 0,
                slo=slo)
        return cls(specs, default_quota=default_quota)

    def configure(self, name: str, quota: Optional[int] = None,
                  priority: Optional[int] = None,
                  slo: Optional[Dict[str, float]] = None) -> TenantSpec:
        """Programmatic policy: create or update one tenant's spec."""
        with self._cond:
            spec = self._specs.get(name) or TenantSpec(name=name,
                                                       quota=self._default_quota)
            if quota is not None:
                spec.quota = quota
            if priority is not None:
                spec.priority = priority
            if slo:
                spec.slo.update(slo)
            self._specs[name] = spec
            return spec

    def spec(self, tenant: Optional[str]) -> Optional[TenantSpec]:
        if tenant is None:
            return None
        with self._cond:
            return self._specs.get(tenant)

    def priority(self, tenant: Optional[str]) -> int:
        s = self.spec(tenant)
        return s.priority if s is not None else 0

    def names(self):
        with self._cond:
            return sorted(set(self._specs) | set(self._open)
                          | set(self._admitted) | set(self._rejected))

    # -- admission --------------------------------------------------------
    def _quota(self, tenant: str) -> Optional[int]:
        # caller holds self._cond; tenants with no spec are unlimited
        # (the env default applies only to *named* tenants — see from_env)
        spec = self._specs.get(tenant)
        return spec.quota if spec is not None else None

    def acquire(self, tenant: Optional[str], block: bool = True,
                deadline: Optional[float] = None) -> bool:
        """Take one open-request slot for ``tenant``.  Untracked tenants
        (None, or no quota configured) always succeed.  A blocked
        acquire waits until a slot frees or ``deadline`` (monotonic,
        same clock as Request.deadline) passes; False = over quota.
        The caller decides whether False becomes ServiceSaturated or
        the expiry-while-blocked ``unknown`` path."""
        if tenant is None:
            return True
        with self._cond:
            while True:
                quota = self._quota(tenant)
                if quota is None or self._open.get(tenant, 0) < quota:
                    self._open[tenant] = self._open.get(tenant, 0) + 1
                    self._admitted[tenant] = self._admitted.get(tenant, 0) + 1
                    return True
                if not block:
                    self._rejected[tenant] = self._rejected.get(tenant, 0) + 1
                    return False
                rem = (deadline - mono_now()) if deadline is not None else None
                if rem is not None and rem <= 0:
                    self._rejected[tenant] = self._rejected.get(tenant, 0) + 1
                    return False
                self._cond.wait(timeout=min(rem, 0.1) if rem is not None
                                else 0.1)

    def release(self, tenant: Optional[str]) -> None:
        if tenant is None:
            return
        with self._cond:
            n = self._open.get(tenant, 0)
            if n <= 1:
                self._open.pop(tenant, None)
            else:
                self._open[tenant] = n - 1
            self._cond.notify_all()

    # -- export -----------------------------------------------------------
    def counts(self) -> Dict[str, Dict[str, Any]]:
        """The per-tenant policy + accounting cut for /metrics.  Names
        and counters only — never token material."""
        with self._cond:
            out: Dict[str, Dict[str, Any]] = {}
            for name in sorted(set(self._specs) | set(self._open)
                               | set(self._admitted) | set(self._rejected)):
                spec = self._specs.get(name)
                out[name] = {
                    "open": self._open.get(name, 0),
                    "admitted": self._admitted.get(name, 0),
                    "quota-rejections": self._rejected.get(name, 0),
                    "quota": (spec.quota if spec is not None
                              else self._default_quota),
                    "priority": spec.priority if spec is not None else 0,
                }
            return out

    def slo_config(self) -> Dict[str, Dict[str, float]]:
        """Per-tenant SLO ceilings for obs/slo.py tenant specs."""
        with self._cond:
            return {name: dict(spec.slo)
                    for name, spec in self._specs.items() if spec.slo}


def _int_or_none(raw: Optional[str],
                 default: Optional[int] = None) -> Optional[int]:
    if raw is None or not str(raw).strip():
        return default
    try:
        return int(str(raw).strip())
    except ValueError:
        return default


def _float_or_none(raw: Optional[str]) -> Optional[float]:
    if raw is None or not str(raw).strip():
        return None
    try:
        return float(str(raw).strip())
    except ValueError:
        return None
