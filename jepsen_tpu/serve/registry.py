"""The fleet membership registry: who is serving, from where, until when.

serve/fleet.py's constructor builds a *fixed* worker set — N slots, all
local, known before the first request.  A multi-host fleet cannot know
its members up front: workers on other machines REGISTER over the wire
(serve/fleetport.py), advertise where to dial them back
(``host:port``), what they are (device inventory, mesh shape, capability
buckets), and then hold a **lease**.  Every telemetry/heartbeat push
renews it; a worker that stops pushing — crashed, partitioned, or
decommissioned, indistinguishable from here and deliberately treated
the same — simply stops renewing, and the lease reaper evicts it
without any local signal.  Eviction is the multi-host analogue of
SIGKILL-the-slot: the slot goes dead, the router's rendezvous ranking
reroutes the worker's keys to siblings, and the journal's entries drain
through the normal driver reroute path.

Mesh shapes are the placement vocabulary: a worker advertising a 4×2
device mesh offers ``4*2*64 = 512`` lanes per dispatch, so a 512-lane
elle group can only land there; a CPU CI worker advertises the
degenerate ``(1,)`` mesh (64 lanes) and takes everything today's tests
route (see ``WorkerRecord.max_lanes`` / ``Router.ranked``).

All lease arithmetic runs on the monotonic clock
(:func:`jepsen_tpu.clock.mono_now`) — a wall-clock lease steps under
NTP adjustment and evicts healthy workers (or keeps dead ones) on a
time jump; CONC01 enforces this, and the registry lock's place in the
declared order is ``fleet-registry`` (lint/lock_order.py): below the
fleet locks, above the per-slot restart lock.

The registry never stores or exports the fleet auth token; its
snapshots are safe to serve from ``GET /fleet`` verbatim.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from jepsen_tpu.clock import mono_now

#: lanes one device contributes to a dispatch (the serve tier's
#: max-lanes default per worker; 8 devices x 64 = the 512-lane ceiling
#: in serve/buckets.MAX_LANE_BUCKET)
LANES_PER_DEVICE = 64

#: default lease duration, seconds (env-overridable)
DEFAULT_LEASE_S = 10.0

#: how many evicted-worker snapshots the registry remembers
EVICTED_RING = 64


def lease_duration_s() -> float:
    """The configured lease duration: ``JEPSEN_TPU_LEASE_S`` (seconds,
    must be > 0) or the 10 s default.  Read at call time so tests and
    the CLI can retune without re-importing."""
    raw = os.environ.get("JEPSEN_TPU_LEASE_S", "")
    try:
        v = float(raw) if raw else DEFAULT_LEASE_S
    except ValueError:
        return DEFAULT_LEASE_S
    return v if v > 0 else DEFAULT_LEASE_S


def parse_mesh(spec: Any) -> Tuple[int, ...]:
    """A mesh shape from wire/CLI forms: ``"4x2"`` / ``[4, 2]`` /
    ``(4, 2)`` → ``(4, 2)``; anything empty or malformed degrades to
    the degenerate ``(1,)`` mesh — a worker that cannot say what it is
    gets the smallest placement claim, never a bigger one."""
    if isinstance(spec, str):
        parts = [p for p in spec.replace("X", "x").split("x") if p]
        try:
            dims = tuple(int(p) for p in parts)
        except ValueError:
            return (1,)
    elif isinstance(spec, (list, tuple)):
        try:
            dims = tuple(int(d) for d in spec)
        except (TypeError, ValueError):
            return (1,)
    else:
        return (1,)
    if not dims or any(d < 1 for d in dims):
        return (1,)
    return dims


def mesh_lanes(mesh: Sequence[int]) -> int:
    """Lane capacity a mesh shape offers per dispatch."""
    n = 1
    for d in mesh:
        n *= max(1, int(d))
    return n * LANES_PER_DEVICE


@dataclass
class WorkerRecord:
    """One registered worker: identity, dial-back address, inventory,
    and the lease.  ``wid`` is assigned by the fleet when the record
    gets a slot; ``generation`` counts re-registrations under the same
    name (a worker that was evicted and came back)."""

    name: str
    host: str
    port: int
    pid: Optional[int] = None
    devices: Tuple[str, ...] = ()
    mesh: Tuple[int, ...] = (1,)
    buckets: Tuple[str, ...] = ()
    wid: Optional[int] = None
    generation: int = 0
    registered_at: float = field(default_factory=mono_now)
    lease_expires_at: float = 0.0
    renewals: int = 0
    evicted: bool = False

    @property
    def max_lanes(self) -> int:
        return mesh_lanes(self.mesh)

    def fits_lanes(self, lanes: int) -> bool:
        return int(lanes) <= self.max_lanes

    def lease_remaining_s(self, now: Optional[float] = None) -> float:
        now = mono_now() if now is None else now
        return self.lease_expires_at - now

    def snapshot(self, now: Optional[float] = None) -> Dict[str, Any]:
        now = mono_now() if now is None else now
        return {"name": self.name, "wid": self.wid,
                "host": self.host, "port": self.port, "pid": self.pid,
                "devices": list(self.devices),
                "mesh": "x".join(str(d) for d in self.mesh),
                "max-lanes": self.max_lanes,
                "buckets": list(self.buckets),
                "generation": self.generation,
                "renewals": self.renewals,
                "age-s": round(max(now - self.registered_at, 0.0), 3),
                "lease-remaining-s": round(self.lease_remaining_s(now), 3),
                "evicted": self.evicted}


class FleetRegistry:
    """Thread-safe membership + lease table.  Writers are the fleetport
    accept threads (register/renew) and the lease reaper (expire);
    readers are the router, ``GET /fleet``, and the metrics scrape."""

    def __init__(self, lease_s: Optional[float] = None):
        self.lease_s = float(lease_s) if lease_s else lease_duration_s()
        self._lock = threading.Lock()
        self._records: Dict[str, WorkerRecord] = {}   # live, by name
        self._gens: Dict[str, int] = {}
        self._blocked: set = set()   # names whose renewals chaos holds
        self._evicted: List[Dict[str, Any]] = []
        self.evictions = 0
        self.registrations = 0

    # -- membership --------------------------------------------------------
    def register(self, name: str, host: str, port: int, *,
                 pid: Optional[int] = None,
                 devices: Sequence[str] = (),
                 mesh: Any = (1,),
                 buckets: Sequence[str] = (),
                 now: Optional[float] = None
                 ) -> Tuple[Optional[WorkerRecord], bool]:
        """Admit (or refresh) one worker.  Returns ``(record, created)``
        — ``created`` is False when a live record under this name was
        renewed/updated in place, True when this registration made a new
        record (first contact, or a comeback after eviction: the
        generation bumps so stale pushes from the old incarnation are
        distinguishable).  Returns ``(None, False)`` when the name is
        chaos-blocked and holds no live record: the fault models a
        worker partitioned from the control plane, and a partitioned
        worker cannot re-register its way back in either — only the
        heal (``unblock_renewals``) reopens the door."""
        now = mono_now() if now is None else now
        with self._lock:
            rec = self._records.get(name)
            if rec is not None and not rec.evicted:
                rec.host, rec.port, rec.pid = str(host), int(port), pid
                rec.devices = tuple(str(d) for d in devices)
                rec.mesh = parse_mesh(mesh)
                rec.buckets = tuple(str(b) for b in buckets)
                if name not in self._blocked:
                    # a blocked live record keeps its (force-expired)
                    # lease: a refresh must not outrun the reaper
                    rec.lease_expires_at = now + self.lease_s
                    rec.renewals += 1
                return rec, False
            if name in self._blocked:
                return None, False
            gen = self._gens.get(name, -1) + 1
            self._gens[name] = gen
            rec = WorkerRecord(
                name=name, host=str(host), port=int(port), pid=pid,
                devices=tuple(str(d) for d in devices),
                mesh=parse_mesh(mesh),
                buckets=tuple(str(b) for b in buckets),
                generation=gen, registered_at=now,
                lease_expires_at=now + self.lease_s)
            self._records[name] = rec
            self.registrations += 1
            return rec, True

    def bind_slot(self, name: str, wid: int) -> None:
        """Record which fleet slot serves this name (fleet-side only)."""
        with self._lock:
            rec = self._records.get(name)
            if rec is not None:
                rec.wid = int(wid)

    # -- leases ------------------------------------------------------------
    def renew(self, name: str, now: Optional[float] = None) -> bool:
        """Extend a live worker's lease (telemetry/heartbeat path).
        False when the name is unknown, already evicted, or its
        renewals are chaos-blocked — a blocked renewal must not
        resurrect a lease the fault is expiring."""
        now = mono_now() if now is None else now
        with self._lock:
            rec = self._records.get(name)
            if rec is None or rec.evicted or name in self._blocked:
                return False
            rec.lease_expires_at = now + self.lease_s
            rec.renewals += 1
            return True

    def force_expire(self, name: str,
                     now: Optional[float] = None) -> bool:
        """Backdate a lease to expired-now (the chaos fault's trigger)."""
        now = mono_now() if now is None else now
        with self._lock:
            rec = self._records.get(name)
            if rec is None or rec.evicted:
                return False
            rec.lease_expires_at = now
            return True

    def block_renewals(self, name: str) -> None:
        with self._lock:
            self._blocked.add(name)

    def unblock_renewals(self, name: str) -> None:
        with self._lock:
            self._blocked.discard(name)

    def expire_leases(self, now: Optional[float] = None
                      ) -> List[WorkerRecord]:
        """Pop every record whose lease is spent (the reaper's sweep).
        The popped records are marked evicted and remembered in a
        bounded ring for ``GET /fleet``'s recent-evictions view."""
        now = mono_now() if now is None else now
        out: List[WorkerRecord] = []
        with self._lock:
            for name in [n for n, r in self._records.items()
                         if r.lease_expires_at <= now]:
                rec = self._records.pop(name)
                rec.evicted = True
                self.evictions += 1
                self._evicted.append(rec.snapshot(now))
                del self._evicted[:-EVICTED_RING]
                out.append(rec)
        return out

    # -- reads -------------------------------------------------------------
    def get(self, name: str) -> Optional[WorkerRecord]:
        with self._lock:
            return self._records.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._records)

    def is_live(self, name: str,
                generation: Optional[int] = None) -> bool:
        """Is this name currently a member (lease not yet reaped)?  With
        ``generation``, additionally require the live record to BE that
        incarnation — an evicted worker's old launcher must read dead
        even after the name re-registers."""
        with self._lock:
            rec = self._records.get(name)
            if rec is None or rec.evicted:
                return False
            if generation is not None and rec.generation != generation:
                return False
            return True

    def lease_age_s(self, name: str,
                    now: Optional[float] = None) -> Optional[float]:
        """Seconds since this worker last renewed (0 right after a
        renewal, climbing toward ``lease_s`` as it goes quiet)."""
        now = mono_now() if now is None else now
        with self._lock:
            rec = self._records.get(name)
            if rec is None:
                return None
            return max(now - (rec.lease_expires_at - self.lease_s), 0.0)

    def max_lease_age_s(self, now: Optional[float] = None) -> float:
        """The staleness high-water mark across the membership — the
        gauge the telemetry plane exports (obs/telemetry.py)."""
        now = mono_now() if now is None else now
        ages = [self.lease_age_s(n, now=now) for n in self.names()]
        return max([a for a in ages if a is not None], default=0.0)

    def snapshot(self, now: Optional[float] = None) -> Dict[str, Any]:
        """The ``GET /fleet`` membership document.  Carries no secret:
        auth status is a boolean, never the token."""
        now = mono_now() if now is None else now
        with self._lock:
            live = [r.snapshot(now) for r in self._records.values()]
            evicted = [dict(e) for e in self._evicted]
            blocked = sorted(self._blocked)
            registrations = self.registrations
            evictions = self.evictions
        live.sort(key=lambda r: (r["wid"] is None, r["wid"], r["name"]))
        return {"lease-s": self.lease_s,
                "workers": live,
                "registrations": registrations,
                "evictions": evictions,
                "renewals-blocked": blocked,
                "recent-evictions": evicted}
