"""The worker side of the wire: a CheckService behind a socket, and the
process launchers that put it there.

``python -m jepsen_tpu.serve.worker_main`` is the entrypoint a
:class:`~jepsen_tpu.serve.fleet.ProcFleet` supervisor spawns per worker
slot: it builds one local :class:`~jepsen_tpu.serve.service.CheckService`,
wraps it in a :class:`WorkerServer` speaking the serve/transport.py frame
protocol, prints one ``{"ready": true, "port": N, "pid": P}`` line on
stdout (the launcher's readiness handshake), and serves until SIGTERM.

Three layers live here:

- :class:`WorkerServer` — the protocol server: accepts connections,
  dedups SUBMIT ids (live requests re-attach to the new connection,
  finished ones re-deliver the cached RESULT — the worker half of the
  exactly-once story), re-anchors ``deadline-rem-s`` on its own
  monotonic clock (already-spent deadlines resolve ``unknown``
  immediately, no dispatch), and answers STATUS/HEALTHZ/DRAIN RPCs.
  A torn frame (mid-frame cut) drops that connection and nothing else;
  an oversized frame is answered with an ERROR frame, then the poisoned
  stream is closed.
- :class:`SubprocessWorker` — control/util-style daemon management for
  a real OS worker process: spawn in its own session (``setsid``
  discipline, so kill() can SIGKILL the whole group), readiness
  handshake with a deadline, stderr to a per-worker log file, SIGTERM →
  SIGKILL escalation on terminate.
- :class:`ThreadWorker` — the same protocol server over a real socket
  but hosting the CheckService in-process: the tier-1 test vehicle.
  Every frame, dedup path, and fault behaves identically; only the
  process boundary is elided, so CI exercises the wire without paying
  subprocess + JAX-warmup tax per test.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import select
import signal
import socket
import subprocess
import sys
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional

from jepsen_tpu.clock import mono_now
from jepsen_tpu.history import History
from jepsen_tpu.serve.aggregate import expired_result
from jepsen_tpu.serve.request import Request
from jepsen_tpu.serve.service import (CheckService, ServiceClosed,
                                      ServiceSaturated)
from jepsen_tpu.obs.telemetry import telemetry_interval_s
from jepsen_tpu.serve.auth import (AuthError, fleet_token, sign_frame,
                                   verify_frame)
from jepsen_tpu.serve.registry import parse_mesh
from jepsen_tpu.serve.transport import (F_ACK, F_DRAIN, F_ERROR, F_HEALTHZ,
                                        F_REGISTER, F_REPLY, F_RESULT,
                                        F_STATUS, F_SUBMIT, F_TELEMETRY,
                                        FrameError, MAX_FRAME_BYTES,
                                        OversizedFrame, TransportError,
                                        WireClient, encode_frame,
                                        read_frame)

log = logging.getLogger("jepsen.serve.worker")

#: finished-request RESULT cache depth: how far back a reconnecting
#: client can ask for a verdict it may have missed.  Bounded so a
#: long-lived worker cannot leak memory one finished cell at a time.
RESULT_CACHE = 1024


class _Conn:
    """One accepted connection: the socket plus a per-connection send
    lock so concurrent RESULT pushes and RPC replies interleave at frame
    boundaries, never mid-frame."""

    def __init__(self, sock: socket.socket,
                 token: Optional[str] = None):
        self.sock = sock
        self.token = token  # outbound frames are signed when set
        self._send_lock = threading.Lock()
        self.open = True

    def send(self, frame: Dict[str, Any], max_frame: int) -> bool:
        data = encode_frame(sign_frame(frame, self.token), max_frame)
        with self._send_lock:
            if not self.open:
                return False
            try:
                self.sock.sendall(data)
                return True
            except OSError:
                self.open = False
                return False

    def close(self) -> None:
        with self._send_lock:
            self.open = False
        try:
            self.sock.close()
        except OSError:
            pass


class WorkerServer:
    """Serve one CheckService over the frame protocol."""

    def __init__(self, service: CheckService, host: str = "127.0.0.1",
                 port: int = 0, max_frame: int = MAX_FRAME_BYTES,
                 telemetry_s: Optional[float] = None,
                 token: Optional[str] = None):
        self.service = service
        self.max_frame = max_frame
        # frame auth (serve/auth.py): with a configured fleet token,
        # every inbound frame must verify or the connection is answered
        # with a typed ERROR and hung up.  The token is held, used for
        # mac computation, and NEVER logged or exported.
        self._token = token if token is not None else fleet_token()
        self._lock = threading.Lock()  # inflight/done/conn tables
        self._inflight: Dict[str, Request] = {}
        self._done: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self._conn_for: Dict[str, _Conn] = {}
        self._conns: List[_Conn] = []
        self._closed = False
        self._last_idle = mono_now()
        self._t0 = mono_now()
        # Watchtower push cadence: None = the env-configured default;
        # <= 0 disables the push thread entirely
        self.telemetry_s = (telemetry_interval_s() if telemetry_s is None
                            else float(telemetry_s))
        self._tele_stop = threading.Event()
        self._tele_seq = 0
        sched = getattr(service, "_sched", None)
        if sched is not None and hasattr(sched, "add_idle_listener"):
            sched.add_idle_listener(self._note_idle)
        self._srv = socket.socket()
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(64)
        self.port = self._srv.getsockname()[1]
        threading.Thread(target=self._accept_loop, daemon=True,
                         name=f"worker-accept-{self.port}").start()
        if self.telemetry_s > 0:
            threading.Thread(target=self._telemetry_loop, daemon=True,
                             name=f"worker-tele-{self.port}").start()

    def _note_idle(self) -> None:
        with self._lock:
            self._last_idle = mono_now()

    # -- telemetry push ----------------------------------------------------
    def telemetry_payload(self) -> Dict[str, Any]:
        """One TELEMETRY frame body: process identity plus the full
        metrics snapshot minus the trace ring (traces are bulky and
        already travel on RESULT frames)."""
        snap = dict(self.service.metrics.snapshot())
        snap.pop("traces", None)
        self._tele_seq += 1
        return {"pid": os.getpid(),
                "uptime-s": round(mono_now() - self._t0, 3),
                "seq": self._tele_seq,
                "interval-s": self.telemetry_s,
                "metrics": snap}

    def _telemetry_loop(self) -> None:
        """Push the payload to every open connection on the cadence.
        Best-effort by design: a dead connection drops the frame (its
        reader cleanup already prunes the conn table), and the *absence*
        of pushes is itself the signal — the fleet-side TelemetryStore
        flags this worker stale after 2 missed intervals."""
        while not self._tele_stop.wait(timeout=self.telemetry_s):
            with self._lock:
                if self._closed:
                    return
                conns = list(self._conns)
            if not conns:
                continue
            try:
                frame = {"type": F_TELEMETRY,
                         "payload": self.telemetry_payload()}
                for conn in conns:
                    conn.send(frame, self.max_frame)
            except Exception:  # noqa: BLE001 — a torn snapshot must not
                log.debug("telemetry push failed", exc_info=True)

    # -- accept/read -------------------------------------------------------
    def _accept_loop(self) -> None:
        while True:
            try:
                sock, _ = self._srv.accept()
            except OSError:
                return  # listener closed
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            conn = _Conn(sock, token=self._token)
            with self._lock:
                if self._closed:
                    conn.close()
                    continue
                self._conns.append(conn)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True,
                             name=f"worker-conn-{self.port}").start()

    def _serve_conn(self, conn: _Conn) -> None:
        try:
            while True:
                try:
                    frame = read_frame(conn.sock, self.max_frame)
                except OversizedFrame as e:
                    # answer, then close: the stream is poisoned (the
                    # oversized payload was never consumed)
                    conn.send({"type": F_ERROR, "id": None,
                               "error": str(e),
                               "error-class": "OversizedFrame"},
                              self.max_frame)
                    return
                except (FrameError, OSError):
                    # torn frame / RST: a mid-frame cut kills this
                    # connection only — in-flight requests keep running
                    # and re-deliver on the client's next connection
                    return
                if frame is None:
                    return  # clean close
                if not verify_frame(frame, self._token):
                    # auth fail-closed: typed ERROR, then hangup.  The
                    # message names the failure mode only — never the
                    # token or the mac (serve/auth.py discipline).
                    what = ("unauthenticated frame"
                            if not isinstance(frame.get("auth"), str)
                            else "bad frame mac")
                    conn.send({"type": F_ERROR, "id": frame.get("id"),
                               "error": f"{what} rejected",
                               "error-class": "AuthError"},
                              self.max_frame)
                    return
                self._dispatch(conn, frame)
        finally:
            conn.close()
            with self._lock:
                if conn in self._conns:
                    self._conns.remove(conn)

    def _dispatch(self, conn: _Conn, frame: Dict[str, Any]) -> None:
        ftype = frame.get("type")
        try:
            if ftype == F_SUBMIT:
                self._handle_submit(conn, frame)
            elif ftype == F_STATUS:
                self._reply(conn, frame, self._status_payload(frame))
            elif ftype == F_HEALTHZ:
                self._reply(conn, frame, self.service.healthz())
            elif ftype == F_DRAIN:
                threading.Thread(
                    target=self._handle_drain, args=(conn, frame),
                    daemon=True).start()
            else:
                conn.send({"type": F_ERROR, "id": frame.get("id"),
                           "error": f"unknown frame type {ftype!r}",
                           "error-class": "FrameError"}, self.max_frame)
        except Exception as e:  # noqa: BLE001 — one bad frame must not
            log.exception("worker frame dispatch failed")  # kill the conn
            conn.send({"type": F_ERROR, "id": frame.get("id"),
                       "error": f"{type(e).__name__}: {e}",
                       "error-class": type(e).__name__}, self.max_frame)

    # -- SUBMIT ------------------------------------------------------------
    def _handle_submit(self, conn: _Conn, frame: Dict[str, Any]) -> None:
        cid = str(frame.get("id"))
        with self._lock:
            cached = self._done.get(cid)
            live = self._inflight.get(cid)
            if live is not None:
                # duplicate of a running SUBMIT (client re-sent across a
                # reconnect): re-attach its RESULT to this connection
                self._conn_for[cid] = conn
        trace = frame.get("trace")
        if cached is not None:
            # duplicate of a FINISHED submit: ack + re-deliver the cached
            # verdict — the client's claim_finish makes a true duplicate
            # delivery a no-op, so resending is always safe
            conn.send({"type": F_ACK, "id": cid, "dup": True,
                       "trace": trace}, self.max_frame)
            conn.send({"type": F_RESULT, "id": cid, "result": cached,
                       "trace": trace}, self.max_frame)
            return
        if live is not None:
            conn.send({"type": F_ACK, "id": cid, "dup": True,
                       "trace": trace}, self.max_frame)
            return
        kind = frame.get("kind") or "wgl"
        rem = frame.get("deadline-rem-s")
        if rem is not None and float(rem) <= 0:
            # spent before arrival: resolve unknown without a dispatch —
            # the deadline authority is the sender's remaining figure,
            # re-anchored here, never a wall clock comparison
            res = expired_result(kind)
            self._remember(cid, res)
            conn.send({"type": F_ACK, "id": cid, "trace": trace},
                      self.max_frame)
            conn.send({"type": F_RESULT, "id": cid, "result": res,
                       "trace": trace}, self.max_frame)
            return
        history = History(frame.get("ops") or [])
        spec = dict(frame.get("spec") or {})
        try:
            # the propagated trace context makes the worker-side request
            # a child span of the sender's; span times re-anchor on THIS
            # process's monotonic clock at submit
            req = self.service.submit(
                history, kind=kind, block=False,
                deadline_s=float(rem) if rem is not None else None,
                trace=trace, **spec)
        except (ServiceSaturated, ServiceClosed) as e:
            conn.send({"type": F_ERROR, "id": cid, "error": str(e),
                       "error-class": type(e).__name__}, self.max_frame)
            return
        with self._lock:
            self._inflight[cid] = req
            self._conn_for[cid] = conn
        conn.send({"type": F_ACK, "id": cid, "trace": trace},
                  self.max_frame)
        threading.Thread(target=self._await_result, args=(cid, req),
                         daemon=True,
                         name=f"worker-wait-{cid}").start()

    def _await_result(self, cid: str, req: Request) -> None:
        try:
            result = req.wait(timeout=None)
        except Exception as e:  # noqa: BLE001 — degrade, never fabricate
            result = {"valid": "unknown", "analyzer": "worker",
                      "error": f"worker wait failed: "
                               f"{type(e).__name__}: {e}"}
        self._finish(cid, result)

    def _remember(self, cid: str, result: Dict[str, Any]) -> None:
        with self._lock:
            self._done[cid] = result
            while len(self._done) > RESULT_CACHE:
                self._done.popitem(last=False)

    def _finish(self, cid: str, result: Dict[str, Any]) -> None:
        with self._lock:
            self._inflight.pop(cid, None)
            self._done[cid] = result
            while len(self._done) > RESULT_CACHE:
                self._done.popitem(last=False)
            conn = self._conn_for.pop(cid, None)
        if conn is not None:
            # best-effort push; a client that missed it (cut link) will
            # re-SUBMIT the same id and hit the _done cache.  The frame
            # carries the trace ids alongside the serve payload so every
            # RESULT is self-identifying on the wire.
            serve = (result or {}).get("serve") or {}
            trace = ({"trace-id": serve.get("trace-id"),
                      "parent-span-id": serve.get("parent-span-id")}
                     if serve.get("trace-id") else None)
            conn.send({"type": F_RESULT, "id": cid, "result": result,
                       "trace": trace}, self.max_frame)

    # -- RPCs --------------------------------------------------------------
    def _status_payload(
            self, frame: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        p = dict(self.service.ping())
        with self._lock:
            p["wire-inflight"] = len(self._inflight)
            p["wire-done-cached"] = len(self._done)
            last_idle = self._last_idle
        p["idle-age-s"] = round(mono_now() - last_idle, 3)
        p["pid"] = os.getpid()
        if frame and frame.get("recorder") is not None:
            # runtime arm/disarm of this process's flight recorder — the
            # worker half of POST /recorder
            from jepsen_tpu.obs.recorder import RECORDER
            if frame.get("recorder"):
                RECORDER.enable()
            else:
                RECORDER.disable()
            p["recorder"] = RECORDER.stats()
        if frame and frame.get("metrics"):
            # the fleet-wide scrape: full Metrics.snapshot() on demand
            # over the same STATUS frame the heartbeat already uses
            p["metrics"] = self.service.metrics.snapshot()
        return p

    def _reply(self, conn: _Conn, frame: Dict[str, Any],
               payload: Any) -> None:
        out = {"type": F_REPLY, "id": frame.get("id"), "payload": payload}
        if frame.get("trace") is not None:  # context echo, wire symmetry
            out["trace"] = frame.get("trace")
        conn.send(out, self.max_frame)

    def _handle_drain(self, conn: _Conn, frame: Dict[str, Any]) -> None:
        t = frame.get("timeout-s")
        ok = self.service.drain(timeout=t)
        self._reply(conn, frame, bool(ok))

    # -- lifecycle ---------------------------------------------------------
    def alive(self) -> bool:
        with self._lock:
            if self._closed:
                return False
        return self.service.alive()

    def close(self) -> None:
        self._tele_stop.set()
        with self._lock:
            self._closed = True
        try:
            self._srv.close()
        except OSError:
            pass

    def kill(self) -> None:
        """Crash semantics: listener down, live connections RST (clients
        see a hard cut, not a graceful close), service killed."""
        self.close()
        with self._lock:
            conns, self._conns = self._conns, []
        for c in conns:
            try:
                c.sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                                  b"\x01\x00\x00\x00\x00\x00\x00\x00")
            except OSError:
                pass
            c.close()
        self.service.kill()


# ---------------------------------------------------------------------------
# launchers
# ---------------------------------------------------------------------------


class SubprocessWorker:
    """One real worker OS process, managed with the control/util daemon
    discipline: own session (killable as a group), readiness handshake
    on stdout, stderr to a log file, SIGTERM → SIGKILL escalation."""

    def __init__(self, name: str, log_path: str, *,
                 args: Optional[Dict[str, Any]] = None,
                 env: Optional[Dict[str, str]] = None,
                 ready_timeout_s: float = 120.0):
        self.name = name
        self.log_path = log_path
        self.ready_timeout_s = ready_timeout_s
        self.port: Optional[int] = None
        # where a client dials this worker back.  A wildcard bind
        # (0.0.0.0/::) is not dialable; local supervision reaches it on
        # loopback, remote fleets advertise a real host via REGISTER.
        bind = (args or {}).get("host")
        self.host = ("127.0.0.1" if bind in (None, "", "0.0.0.0", "::")
                     else str(bind))
        argv = [sys.executable, "-m", "jepsen_tpu.serve.worker_main"]
        for k, v in (args or {}).items():
            if v is None:
                continue
            argv += [f"--{k}", str(v)]
        root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        penv = dict(os.environ)
        penv["PYTHONPATH"] = root + os.pathsep + penv.get("PYTHONPATH", "")
        penv.setdefault("JAX_PLATFORMS", os.environ.get(
            "JAX_PLATFORMS", "cpu"))
        penv.update(env or {})
        self._log = open(log_path, "ab")
        self.proc = subprocess.Popen(
            argv, stdout=subprocess.PIPE, stderr=self._log,
            cwd=root, env=penv,
            start_new_session=True)  # own group: kill() nukes descendants

    def await_ready(self) -> int:
        """Block until the worker prints its ready line; returns the real
        port it listens on.  Raises if the process dies or stalls first."""
        if self.port is not None:
            return self.port
        out = self.proc.stdout
        deadline = mono_now() + self.ready_timeout_s
        buf = b""
        while b"\n" not in buf:
            left = deadline - mono_now()
            if left <= 0:
                raise TimeoutError(
                    f"worker {self.name} not ready after "
                    f"{self.ready_timeout_s:.0f}s (log: {self.log_path})")
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"worker {self.name} exited rc={self.proc.returncode} "
                    f"before ready (log: {self.log_path})")
            r, _, _ = select.select([out], [], [], min(0.5, left))
            if r:
                chunk = os.read(out.fileno(), 4096)
                if not chunk:
                    raise RuntimeError(
                        f"worker {self.name} closed stdout before ready "
                        f"(log: {self.log_path})")
                buf += chunk
        line = buf.split(b"\n", 1)[0]
        msg = json.loads(line.decode("utf-8"))
        if not msg.get("ready"):
            raise RuntimeError(f"worker {self.name} bad ready line: {msg}")
        self.port = int(msg["port"])
        return self.port

    def alive(self) -> bool:
        return self.proc.poll() is None

    def kill(self) -> None:
        """Crash the worker: SIGKILL its whole process group."""
        try:
            os.killpg(self.proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError, OSError):
            pass
        try:
            self.proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pass
        self._close_log()

    def terminate(self, timeout_s: float = 10.0) -> None:
        """Graceful stop: SIGTERM (the worker closes its service), then
        SIGKILL the group if it hangs."""
        if self.proc.poll() is None:
            try:
                os.killpg(self.proc.pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError, OSError):
                pass
            try:
                self.proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                self.kill()
                return
        self._close_log()

    def _close_log(self) -> None:
        try:
            self._log.close()
        except OSError:
            pass

    def status(self) -> Dict[str, Any]:
        return {"kind": "subprocess", "pid": self.proc.pid,
                "alive": self.alive(), "port": self.port,
                "log": self.log_path}


class ThreadWorker:
    """The protocol server over a real socket, CheckService in-process:
    identical wire behavior to :class:`SubprocessWorker` minus the
    process boundary.  Tier-1 tests and ``ProcFleet(spawn=False)`` use
    this so the frame/dedup/fault paths run on CPU CI in milliseconds."""

    def __init__(self, name: str, make_service, *,
                 max_frame: int = MAX_FRAME_BYTES,
                 telemetry_s: Optional[float] = None):
        self.name = name
        self.host = "127.0.0.1"  # in-process: always loopback-dialable
        self.service = make_service()
        self.server = WorkerServer(self.service, max_frame=max_frame,
                                   telemetry_s=telemetry_s)
        self._killed = False

    def await_ready(self) -> int:
        return self.server.port

    def alive(self) -> bool:
        return not self._killed and self.server.alive()

    def kill(self) -> None:
        self._killed = True
        self.server.kill()

    def terminate(self, timeout_s: float = 10.0) -> None:
        self._killed = True
        self.server.close()
        self.service.close(timeout=timeout_s)

    def status(self) -> Dict[str, Any]:
        return {"kind": "thread", "pid": os.getpid(),
                "alive": self.alive(), "port": self.server.port}


# ---------------------------------------------------------------------------
# fleet registration (the worker side of serve/fleetport.py)
# ---------------------------------------------------------------------------


class FleetRegistration:
    """Register this worker with a fleetport and keep its lease alive.

    The worker dials the fleet (not the other way around) exactly once
    per incarnation: a REGISTER frame carries its dial-back address,
    device inventory, mesh shape, and capability buckets; the REPLY
    brings back the slot id and the lease duration.  From then on the
    renewal loop pushes *named* TELEMETRY frames at a third of the lease
    — the same frames Watchtower already aggregates double as
    heartbeats, so there is no separate keepalive protocol to keep
    honest.

    Failure discipline mirrors the verdict discipline: a transport cut
    degrades (re-register with backoff — the fleet treats a comeback
    after eviction as a new generation), but an :class:`AuthError` is
    **permanent** — a worker holding the wrong token must not hammer
    the control plane with frames it can never authenticate."""

    def __init__(self, server: WorkerServer, *,
                 fleet_addr, name: str,
                 advertise_host: str, port: Optional[int] = None,
                 mesh: Any = (1,), devices=(), buckets=(),
                 token: Optional[str] = None):
        self.server = server
        self.name = name
        self.host = advertise_host
        self.port = int(port if port is not None else server.port)
        self.mesh = parse_mesh(mesh)
        self.devices = tuple(devices)
        self.buckets = tuple(buckets)
        self.wid: Optional[int] = None
        self.lease_s: float = 10.0
        self.registrations = 0
        self.rejected = False  # permanent auth rejection
        self.registered = threading.Event()
        self._stop = threading.Event()
        self._client = WireClient(tuple(fleet_addr),
                                  name=f"fleet@{fleet_addr[0]}",
                                  token=token)
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "FleetRegistration":
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"fleet-reg-{self.name}")
        self._thread.start()
        return self

    def wait_registered(self, timeout: Optional[float] = None) -> bool:
        return self.registered.wait(timeout=timeout)

    def _register(self) -> None:
        reply = self._client.call(
            F_REGISTER,
            {"name": self.name, "host": self.host, "port": self.port,
             "pid": os.getpid(), "devices": list(self.devices),
             "mesh": "x".join(str(d) for d in self.mesh),
             "buckets": list(self.buckets)},
            timeout_s=10.0) or {}
        self.wid = reply.get("wid")
        lease = reply.get("lease-s")
        if lease:
            self.lease_s = float(lease)
        self.registrations += 1
        self.registered.set()

    def _loop(self) -> None:
        backoff = 0.2
        joined = False
        while not self._stop.is_set():
            try:
                if not joined:
                    self._register()
                    joined = True
                    backoff = 0.2
                # the renewal IS a telemetry frame — sent as an RPC so a
                # refusal is observable: the fleetport replies REPLY to a
                # member, and a typed ERROR ("NotRegistered") to an
                # evicted name, which lands here as a TransportError and
                # drives the re-register below
                self._client.call(
                    F_TELEMETRY,
                    {"name": self.name,
                     "payload": self.server.telemetry_payload()},
                    timeout_s=max(self.lease_s / 2.0, 1.0))
            except AuthError:
                # wrong/missing token: permanent — stop, never retry.
                # The log line names the condition, never the token.
                log.error("fleet registration rejected: auth failure")
                self.rejected = True
                return
            except (TransportError, OSError) as e:
                # cut link / refused dial / torn frame: transient —
                # re-register next round (the fleet sees a comeback as
                # a new generation if the lease lapsed meanwhile)
                log.warning("fleet link lost (%s); re-registering",
                            type(e).__name__)
                joined = False
                self._stop.wait(timeout=backoff)
                backoff = min(backoff * 2, 2.0)
                continue
            self._stop.wait(timeout=max(self.lease_s / 3.0, 0.05))

    def stop(self) -> None:
        self._stop.set()
        self._client.close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)


# ---------------------------------------------------------------------------
# entrypoint
# ---------------------------------------------------------------------------


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="jepsen_tpu.serve.worker_main",
        description="one fleet worker: a CheckService behind the wire")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--max-lanes", type=int, default=64)
    ap.add_argument("--max-queue", type=int, default=4096)
    ap.add_argument("--store-base", default=None)
    ap.add_argument("--capacity", type=int, default=None)
    ap.add_argument("--max-capacity", type=int, default=None)
    ap.add_argument("--max-frame", type=int, default=MAX_FRAME_BYTES)
    ap.add_argument("--telemetry-s", type=float, default=None,
                    help="TELEMETRY push cadence in seconds (default: "
                         "JEPSEN_TPU_TELEMETRY_S or 1.0; <= 0 disables)")
    ap.add_argument("--name", default=None,
                    help="worker name to register under (default: "
                         "worker-<pid>)")
    ap.add_argument("--fleet-addr", default=None, metavar="HOST:PORT",
                    help="register with the fleetport at this address "
                         "and hold a lease there")
    ap.add_argument("--advertise-host", default=None,
                    help="dial-back host to advertise in REGISTER "
                         "(required sense when binding 0.0.0.0; "
                         "default: --host, or 127.0.0.1 on a wildcard "
                         "bind)")
    ap.add_argument("--mesh", default="1",
                    help="device-mesh shape to advertise, e.g. 4x2 "
                         "(default: the degenerate 1-mesh)")
    args = ap.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO, stream=sys.stderr,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    svc_kw: Dict[str, Any] = dict(max_lanes=args.max_lanes,
                                  max_queue_cells=args.max_queue,
                                  store_base=args.store_base)
    if args.capacity is not None:
        svc_kw["capacity"] = args.capacity
    if args.max_capacity is not None:
        svc_kw["max_capacity"] = args.max_capacity
    service = CheckService(**svc_kw)
    server = WorkerServer(service, host=args.host, port=args.port,
                          max_frame=args.max_frame,
                          telemetry_s=args.telemetry_s)
    registration: Optional[FleetRegistration] = None
    if args.fleet_addr:
        fhost, _, fport = args.fleet_addr.rpartition(":")
        adv = args.advertise_host or (
            "127.0.0.1" if args.host in ("0.0.0.0", "::") else args.host)
        registration = FleetRegistration(
            server, fleet_addr=(fhost or "127.0.0.1", int(fport)),
            name=args.name or f"worker-{os.getpid()}",
            advertise_host=adv, mesh=args.mesh,
            buckets=("wgl", "elle")).start()
    stop = threading.Event()

    def _on_signal(signum, frame):  # noqa: ARG001 — signal signature
        stop.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    print(json.dumps({"ready": True, "port": server.port,
                      "pid": os.getpid()}), flush=True)
    while not stop.is_set():
        # the wait is the whole main thread's job; everything else runs
        # on the accept/conn/waiter threads
        stop.wait(timeout=1.0)
    if registration is not None:
        registration.stop()
    server.close()
    service.close(timeout=30.0)
    return 0


if __name__ == "__main__":
    sys.exit(main())
