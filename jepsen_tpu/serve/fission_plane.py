"""Hydra — the distributed-fission plane: fan one giant history across
the whole fleet.

Engine fission (engine.fission, PR 11) splits an overflowing WGL search
into independent sub-problems — per-key component projections
(arXiv 1504.00204) and ghost case-splits (arXiv 2410.04581) — but
recombines them *inside one worker*, so the capacity ceiling merely
moved from "one device" to "one host".  This plane applies the same two
splitters at the **fleet edge**: when a WGL cell's event count crosses
the fleet-fission threshold at admission, :func:`scatter` decomposes it
into first-class child cells that ride the existing machinery
unchanged — the rendezvous router places each sub-problem on its own
worker (distinct cell ids → distinct route tokens), mesh-aware
placement, hedging, circuit breakers, lease-eviction reroute and the
FleetJournal all apply *per sub-problem*, so a worker SIGKILL
mid-search re-runs only the sub-problems that worker owned.

Recombination happens in serve.aggregate under the exact
unknown-never-false table from docs/fission.md, with one discipline
*stricter* than the engine's: a distributed ``False`` must carry the
refuting sub-problem's op **and** witness, else the group degrades to
unknown — a lost worker can cost a refutation, never fabricate one.
:func:`on_child_result` enforces the evidence half of that contract at
the finalize seam: a refuting child that arrived witness-less gets one
witness-recovery re-check dispatched **only to the worker that produced
the refutation** (its engine cache is the only warm one), and siblings
whose group is already decided are cancelled at the fleet edge (the
drive loop stops re-dispatching; a worker mid-compute is never
interrupted — its verdict is simply ignored).

The one-giant-component case — nothing to scatter — is not this
plane's job: the worker-local fission path now ends in the
window-shrinking recursion (engine.shrink) instead of an escalation to
a capacity no worker has.

Knobs (README env table): ``JTPU_FLEETFISSION`` (default on),
``JTPU_FLEETFISSION_THRESHOLD`` (default 8192 events — the admission
event count past which a cell scatters), and
``JTPU_FLEETFISSION_MAX_SUBPROBLEMS`` (default 256 — a cell that would
need more children stays whole and is the worker's problem).
"""

from __future__ import annotations

import itertools
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, TYPE_CHECKING

from jepsen_tpu.engine import fission as engine_fission
from jepsen_tpu.obs.hist import HistogramSet
from jepsen_tpu.obs.recorder import RECORDER
from jepsen_tpu.serve import buckets
from jepsen_tpu.serve.decompose import _engine_identity
from jepsen_tpu.serve.metrics import mono_now
from jepsen_tpu.serve.request import Cell, KIND_WGL, Request

if TYPE_CHECKING:  # pragma: no cover
    from jepsen_tpu.serve.fleet import Fleet

log = logging.getLogger("jepsen_tpu.serve.fission_plane")

ANALYZER = "fleet-fission"

DEFAULT_THRESHOLD = 8192
DEFAULT_MAX_SUBPROBLEMS = 256

#: Bound on one witness-recovery re-check (further clamped by the
#: request's remaining deadline budget).
RECOVERY_WAIT_S = 30.0

_gids = itertools.count(1)

#: Sub-problem turnaround (admission → finalize) histogram, merged into
#: the /metrics fission section.
HISTS = HistogramSet()


# ---------------------------------------------------------------------------
# Knobs
# ---------------------------------------------------------------------------

def fleetfission_enabled() -> bool:
    return os.environ.get("JTPU_FLEETFISSION", "1").lower() \
        not in ("0", "false", "no", "off", "")


def fleetfission_threshold() -> int:
    """Admission event count past which a WGL cell scatters fleet-wide."""
    try:
        return max(1, int(os.environ.get("JTPU_FLEETFISSION_THRESHOLD",
                                         DEFAULT_THRESHOLD)))
    except ValueError:
        return DEFAULT_THRESHOLD


def fleetfission_max_subproblems() -> int:
    try:
        return max(2, int(os.environ.get("JTPU_FLEETFISSION_MAX_SUBPROBLEMS",
                                         DEFAULT_MAX_SUBPROBLEMS)))
    except ValueError:
        return DEFAULT_MAX_SUBPROBLEMS


# ---------------------------------------------------------------------------
# Counters (serve idiom: hyphenated keys, exported in /metrics "fission")
# ---------------------------------------------------------------------------

_STATS_LOCK = threading.Lock()


def _zero_stats() -> Dict[str, int]:
    return {"scattered": 0, "remote-subproblems": 0, "cancelled": 0,
            "witness-recoveries": 0, "witness-recovery-failures": 0}


_STATS = _zero_stats()


def plane_stats() -> Dict[str, int]:
    """Fleet-edge fission counters: cells scattered, child cells created,
    siblings early-cancelled, witness recoveries run and failed."""
    with _STATS_LOCK:
        return dict(_STATS)


def reset_plane_stats() -> None:
    with _STATS_LOCK:
        _STATS.update(_zero_stats())


def _bump(**kw: int) -> None:
    with _STATS_LOCK:
        for k, v in kw.items():
            _STATS[k] += v


# ---------------------------------------------------------------------------
# Scatter: admission-time decomposition into first-class fleet cells
# ---------------------------------------------------------------------------

def cancelled_result() -> Dict[str, Any]:
    """What a cancelled sub-problem resolves to: unknown, never false —
    a sibling already decided the group, so this verdict is vestigial
    and the recombiner's any-False / any-True rules ignore it."""
    return {"valid": "unknown", "analyzer": ANALYZER, "cancelled": True,
            "error": "sub-problem cancelled: a sibling already decided "
                     "the fission group"}


def scatter(req: Request) -> List[Cell]:
    """Replace each over-threshold WGL cell in ``req.cells`` with
    fission child cells (component projections, else ghost variants);
    cells that don't qualify — or whose split fails for any reason —
    pass through untouched: scatter degrades to "the worker's problem",
    never to a lost cell.  Returns the new ``req.cells``."""
    if req.kind != KIND_WGL or not fleetfission_enabled() \
            or req.spec.get("fission") is False:
        return req.cells
    thr = fleetfission_threshold()
    out: List[Cell] = []
    for cell in req.cells:
        if len(cell.history.ops) < thr:
            out.append(cell)
            continue
        try:
            children = _split_cell(req, cell)
        except Exception as e:  # noqa: BLE001 — scatter must never lose a cell
            log.exception("fleet fission split failed; cell stays whole")
            RECORDER.record("fission", "scatter-error",
                            args={"error": f"{type(e).__name__}: {e}"})
            children = None
        if not children:
            out.append(cell)
            continue
        _bump(scattered=1)
        _bump(**{"remote-subproblems": len(children)})
        RECORDER.record("fission", "scatter", trace_id=req.trace_id,
                        span_id=req.span_id,
                        args={"group": children[0].fission["group"],
                              "mode": children[0].fission["mode"],
                              "subproblems": len(children),
                              "events": len(cell.history.ops)})
        out.extend(children)
    req.cells = out
    return out


def _split_cell(req: Request, cell: Cell) -> Optional[List[Cell]]:
    """One cell → fission children, or None when neither splitter
    applies within the sub-problem cap (one giant component AND too many
    ghosts: the worker-local shrink recursion is the remaining tool)."""
    model = req.spec["model"]
    max_subs = fleetfission_max_subproblems()
    subs = engine_fission.component_split(model, cell.history)
    if subs is not None and len(subs) >= 2 and len(subs) <= max_subs:
        # Component children keep worker-local fission ON: an exceeded
        # projection re-splits inside its worker (ghost re-resolve),
        # exactly as _check_components does for exceeded lanes.
        return _make_children(req, cell, "components", subs, overrides={})
    h = cell.history.client_ops()
    ghosts = engine_fission._real_ghosts(model, h)
    if not ghosts or (1 << len(ghosts)) > max_subs:
        return None
    k = len(ghosts)
    variants = [engine_fission.ghost_variant(h, ghosts, m)
                for m in range(1 << k)]
    # Every variant is ghost-free, so each worker checks it lean at a
    # threshold-sized ceiling — the same shape engine._ghost_split
    # dispatches, which is what lane-for-lane parity is measured against.
    wthr = engine_fission.fission_threshold()
    return _make_children(req, cell, "ghosts", variants,
                          overrides={"fission": False,
                                     "capacity": min(256, wthr),
                                     "max_capacity": wthr})


def _make_children(req: Request, parent: Cell, mode: str, subs: List,
                   overrides: Dict[str, Any]) -> List[Cell]:
    gid = f"{req.id}.g{next(_gids)}"
    ident = _engine_identity(req)
    now = mono_now()
    return [Cell(request=req, history=sub, key=parent.key,
                 bucket=(req.kind, ident) + buckets.wgl_bucket(sub),
                 enqueued=now,
                 fission={"group": gid, "mode": mode, "index": i,
                          "subproblems": len(subs)},
                 spec_overrides=dict(overrides))
            for i, sub in enumerate(subs)]


# ---------------------------------------------------------------------------
# Finalize seam: evidence discipline + sibling cancel
# ---------------------------------------------------------------------------

def on_child_result(fleet: "Fleet", cell: Cell,
                    result: Dict[str, Any]) -> Dict[str, Any]:
    """Called by the fleet as each cell's verdict lands, *before* the
    cell is finalized.  Ordinary cells pass through.  For fission
    children: observe turnaround, enforce the evidence contract on
    refutations (witness recovery on the refuting worker only, degrade
    to unknown on failure — never fabricate False), and early-cancel
    siblings once this child decides the group."""
    if cell.fission is None:
        return result
    if cell.enqueued:
        HISTS.observe("fleetfission:subproblem-s",
                      mono_now() - cell.enqueued)
    mode = cell.fission["mode"]
    index = cell.fission["index"]
    v = result.get("valid")
    # The evidence-bearing refutation sites: a components child's False
    # decides the group; a ghosts child's False only matters as evidence
    # when it is the all-elided branch (index 0), whose op/witness are
    # the canonical ones for the all-False conjunction.
    bears_evidence = (mode == "components" and v is False) \
        or (mode == "ghosts" and v is False and index == 0)
    if bears_evidence and not ("op" in result and "witness" in result):
        result = _recover_witness(fleet, cell, result)
        v = result.get("valid")
    decides = (mode == "components" and v is False
               and "op" in result and "witness" in result) \
        or (mode == "ghosts" and v is True)
    if decides:
        _cancel_siblings(fleet, cell)
    return result


def _recover_witness(fleet: "Fleet", cell: Cell,
                     result: Dict[str, Any]) -> Dict[str, Any]:
    """A refuting child arrived witness-less (witness budget, wire
    truncation).  Re-check the sub-history on the SAME worker that
    refuted it — the only one with a warm engine cache for this shape —
    and adopt its op/witness.  Any failure (worker dead, re-check
    unknown, deadline) degrades this child's False to unknown: the
    distributed table refuses an unwitnessed False, so a lost worker
    can lose a refutation but can never fabricate one."""
    req = cell.request
    wid = (result.get("fleet") or {}).get("worker")
    worker = next((w for w in fleet.workers if w.wid == wid), None)
    _bump(**{"witness-recoveries": 1})
    t0 = mono_now()
    recovered: Optional[Dict[str, Any]] = None
    why = "refuting worker not found"
    if worker is not None and worker.alive():
        try:
            recovered = _recheck_on(worker, cell)
        except Exception as e:  # noqa: BLE001 — recovery is best-effort
            why = f"witness re-check failed: {type(e).__name__}: {e}"
    elif worker is not None:
        why = f"refuting worker w{wid} died before witness recovery"
    RECORDER.record("fission", "witness-recovery", trace_id=req.trace_id,
                    span_id=req.span_id, dur_s=mono_now() - t0,
                    args={"group": cell.fission["group"], "worker": wid,
                          "ok": bool(recovered)})
    if recovered is not None and recovered.get("valid") is False \
            and "op" in recovered and "witness" in recovered:
        # witness: re-derived on the refuting worker from the same sub-history; False keeps its evidence
        out = dict(result)
        out["op"] = recovered["op"]
        out["witness"] = recovered["witness"]
        out.setdefault("fission", {})
        if isinstance(out["fission"], dict):
            out["fission"]["witness-recovered"] = True
        return out
    if recovered is not None:
        why = (f"witness re-check did not re-refute "
               f"(valid={recovered.get('valid')!r})")
    _bump(**{"witness-recovery-failures": 1})
    return {"valid": "unknown", "analyzer": ANALYZER,
            "error": f"unwitnessed refutation degraded to unknown: {why}",
            "configs-explored": int(result.get("configs-explored", 0) or 0),
            "fleet": dict(result.get("fleet") or {})}


def _recheck_on(worker, cell: Cell) -> Optional[Dict[str, Any]]:
    """One bounded explain=True re-check of ``cell`` on ``worker``."""
    req = cell.request
    from jepsen_tpu.serve.service import submit_kwargs
    kw = submit_kwargs(req)
    kw.update(cell.spec_overrides)
    kw["explain"] = True
    rem = req.remaining_s()
    cap = RECOVERY_WAIT_S if rem is None else max(0.0, min(rem,
                                                           RECOVERY_WAIT_S))
    wreq = worker.service.submit(cell.history, block=False,
                                 deadline_s=rem,
                                 trace=req.trace_context(), **kw)
    deadline = mono_now() + cap
    while mono_now() < deadline:
        if wreq.done():
            return dict(wreq.result or {})
        if not worker.alive():
            return None
        time.sleep(0.02)
    return None


def _cancel_siblings(fleet: "Fleet", cell: Cell) -> None:
    """Flag every still-unresolved sibling in this cell's fission group:
    the drive loop stops (re-)dispatching them and they finalize as
    :func:`cancelled_result`.  A worker already computing one is never
    interrupted — its verdict just stops mattering (the recombiner's
    any-False / any-True rules dominate unknowns)."""
    gid = cell.fission["group"]
    n = 0
    for sib in cell.request.cells:
        if sib is cell or sib.fission is None \
                or sib.fission.get("group") != gid:
            continue
        if sib.result is None and not sib.cancelled:
            sib.cancelled = True
            n += 1
    if n:
        _bump(cancelled=n)
        RECORDER.record("fission", "cancel-siblings",
                        trace_id=cell.request.trace_id,
                        span_id=cell.request.span_id,
                        args={"group": gid, "cancelled": n})
