"""Observability: distributed tracing, histograms, and a flight recorder.

The telescope the serving tier looks through.  Three instruments, all
cheap enough to leave on in production and all exportable through the
existing ``/metrics`` surface:

- ``trace``    — trace-context ids minted at ``Request`` submit and
                 propagated on every wire frame, plus the Chrome
                 trace-event (Perfetto) conversion for merged traces.
- ``hist``     — log-bucketed latency/compile-time histograms on the
                 same pow2 ladder the serve shape buckets use, so the
                 histogram buckets *are* the shape buckets.
- ``recorder`` — a bounded process-wide ring of structured
                 dispatch/compile/transfer/retry/chaos events with an
                 atomic Chrome-trace export (``RECORDER``).

Import discipline: nothing here imports ``jepsen_tpu.serve`` at module
scope (serve's metrics layer imports us — the ladder reuse in ``hist``
is a lazy import to keep the cycle open).
"""

from jepsen_tpu.obs.hist import (  # noqa: F401
    Histogram, HistogramSet, compile_hist_stats, merge_hist_snapshots,
    observe_compile, timed_first_call,
)
from jepsen_tpu.obs.recorder import RECORDER, FlightRecorder  # noqa: F401
from jepsen_tpu.obs.trace import (  # noqa: F401
    chrome_document, chrome_events_from_trace, new_span_id, new_trace_id,
    wall_anchor, write_chrome,
)

__all__ = [
    "Histogram", "HistogramSet", "compile_hist_stats",
    "merge_hist_snapshots", "observe_compile", "timed_first_call",
    "RECORDER", "FlightRecorder",
    "chrome_document", "chrome_events_from_trace", "new_span_id",
    "new_trace_id", "wall_anchor", "write_chrome",
]
