"""Prometheus text-exposition rendering of a ``Metrics.snapshot()``.

The pow2 histogram ladder (obs/hist.py) maps directly onto Prometheus
histogram conventions: every bucket upper bound (microseconds) becomes a
cumulative ``le`` label in seconds, with the mandatory ``+Inf`` bucket
equal to the total count.  Because every process shares the identical
ladder, the fleet-merged histograms render exactly like single-process
ones — no re-bucketing, no quantile loss beyond the pow2 resolution the
ladder already has.

Naming is mechanical and therefore stable: ``metric_name`` lowercases,
squashes every non-``[a-zA-Z0-9_]`` rune to ``_``, prefixes
``jepsen_tpu_``, and suffixes by kind (``_total`` for counters,
``_seconds`` for histograms).  The TestMetricsSchema prom test pins that
every counter/gauge/histogram in the snapshot appears under this
mapping, so a rename here is a deliberate, test-visible act.

``validate_exposition`` is the minimal line-format validator the tests
and the telemetry smoke round-trip the output through: it checks the
comment grammar, the sample-line grammar, label syntax, and histogram
bucket monotonicity — the properties a real scraper would reject on.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Tuple

#: fixed metric prefix
PREFIX = "jepsen_tpu"

_SAN_RE = re.compile(r"[^a-zA-Z0-9_]+")
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?P<labels>\{[^{}]*\})?"
    r" (?P<value>[-+]?(?:[0-9]*\.?[0-9]+(?:[eE][-+]?[0-9]+)?|Inf|NaN))$")
_LABEL_RE = re.compile(r'^([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"$')
_TYPES = ("counter", "gauge", "histogram", "summary", "untyped")


def sanitize(name: str) -> str:
    out = _SAN_RE.sub("_", name.strip().lower()).strip("_")
    return out or "unnamed"


def metric_name(kind: str, name: str) -> str:
    """The stable exposition name for one snapshot entry.  ``kind`` is
    ``counter`` / ``gauge`` / ``histogram``."""
    base = f"{PREFIX}_{sanitize(name)}"
    if kind == "counter":
        return f"{base}_total"
    if kind == "histogram":
        return f"{base}_seconds"
    return base


def _fmt(v: Any) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _esc(v: Any) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _hist_lines(name: str, h: Dict[str, Any]) -> List[str]:
    full = metric_name("histogram", name)
    lines = [f"# HELP {full} {_help_text(name)}",
             f"# TYPE {full} histogram"]
    try:
        buckets = sorted((int(b), int(n))
                         for b, n in (h.get("buckets-us") or {}).items())
        count = int(h.get("count", 0))
        sum_s = float(h.get("sum-s", 0.0))
    except (TypeError, ValueError):
        return []
    cum = 0
    for upper_us, n in buckets:
        cum += n
        lines.append(f'{full}_bucket{{le="{repr(upper_us / 1e6)}"}} {cum}')
    lines.append(f'{full}_bucket{{le="+Inf"}} {count}')
    lines.append(f"{full}_sum {repr(sum_s)}")
    lines.append(f"{full}_count {count}")
    return lines


def _help_text(name: str) -> str:
    return f"jepsen-tpu snapshot entry {_esc(name)}"


def render_prom(snap: Dict[str, Any]) -> str:
    """One ``Metrics.snapshot()`` (service- or fleet-shaped) as
    Prometheus text exposition (content type
    ``text/plain; version=0.0.4``)."""
    lines: List[str] = []

    for name, v in sorted((snap.get("counters") or {}).items()):
        full = metric_name("counter", name)
        lines.append(f"# HELP {full} {_help_text(name)}")
        lines.append(f"# TYPE {full} counter")
        lines.append(f"{full} {_fmt(v)}")

    for name, v in sorted((snap.get("gauges") or {}).items()):
        if v is None:
            continue   # e.g. compiles-per-1k before the first dispatch
        full = metric_name("gauge", name)
        lines.append(f"# HELP {full} {_help_text(name)}")
        lines.append(f"# TYPE {full} gauge")
        lines.append(f"{full} {_fmt(v)}")

    for name, h in sorted((snap.get("histograms") or {}).items()):
        if isinstance(h, dict):
            lines.extend(_hist_lines(name, h))

    # fleet-only extras: per-worker staleness + alert volume, so one
    # scrape of the fleet endpoint carries the whole Watchtower state
    tele = snap.get("telemetry")
    if isinstance(tele, dict):
        full = f"{PREFIX}_worker_stale"
        lines.append(f"# HELP {full} 1 when the worker has missed 2+ "
                     "telemetry intervals")
        lines.append(f"# TYPE {full} gauge")
        for wid, entry in sorted((tele.get("workers") or {}).items()):
            stale = 1 if (isinstance(entry, dict) and entry.get("stale")) \
                else 0
            lines.append(f'{full}{{worker="{_esc(wid)}"}} {stale}')
    slo = snap.get("slo")
    if isinstance(slo, dict):
        full = f"{PREFIX}_slo_alerts_total"
        lines.append(f"# HELP {full} SLO alerts fired since start")
        lines.append(f"# TYPE {full} counter")
        lines.append(f"{full} {int(slo.get('fired-total', 0))}")

    return "\n".join(lines) + "\n"


def validate_exposition(text: str) -> Dict[str, List[Tuple[str, Dict[str, str], float]]]:
    """Minimal Prometheus text-format validator: raises ``ValueError``
    on any malformed line; returns ``{family: [(sample_name, labels,
    value), ...]}`` for assertions.  Checks line grammar, label syntax,
    TYPE declarations, and histogram bucket monotonicity."""
    families: Dict[str, List[Tuple[str, Dict[str, str], float]]] = {}
    types: Dict[str, str] = {}
    for ln, raw in enumerate(text.splitlines(), start=1):
        line = raw.rstrip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) < 3 or parts[1] not in ("HELP", "TYPE"):
                raise ValueError(f"line {ln}: malformed comment: {raw!r}")
            if parts[1] == "TYPE":
                if len(parts) != 4 or parts[3] not in _TYPES:
                    raise ValueError(f"line {ln}: bad TYPE: {raw!r}")
                types[parts[2]] = parts[3]
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"line {ln}: malformed sample: {raw!r}")
        name = m.group("name")
        labels: Dict[str, str] = {}
        body = (m.group("labels") or "{}")[1:-1].strip()
        if body:
            for pair in body.split(","):
                lm = _LABEL_RE.match(pair.strip())
                if lm is None:
                    raise ValueError(f"line {ln}: bad label {pair!r}")
                labels[lm.group(1)] = lm.group(2)
        value = float(m.group("value").replace("Inf", "inf"))
        family = name
        for suffix in ("_bucket", "_sum", "_count"):
            base = name[: -len(suffix)] if name.endswith(suffix) else None
            if base is not None and types.get(base) == "histogram":
                family = base
                break
        families.setdefault(family, []).append((name, labels, value))
    for family, samples in families.items():
        if types.get(family) != "histogram":
            continue
        _validate_hist(family, samples)
    return families


def _validate_hist(family: str,
                   samples: List[Tuple[str, Dict[str, str], float]]) -> None:
    buckets: List[Tuple[float, float]] = []
    count: Optional[float] = None
    for name, labels, value in samples:
        if name == f"{family}_bucket":
            le = labels.get("le")
            if le is None:
                raise ValueError(f"{family}: bucket without le label")
            buckets.append((float(le.replace("+Inf", "inf")), value))
        elif name == f"{family}_count":
            count = value
    prev = -1.0
    for le, v in sorted(buckets):
        if v < prev:
            raise ValueError(f"{family}: non-cumulative bucket at le={le}")
        prev = v
    if buckets and count is not None:
        inf_v = max(buckets)[1]
        if inf_v != count:
            raise ValueError(
                f"{family}: +Inf bucket {inf_v} != count {count}")
