"""Prometheus text-exposition rendering of a ``Metrics.snapshot()``.

The pow2 histogram ladder (obs/hist.py) maps directly onto Prometheus
histogram conventions: every bucket upper bound (microseconds) becomes a
cumulative ``le`` label in seconds, with the mandatory ``+Inf`` bucket
equal to the total count.  Because every process shares the identical
ladder, the fleet-merged histograms render exactly like single-process
ones — no re-bucketing, no quantile loss beyond the pow2 resolution the
ladder already has.

Naming is mechanical and therefore stable: ``metric_name`` lowercases,
squashes every non-``[a-zA-Z0-9_]`` rune to ``_``, prefixes
``jepsen_tpu_``, and suffixes by kind (``_total`` for counters,
``_seconds`` for histograms).  The TestMetricsSchema prom test pins that
every counter/gauge/histogram in the snapshot appears under this
mapping, so a rename here is a deliberate, test-visible act.

``validate_exposition`` is the minimal line-format validator the tests
and the telemetry smoke round-trip the output through: it checks the
comment grammar, the sample-line grammar, label syntax, and histogram
bucket monotonicity — the properties a real scraper would reject on.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Tuple

#: fixed metric prefix
PREFIX = "jepsen_tpu"

_SAN_RE = re.compile(r"[^a-zA-Z0-9_]+")
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?P<labels>\{[^{}]*\})?"
    r" (?P<value>[-+]?(?:[0-9]*\.?[0-9]+(?:[eE][-+]?[0-9]+)?|Inf|NaN))$")
_LABEL_RE = re.compile(r'^([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"$')
_TYPES = ("counter", "gauge", "histogram", "summary", "untyped")


def sanitize(name: str) -> str:
    out = _SAN_RE.sub("_", name.strip().lower()).strip("_")
    return out or "unnamed"


def metric_name(kind: str, name: str) -> str:
    """The stable exposition name for one snapshot entry.  ``kind`` is
    ``counter`` / ``gauge`` / ``histogram``."""
    base = f"{PREFIX}_{sanitize(name)}"
    if kind == "counter":
        return f"{base}_total"
    if kind == "histogram":
        return f"{base}_seconds"
    return base


def _fmt(v: Any) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _esc(v: Any) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _label_token(v: Any) -> str:
    """Scheduler bucket keys are ``str((kind, ident, shape))`` — flatten
    to a quote- and comma-free token (``wgl:cas-register:64``) so naive
    label splitters (including validate_exposition) survive the value."""
    s = re.sub(r"[\s'\"()\[\]{}]", "", str(v))
    return s.replace(",", ":") or "none"


def _hist_lines(name: str, h: Dict[str, Any]) -> List[str]:
    full = metric_name("histogram", name)
    lines = [f"# HELP {full} {_help_text(name)}",
             f"# TYPE {full} histogram"]
    try:
        buckets = sorted((int(b), int(n))
                         for b, n in (h.get("buckets-us") or {}).items())
        count = int(h.get("count", 0))
        sum_s = float(h.get("sum-s", 0.0))
    except (TypeError, ValueError):
        return []
    cum = 0
    for upper_us, n in buckets:
        cum += n
        lines.append(f'{full}_bucket{{le="{repr(upper_us / 1e6)}"}} {cum}')
    lines.append(f'{full}_bucket{{le="+Inf"}} {count}')
    lines.append(f"{full}_sum {repr(sum_s)}")
    lines.append(f"{full}_count {count}")
    return lines


def _help_text(name: str) -> str:
    return f"jepsen-tpu snapshot entry {_esc(name)}"


def render_prom(snap: Dict[str, Any]) -> str:
    """One ``Metrics.snapshot()`` (service- or fleet-shaped) as
    Prometheus text exposition (content type
    ``text/plain; version=0.0.4``)."""
    lines: List[str] = []

    for name, v in sorted((snap.get("counters") or {}).items()):
        full = metric_name("counter", name)
        lines.append(f"# HELP {full} {_help_text(name)}")
        lines.append(f"# TYPE {full} counter")
        lines.append(f"{full} {_fmt(v)}")

    for name, v in sorted((snap.get("gauges") or {}).items()):
        if v is None:
            continue   # e.g. compiles-per-1k before the first dispatch
        full = metric_name("gauge", name)
        lines.append(f"# HELP {full} {_help_text(name)}")
        lines.append(f"# TYPE {full} gauge")
        lines.append(f"{full} {_fmt(v)}")

    for name, h in sorted((snap.get("histograms") or {}).items()):
        if isinstance(h, dict):
            lines.extend(_hist_lines(name, h))

    # fleet-only extras: per-worker staleness + alert volume, so one
    # scrape of the fleet endpoint carries the whole Watchtower state
    tele = snap.get("telemetry")
    if isinstance(tele, dict):
        full = f"{PREFIX}_worker_stale"
        lines.append(f"# HELP {full} 1 when the worker has missed 2+ "
                     "telemetry intervals")
        lines.append(f"# TYPE {full} gauge")
        for wid, entry in sorted((tele.get("workers") or {}).items()):
            stale = 1 if (isinstance(entry, dict) and entry.get("stale")) \
                else 0
            lines.append(f'{full}{{worker="{_esc(wid)}"}} {stale}')
    slo = snap.get("slo")
    if isinstance(slo, dict):
        full = f"{PREFIX}_slo_alerts_total"
        lines.append(f"# HELP {full} SLO alerts fired since start")
        lines.append(f"# TYPE {full} counter")
        lines.append(f"{full} {int(slo.get('fired-total', 0))}")

    # per-tenant cut: labeled families so one scrape answers "which
    # tenant is burning" without parsing the JSON snapshot.  Tenant
    # NAMES are labels by design; token material never enters the
    # snapshot in the first place (serve/tenants.py, SEC01).
    tenants = snap.get("tenants")
    if isinstance(tenants, dict) and tenants:
        for key, fam in (("requests-completed", "tenant_requests"),
                         ("verdicts-unknown", "tenant_unknown_verdicts"),
                         ("deadline-expired", "tenant_deadline_expired"),
                         ("quota-rejections", "tenant_quota_rejections"),
                         ("admitted", "tenant_admitted")):
            full = f"{PREFIX}_{fam}_total"
            lines.append(f"# HELP {full} per-tenant {_esc(key)}")
            lines.append(f"# TYPE {full} counter")
            for name, cut in sorted(tenants.items()):
                v = int(cut.get(key) or 0)
                lines.append(f'{full}{{tenant="{_esc(name)}"}} {v}')
        for key, fam, scale in (("open", "tenant_open_requests", 1.0),
                                ("quota", "tenant_quota", 1.0),
                                ("priority", "tenant_priority", 1.0),
                                ("p99-dispatch-verdict-us",
                                 "tenant_p99_dispatch_verdict_seconds",
                                 1e-6)):
            full = f"{PREFIX}_{fam}"
            lines.append(f"# HELP {full} per-tenant {_esc(key)}")
            lines.append(f"# TYPE {full} gauge")
            for name, cut in sorted(tenants.items()):
                v = cut.get(key)
                if v is None:
                    continue   # unlimited quota / no latency data yet
                lines.append(f'{full}{{tenant="{_esc(name)}"}} '
                             f"{_fmt(float(v) * scale)}")

    # queue shape: per-bucket depth (the autoscaler's occupancy input,
    # broken out by (kind, ident, shape) bucket key)
    queue = snap.get("queue")
    if isinstance(queue, dict):
        buckets = queue.get("buckets")
        if isinstance(buckets, dict) and buckets:
            full = f"{PREFIX}_queue_bucket_depth"
            lines.append(f"# HELP {full} queued cells per scheduler bucket")
            lines.append(f"# TYPE {full} gauge")
            for bucket, n in sorted(buckets.items()):
                lines.append(
                    f'{full}{{bucket="{_label_token(bucket)}"}} {int(n)}')

    # fission plane (engine splitters + shrink recursion + Hydra's
    # fleet-edge counters): the section nests its own counters and
    # histograms under snap["fission"], so it needs its own renderer —
    # names prefixed ``fission_`` to keep them out of the flat
    # counter namespace
    fission = snap.get("fission")
    if isinstance(fission, dict):
        for key, v in sorted(fission.items()):
            if key == "histograms" or not isinstance(v, (int, float)):
                continue
            full = f"{PREFIX}_fission_{sanitize(key)}_total"
            lines.append(f"# HELP {full} fission counter {_esc(key)}")
            lines.append(f"# TYPE {full} counter")
            lines.append(f"{full} {_fmt(v)}")
        for name, h in sorted((fission.get("histograms") or {}).items()):
            if isinstance(h, dict):
                lines.extend(_hist_lines(name, h))

    # Governor (serve/autoscale.py): decision counters + pending
    # structured scale requests, distinct from the fleet's
    # autoscale-ups/-downs action counters rendered above
    scale = snap.get("autoscale")
    if isinstance(scale, dict):
        for key, v in sorted((scale.get("counters") or {}).items()):
            full = f"{PREFIX}_governor_{sanitize(key)}_total"
            lines.append(f"# HELP {full} governor decision counter "
                         f"{_esc(key)}")
            lines.append(f"# TYPE {full} counter")
            lines.append(f"{full} {int(v)}")
        full = f"{PREFIX}_governor_scale_requests_pending"
        lines.append(f"# HELP {full} structured scale requests awaiting "
                     "the deployment layer")
        lines.append(f"# TYPE {full} gauge")
        lines.append(f"{full} {len(scale.get('scale-requests') or [])}")

    return "\n".join(lines) + "\n"


def validate_exposition(text: str) -> Dict[str, List[Tuple[str, Dict[str, str], float]]]:
    """Minimal Prometheus text-format validator: raises ``ValueError``
    on any malformed line; returns ``{family: [(sample_name, labels,
    value), ...]}`` for assertions.  Checks line grammar, label syntax,
    TYPE declarations, and histogram bucket monotonicity."""
    families: Dict[str, List[Tuple[str, Dict[str, str], float]]] = {}
    types: Dict[str, str] = {}
    for ln, raw in enumerate(text.splitlines(), start=1):
        line = raw.rstrip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) < 3 or parts[1] not in ("HELP", "TYPE"):
                raise ValueError(f"line {ln}: malformed comment: {raw!r}")
            if parts[1] == "TYPE":
                if len(parts) != 4 or parts[3] not in _TYPES:
                    raise ValueError(f"line {ln}: bad TYPE: {raw!r}")
                types[parts[2]] = parts[3]
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"line {ln}: malformed sample: {raw!r}")
        name = m.group("name")
        labels: Dict[str, str] = {}
        body = (m.group("labels") or "{}")[1:-1].strip()
        if body:
            for pair in body.split(","):
                lm = _LABEL_RE.match(pair.strip())
                if lm is None:
                    raise ValueError(f"line {ln}: bad label {pair!r}")
                labels[lm.group(1)] = lm.group(2)
        value = float(m.group("value").replace("Inf", "inf"))
        family = name
        for suffix in ("_bucket", "_sum", "_count"):
            base = name[: -len(suffix)] if name.endswith(suffix) else None
            if base is not None and types.get(base) == "histogram":
                family = base
                break
        families.setdefault(family, []).append((name, labels, value))
    for family, samples in families.items():
        if types.get(family) != "histogram":
            continue
        _validate_hist(family, samples)
    return families


def _validate_hist(family: str,
                   samples: List[Tuple[str, Dict[str, str], float]]) -> None:
    buckets: List[Tuple[float, float]] = []
    count: Optional[float] = None
    for name, labels, value in samples:
        if name == f"{family}_bucket":
            le = labels.get("le")
            if le is None:
                raise ValueError(f"{family}: bucket without le label")
            buckets.append((float(le.replace("+Inf", "inf")), value))
        elif name == f"{family}_count":
            count = value
    prev = -1.0
    for le, v in sorted(buckets):
        if v < prev:
            raise ValueError(f"{family}: non-cumulative bucket at le={le}")
        prev = v
    if buckets and count is not None:
        inf_v = max(buckets)[1]
        if inf_v != count:
            raise ValueError(
                f"{family}: +Inf bucket {inf_v} != count {count}")
