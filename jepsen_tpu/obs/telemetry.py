"""Watchtower: the push-based fleet telemetry plane.

PR 10's scrape is pull-only — a supervisor walking STATUS frames cannot
notice a silent worker, because silence looks exactly like "nothing to
report".  Watchtower inverts the direction: every worker pushes a
TELEMETRY frame (its ``Metrics.snapshot()`` plus pid/uptime/sequence)
over the already-open wire on a ``JEPSEN_TPU_TELEMETRY_S`` cadence, and
the fleet side lands each push in a bounded per-worker time-series ring
(``TelemetryStore``).  The store derives what the raw snapshots cannot
say alone:

- windowed rates — histories/s and dispatches/s from counter deltas,
  ``unknown-rate`` from the verdict counters, ``compiles-per-1k`` off
  the gauge once the worker has enough cumulative dispatches for the
  ratio to mean anything (cold-start gating, see
  ``MIN_DISPATCHES_FOR_COMPILE_RATE``);
- ``breaker-open-s`` — wall seconds each worker's circuit breaker has
  spent OPEN, integrated from the fleet heartbeat's observations;
- *staleness* — a worker whose newest push is older than
  ``STALE_AFTER_INTERVALS`` push intervals is flagged stale.  This is
  the lease/heartbeat primitive the multi-host supervisor needs: a
  remote worker that stops pushing is indistinguishable from a dead
  one, and both must be evicted the same way.  A worker that has never
  pushed gets ``startup_grace_s`` of extra silence allowance first — a
  spawned worker process spends real wall time (interpreter + JAX
  import) before its first frame can possibly exist, and the staleness
  clock must not race the boot; once the first push lands, the strict
  2-interval contract governs.

The store's lock is a leaf in the declared lock order
(lint/lock_order.py, ``obs-telemetry``): pushes arrive on wire reader
threads and observations on the fleet heartbeat thread, both of which
may already hold locks earlier in the serve chain.

The module also hosts a small process-wide gauge registry
(``set_gauge``/``process_gauges``) so tiers without a ``Metrics``
instance of their own — the monitor's epoch loop, concretely — can
publish scalars (``epochs-behind-live``) that every snapshot in the
process picks up and every telemetry push therefore carries.
"""

from __future__ import annotations

import os
import threading
from collections import deque
from typing import Any, Dict, List, Optional

from jepsen_tpu.clock import mono_now

#: default push cadence, seconds (env-overridable)
DEFAULT_TELEMETRY_S = 1.0

#: a worker is stale after this many missed push intervals
STALE_AFTER_INTERVALS = 2

#: per-worker ring length: at the 1 s default cadence this is ~2 min of
#: history per worker — enough for every burn window shipped in slo.py
DEFAULT_RING = 128

#: compiles-per-1k is a *steady-state* ratio: below this many cumulative
#: dispatches it is all cold-start noise (1 compile over 2 dispatches
#: reads as 500/1k) and the store reports None instead — otherwise every
#: fresh worker trips the compile-pressure SLO on its first real push
MIN_DISPATCHES_FOR_COMPILE_RATE = 100


def telemetry_interval_s() -> float:
    """The configured push cadence: ``JEPSEN_TPU_TELEMETRY_S`` (seconds,
    <= 0 disables pushing) or the 1 s default.  Read at call time, not
    import time, so tests and the CLI can retune a live process."""
    raw = os.environ.get("JEPSEN_TPU_TELEMETRY_S", "")
    try:
        return float(raw) if raw else DEFAULT_TELEMETRY_S
    except ValueError:
        return DEFAULT_TELEMETRY_S


# -- process-wide gauges -------------------------------------------------------

_GAUGE_LOCK = threading.Lock()
_GAUGES: Dict[str, float] = {}


def set_gauge(name: str, value: float) -> None:
    """Publish a process-wide gauge (e.g. the monitor's
    ``epochs-behind-live``).  Last write wins; snapshot readers see the
    latest sample."""
    with _GAUGE_LOCK:
        _GAUGES[name] = float(value)


def process_gauges() -> Dict[str, float]:
    """A copy of every process-wide gauge published so far."""
    with _GAUGE_LOCK:
        return dict(_GAUGES)


# -- the store -----------------------------------------------------------------

def _counter(payload: Dict[str, Any], name: str) -> int:
    m = payload.get("metrics") or {}
    try:
        return int((m.get("counters") or {}).get(name, 0))
    except (TypeError, ValueError):
        return 0


def _gauge(payload: Dict[str, Any], name: str) -> Optional[float]:
    m = payload.get("metrics") or {}
    v = (m.get("gauges") or {}).get(name)
    try:
        return float(v) if v is not None else None
    except (TypeError, ValueError):
        return None


def _hist_p99_us(payload: Dict[str, Any], hist: str) -> Optional[float]:
    m = payload.get("metrics") or {}
    h = (m.get("histograms") or {}).get(hist)
    if not isinstance(h, dict) or not h.get("count"):
        return None
    try:
        return float(h["p99"]) * 1e6
    except (TypeError, ValueError, KeyError):
        return None


def _hist_buckets(payload: Dict[str, Any], hist: str) -> Dict[int, int]:
    m = payload.get("metrics") or {}
    h = (m.get("histograms") or {}).get(hist)
    if not isinstance(h, dict):
        return {}
    try:
        return {int(b): int(n)
                for b, n in (h.get("buckets-us") or {}).items()}
    except (TypeError, ValueError):
        return {}


def _windowed_p99_us(newest: Dict[str, Any], oldest: Dict[str, Any],
                     hist: str) -> Optional[float]:
    """p99 over only the observations that landed between two pushes —
    bucket-wise subtraction of cumulative pow2 histograms.  The
    cumulative p99 is useless as an SLO signal once a cold-start outlier
    is in the ring (a 2 s first-compile dispatch pins it forever);
    the windowed delta is what 'latency right now' actually means.
    None when the window saw no observations."""
    delta = dict(_hist_buckets(newest, hist))
    for b, n in _hist_buckets(oldest, hist).items():
        delta[b] = delta.get(b, 0) - n
    delta = {b: n for b, n in delta.items() if n > 0}
    count = sum(delta.values())
    if count <= 0:
        return None
    target = 0.99 * count
    seen = 0
    for b in sorted(delta):
        seen += delta[b]
        if seen >= target:
            return float(b)
    return float(max(delta))  # pragma: no cover - defensive


class TelemetryStore:
    """Bounded per-worker time-series of TELEMETRY pushes, plus the
    derived fleet-health signals (rates, breaker-open time, staleness).

    Keys are whatever the fleet uses to name workers (slot ints, plus
    the ``"fleet"`` pseudo-worker for the fleet process's own metrics).
    ``register`` pins a worker's birth time so one that *never* pushes
    still goes stale instead of staying invisible forever.
    """

    def __init__(self, interval_s: Optional[float] = None,
                 ring: int = DEFAULT_RING, *,
                 startup_grace_s: float = 0.0):
        self.interval_s = float(interval_s if interval_s is not None
                                else telemetry_interval_s())
        if self.interval_s <= 0:
            self.interval_s = DEFAULT_TELEMETRY_S
        # extra silence allowance for workers that have NEVER pushed
        # (see module docstring); 0.0 keeps the strict 2-interval
        # contract for in-process stores
        self.startup_grace_s = max(float(startup_grace_s), 0.0)
        self._ring = max(int(ring), 2)
        self._lock = threading.Lock()
        self._rings: Dict[Any, deque] = {}
        self._born: Dict[Any, float] = {}
        self._pushes: Dict[Any, int] = {}
        # breaker integration: {wid: [is_open, since_t, accumulated_s]}
        self._breaker: Dict[Any, List[Any]] = {}
        self._evictions = 0

    # -- ingest ----------------------------------------------------------------

    def register(self, worker: Any, now: Optional[float] = None) -> None:
        """Declare a worker exists (staleness clock starts now even if
        it never manages a single push)."""
        now = mono_now() if now is None else now
        with self._lock:
            self._born.setdefault(worker, now)
            self._rings.setdefault(worker, deque(maxlen=self._ring))

    def record_push(self, worker: Any, payload: Dict[str, Any],
                    now: Optional[float] = None) -> Dict[str, Any]:
        """Land one TELEMETRY payload; returns the stamped entry."""
        now = mono_now() if now is None else now
        if not isinstance(payload, dict):
            payload = {}
        entry = {"t": now, "payload": payload}
        with self._lock:
            ring = self._rings.get(worker)
            if ring is None:
                ring = self._rings[worker] = deque(maxlen=self._ring)
                self._born.setdefault(worker, now)
            ring.append(entry)
            self._pushes[worker] = self._pushes.get(worker, 0) + 1
        return entry

    def evict(self, worker: Any) -> bool:
        """Forget a worker the registry evicted (lease expiry): its
        ring, birth time, push count, and breaker integral all go — the
        staleness sweep must not alert on a member that no longer
        exists, and a later re-registration under the same key starts a
        fresh staleness clock.  Returns True when the worker was known."""
        with self._lock:
            known = worker in self._rings or worker in self._born
            self._rings.pop(worker, None)
            self._born.pop(worker, None)
            self._pushes.pop(worker, None)
            self._breaker.pop(worker, None)
            if known:
                self._evictions += 1
            return known

    def observe_breaker(self, worker: Any, is_open: bool,
                        now: Optional[float] = None) -> None:
        """Integrate breaker state over time: called from the fleet
        heartbeat on every sweep; accumulates OPEN wall-seconds."""
        now = mono_now() if now is None else now
        with self._lock:
            st = self._breaker.get(worker)
            if st is None:
                self._breaker[worker] = [bool(is_open), now, 0.0]
                return
            was_open, since, acc = st
            if was_open:
                acc += max(now - since, 0.0)
            self._breaker[worker] = [bool(is_open), now, acc]

    # -- reads -----------------------------------------------------------------

    def workers(self) -> List[Any]:
        with self._lock:
            return sorted(self._rings, key=str)

    def latest(self, worker: Any) -> Optional[Dict[str, Any]]:
        with self._lock:
            ring = self._rings.get(worker)
            return dict(ring[-1]) if ring else None

    def push_count(self, worker: Any) -> int:
        with self._lock:
            return self._pushes.get(worker, 0)

    def last_push_age_s(self, worker: Any,
                        now: Optional[float] = None) -> Optional[float]:
        """Seconds since the newest push — falling back to the worker's
        registration time when it has never pushed; None for a worker
        the store has never heard of at all."""
        now = mono_now() if now is None else now
        with self._lock:
            ring = self._rings.get(worker)
            if ring:
                return max(now - ring[-1]["t"], 0.0)
            born = self._born.get(worker)
            return max(now - born, 0.0) if born is not None else None

    def stale_s(self, worker: Any, now: Optional[float] = None,
                ) -> Optional[float]:
        """How far past the staleness threshold this worker is (0.0 when
        healthy); None when unknown.  The threshold is 2 push intervals
        from the newest push — or, for a worker that has never pushed,
        the larger of that and ``startup_grace_s`` measured from
        registration (a booting worker process cannot push yet; a booted
        one that goes silent must not get the grace twice)."""
        now = mono_now() if now is None else now
        with self._lock:
            ring = self._rings.get(worker)
            last_push_t = ring[-1]["t"] if ring else None
            born = self._born.get(worker)
        threshold = STALE_AFTER_INTERVALS * self.interval_s
        if last_push_t is not None:
            age = max(now - last_push_t, 0.0)
        elif born is not None:
            age = max(now - born, 0.0)
            threshold = max(threshold, self.startup_grace_s)
        else:
            return None
        return max(age - threshold, 0.0)

    def is_stale(self, worker: Any, now: Optional[float] = None) -> bool:
        s = self.stale_s(worker, now=now)
        return bool(s and s > 0.0)

    def stale_workers(self, now: Optional[float] = None) -> List[Any]:
        now = mono_now() if now is None else now
        return [w for w in self.workers() if self.is_stale(w, now=now)]

    def breaker_open_s(self, worker: Any,
                       now: Optional[float] = None) -> float:
        """Total OPEN wall-seconds integrated so far (including the
        currently-running OPEN stretch, if any)."""
        now = mono_now() if now is None else now
        with self._lock:
            st = self._breaker.get(worker)
            if st is None:
                return 0.0
            is_open, since, acc = st
            return acc + (max(now - since, 0.0) if is_open else 0.0)

    def rates(self, worker: Any, window_s: Optional[float] = None,
              ) -> Dict[str, Any]:
        """Windowed deltas between the oldest in-window push and the
        newest: the rate view a dashboard wants and a raw cumulative
        snapshot cannot give.  Empty-ish dict when fewer than two pushes
        are in the window."""
        window_s = (STALE_AFTER_INTERVALS * 4 * self.interval_s
                    if window_s is None else window_s)
        with self._lock:
            ring = self._rings.get(worker)
            entries = list(ring) if ring else []
        if not entries:
            return {}
        newest = entries[-1]
        cutoff = newest["t"] - window_s
        in_window = [e for e in entries if e["t"] >= cutoff]
        total_dispatches = (
            _counter(newest["payload"], "dispatches")
            + int((((newest["payload"].get("metrics") or {})
                    .get("megabatch") or {}).get("dispatches", 0) or 0)))
        out: Dict[str, Any] = {
            "compiles-per-1k": (
                _gauge(newest["payload"], "compiles-per-1k-dispatches")
                if total_dispatches >= MIN_DISPATCHES_FOR_COMPILE_RATE
                else None),
            "p99-dispatch-verdict-us":
                _hist_p99_us(newest["payload"], "edge:dispatch->verdict"),
            # worst per-stream streaming-monitor lag, in epochs, off the
            # newest push (Metrics.snapshot folds the per-stream gauges
            # to their max) — the monitor_lag_epochs SLO's signal
            "monitor-lag-epochs":
                _gauge(newest["payload"], "monitor-lag-epochs"),
        }
        if len(in_window) < 2:
            return out
        oldest = in_window[0]
        dt = newest["t"] - oldest["t"]
        if dt <= 0:
            return out
        # with a real window, the latency signal goes windowed: p99 of
        # only the observations inside it (None when the window is
        # quiet), not the forever-pinned cumulative p99
        out["p99-dispatch-verdict-us"] = _windowed_p99_us(
            newest["payload"], oldest["payload"], "edge:dispatch->verdict")
        d_completed = (_counter(newest["payload"], "requests-completed")
                       - _counter(oldest["payload"], "requests-completed"))
        d_unknown = (_counter(newest["payload"], "verdicts-unknown")
                     - _counter(oldest["payload"], "verdicts-unknown"))
        d_dispatch = (_counter(newest["payload"], "dispatches")
                      - _counter(oldest["payload"], "dispatches"))
        out.update({
            "window-s": round(dt, 3),
            "hist-per-s": round(max(d_completed, 0) / dt, 4),
            "dispatch-per-s": round(max(d_dispatch, 0) / dt, 4),
            "unknown-rate": (round(max(d_unknown, 0) / d_completed, 4)
                             if d_completed > 0 else None),
        })
        return out

    def tenant_rates(self, worker: Any, tenant: str,
                     window_s: Optional[float] = None) -> Dict[str, Any]:
        """The per-tenant analogue of :meth:`rates`: windowed deltas over
        one tenant's cut of a worker's pushes — the ``tenants`` snapshot
        section for the counters, the ``tenant:<name>:edge:...``
        histogram for the latency signal.  Normally read against the
        ``"fleet"`` pseudo-worker, whose pushes carry the fleet-wide
        tenant accounting (obs/slo.py ``tenant_slo_specs``)."""
        window_s = (STALE_AFTER_INTERVALS * 4 * self.interval_s
                    if window_s is None else window_s)
        with self._lock:
            ring = self._rings.get(worker)
            entries = list(ring) if ring else []
        if not entries:
            return {}

        def tcounter(payload: Dict[str, Any], name: str) -> int:
            m = payload.get("metrics") or {}
            cut = (m.get("tenants") or {}).get(tenant) or {}
            try:
                return int(cut.get(name, 0) or 0)
            except (TypeError, ValueError):
                return 0

        hist = f"tenant:{tenant}:edge:dispatch->verdict"
        newest = entries[-1]
        out: Dict[str, Any] = {
            "p99-dispatch-verdict-us": _hist_p99_us(newest["payload"],
                                                    hist),
        }
        cutoff = newest["t"] - window_s
        in_window = [e for e in entries if e["t"] >= cutoff]
        if len(in_window) < 2:
            return out
        oldest = in_window[0]
        dt = newest["t"] - oldest["t"]
        if dt <= 0:
            return out
        out["p99-dispatch-verdict-us"] = _windowed_p99_us(
            newest["payload"], oldest["payload"], hist)
        d_completed = (tcounter(newest["payload"], "requests-completed")
                       - tcounter(oldest["payload"], "requests-completed"))
        d_unknown = (tcounter(newest["payload"], "verdicts-unknown")
                     - tcounter(oldest["payload"], "verdicts-unknown"))
        out.update({
            "window-s": round(dt, 3),
            "hist-per-s": round(max(d_completed, 0) / dt, 4),
            "unknown-rate": (round(max(d_unknown, 0) / d_completed, 4)
                             if d_completed > 0 else None),
        })
        return out

    # -- export ----------------------------------------------------------------

    def snapshot(self, now: Optional[float] = None) -> Dict[str, Any]:
        """The per-worker health summary the fleet snapshot embeds."""
        now = mono_now() if now is None else now
        out: Dict[str, Any] = {"interval-s": self.interval_s,
                               "workers": {}}
        for w in self.workers():
            latest = self.latest(w)
            payload = (latest or {}).get("payload") or {}
            out["workers"][str(w)] = {
                "pushes": self.push_count(w),
                "last-push-age-s": (
                    round(self.last_push_age_s(w, now=now) or 0.0, 3)),
                "stale": self.is_stale(w, now=now),
                "pid": payload.get("pid"),
                "generation": payload.get("generation"),
                "uptime-s": payload.get("uptime-s"),
                "breaker-open-s": round(self.breaker_open_s(w, now=now), 3),
                "rates": self.rates(w),
            }
        out["stale-workers"] = [str(w) for w in self.stale_workers(now=now)]
        with self._lock:
            out["evictions"] = self._evictions
        return out

    def dump(self) -> Dict[str, Any]:
        """Full ring contents (minus the bulky per-push metrics bodies'
        trace sections, already stripped at push time) — the artifact
        the telemetry smoke uploads."""
        with self._lock:
            rings = {str(w): [dict(e) for e in ring]
                     for w, ring in self._rings.items()}
        return {"interval-s": self.interval_s, "rings": rings}
