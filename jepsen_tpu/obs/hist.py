"""Log-bucketed latency and compile-time histograms.

Buckets ride the same pow2 ladder the serve shape buckets use
(``serve.buckets.pow2_at_least``): an observation of ``s`` seconds
lands in the bucket whose upper bound is the smallest power of two of
microseconds >= ``s``.  That keeps the bucket universe bounded (a
64-second tail is ~36 rungs from the 1 µs floor), makes histograms from
different processes mergeable by plain bucket-wise addition (every
process has the identical ladder), and means a compile-time histogram
keyed by an engine-cache bucket key reports quantiles over exactly the
shapes the compile cache distinguishes.

Percentiles are cumulative-walk upper bounds: ``p99`` is the upper edge
of the first bucket at or past the 99th percentile of the count mass —
conservative (never under-reports) and exact enough at pow2 resolution
for dashboard work.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Iterable, List, Optional

#: histogram floor: one microsecond
_FLOOR_US = 1


def _bucket_of(us: int) -> int:
    # lazy import: serve.metrics imports this module, and serve's package
    # __init__ imports metrics — a module-scope import here would close
    # an import cycle through jepsen_tpu.serve
    from jepsen_tpu.serve.buckets import pow2_at_least
    return pow2_at_least(max(us, _FLOOR_US), _FLOOR_US)


class Histogram:
    """One unlocked log-bucketed histogram (callers hold the set lock)."""

    __slots__ = ("buckets", "count", "sum_s")

    def __init__(self) -> None:
        self.buckets: Dict[int, int] = {}
        self.count = 0
        self.sum_s = 0.0

    def observe(self, seconds: float) -> None:
        us = int(seconds * 1e6)
        b = _bucket_of(us)
        self.buckets[b] = self.buckets.get(b, 0) + 1
        self.count += 1
        self.sum_s += max(seconds, 0.0)

    def merge_counts(self, buckets: Dict[int, int], count: int,
                     sum_s: float) -> None:
        for b, n in buckets.items():
            self.buckets[b] = self.buckets.get(b, 0) + n
        self.count += count
        self.sum_s += sum_s

    def percentile(self, p: float) -> float:
        """Upper bound of the bucket at the ``p``-th percentile, in
        seconds (0.0 for an empty histogram)."""
        if self.count <= 0:
            return 0.0
        target = p / 100.0 * self.count
        seen = 0
        for b in sorted(self.buckets):
            seen += self.buckets[b]
            if seen >= target:
                return b / 1e6
        return max(self.buckets) / 1e6  # pragma: no cover - defensive

    def snapshot(self) -> Dict[str, Any]:
        return {"count": self.count,
                "sum-s": round(self.sum_s, 6),
                "p50": self.percentile(50),
                "p90": self.percentile(90),
                "p99": self.percentile(99),
                "buckets-us": {str(b): self.buckets[b]
                               for b in sorted(self.buckets)}}


class HistogramSet:
    """A thread-safe named family of histograms (the unit Metrics and
    the compile sites observe into, and the unit scrapes merge)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._hists: Dict[str, Histogram] = {}

    def observe(self, name: str, seconds: float) -> None:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram()
            h.observe(seconds)

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {name: h.snapshot()
                    for name, h in sorted(self._hists.items())}


#: process-wide count of malformed per-histogram entries dropped by
#: ``merge_hist_snapshots`` — silently skipping a worker's corrupt
#: histogram is the right availability call (a scrape must not fail
#: because one worker was mid-crash), but the drop has to be visible
#: somewhere, so it lands in every ``Metrics.snapshot()``'s counters as
#: ``hist-merge-skipped``.  Whole-snapshot ``None`` (the "worker
#: unreachable" convention) is NOT counted: that is the protocol, not
#: corruption.
_MERGE_LOCK = threading.Lock()
_MERGE_SKIPPED = 0


def _note_merge_skip(n: int = 1) -> None:
    global _MERGE_SKIPPED
    with _MERGE_LOCK:
        _MERGE_SKIPPED += n


def merge_skipped_count() -> int:
    with _MERGE_LOCK:
        return _MERGE_SKIPPED


def merge_hist_snapshots(
        snaps: Iterable[Optional[Dict[str, Dict[str, Any]]]],
) -> Dict[str, Dict[str, Any]]:
    """Bucket-wise merge of ``HistogramSet.snapshot()`` documents from
    several processes into one fleet-wide document.  Identical ladders
    make the merge exact; malformed entries are skipped — and counted
    (``merge_skipped_count``) — so a scrape neither fails because one
    worker was mid-crash nor hides that its data was dropped."""
    merged: Dict[str, Histogram] = {}
    for snap in snaps:
        if not isinstance(snap, dict):
            continue
        for name, s in snap.items():
            if not isinstance(s, dict):
                _note_merge_skip()
                continue
            try:
                buckets = {int(b): int(n)
                           for b, n in (s.get("buckets-us") or {}).items()}
                count = int(s.get("count", 0))
                sum_s = float(s.get("sum-s", 0.0))
            except (TypeError, ValueError):
                _note_merge_skip()
                continue
            h = merged.get(name)
            if h is None:
                h = merged[name] = Histogram()
            h.merge_counts(buckets, count, sum_s)
    return {name: h.snapshot() for name, h in sorted(merged.items())}


#: process-wide compile/build histograms, one per engine-cache bucket
#: key family — global like the engine cache itself, surfaced through
#: every Metrics.snapshot() in the process
COMPILES = HistogramSet()


def observe_compile(name: str, seconds: float) -> None:
    COMPILES.observe(name, seconds)


def compile_hist_stats() -> Dict[str, Dict[str, Any]]:
    return COMPILES.snapshot()


def compile_event_count() -> int:
    """Total compile events observed process-wide — the numerator of the
    steady-state ``compiles-per-1k-dispatches`` gauge, and the number
    the megabatch CI smoke asserts goes flat once the ladder is warm."""
    return sum(int(s.get("count", 0))
               for s in COMPILES.snapshot().values())


#: process-wide monitor epoch-wall histograms, one per
#: ``monitor-epoch:<kind>:<stream>`` family — global like the monitors
#: themselves (they outlive any one service), surfaced through every
#: Metrics.snapshot() next to the compile histograms.  The stream bench
#: reads these to assert per-epoch wall stays flat in history length.
MONITOR_EPOCHS = HistogramSet()


def observe_monitor_epoch(name: str, seconds: float) -> None:
    MONITOR_EPOCHS.observe(name, seconds)


def monitor_epoch_hist_stats() -> Dict[str, Dict[str, Any]]:
    return MONITOR_EPOCHS.snapshot()


def timed_first_call(fn, name: str):
    """Wrap a jitted callable so its *first* invocation — the one that
    pays XLA compilation — is timed into the compile histogram ``name``
    and the flight recorder.  Later calls go straight through with one
    list-lookup of overhead.  The build sites (wgl/batch/megabatch
    cache misses) apply this to the callable they cache, so the
    histogram measures real compile latency per cache bucket key, not
    just host-side trace/wrap time."""
    fired: List[bool] = []

    def first_timed(*args, **kwargs):
        if fired:
            return fn(*args, **kwargs)
        from jepsen_tpu.clock import mono_now
        from jepsen_tpu.obs.recorder import RECORDER
        t0 = mono_now()
        out = fn(*args, **kwargs)
        dt = mono_now() - t0
        fired.append(True)
        observe_compile(name, dt)
        RECORDER.record("compile", name, dur_s=dt)
        return out

    return first_timed
