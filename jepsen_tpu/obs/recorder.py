"""The flight recorder: a bounded process-wide ring of structured events.

One ``FlightRecorder`` per process (``RECORDER``), recording
dispatch / compile / transfer / retry / chaos / monitor / alert events
into a ``deque(maxlen=...)`` ring.  Disabled by default but armable at
runtime (``POST /recorder?on=1``, ``Fleet.set_recorder``) so an operator
can open a capture window around a live alert without restarting: the
off path is a single attribute check (``if not self.enabled: return``)
so leaving the instrumentation compiled into the hot paths costs
~nothing, and the
ring bound means the on path cannot grow memory under sustained load —
old events fall off the back, ``recorded``/``buffered`` in ``stats()``
tell you how much history survived.

Events carry the local monotonic timestamp plus pid/tid and optional
trace/span ids; export converts them to absolute microseconds using a
wall anchor captured once at construction (the same re-anchoring
discipline as request spans) and writes Chrome trace-event JSON through
``atomic_io`` — the exported file is loadable in Perfetto as-is.

Knobs: ``JEPSEN_TPU_FLIGHT_RECORDER`` (truthy enables at import),
``JEPSEN_TPU_FLIGHT_EVENTS`` (ring capacity, default 4096).
"""

from __future__ import annotations

import os
import threading
from collections import deque
from typing import Any, Dict, List, Optional

from jepsen_tpu.clock import mono_now
from jepsen_tpu.obs.trace import chrome_document, wall_anchor

#: the structured event categories the serving tier records — "monitor"
#: is the epoch spans of the streaming checkers, "alert" the SLO engine's
#: breach instants (obs/slo.py)
CATEGORIES = ("dispatch", "compile", "transfer", "retry", "chaos",
              "monitor", "alert")


class FlightRecorder:
    def __init__(self, capacity: Optional[int] = None,
                 enabled: Optional[bool] = None) -> None:
        if capacity is None:
            capacity = int(os.environ.get("JEPSEN_TPU_FLIGHT_EVENTS",
                                          "4096"))
        if enabled is None:
            enabled = os.environ.get("JEPSEN_TPU_FLIGHT_RECORDER",
                                     "") not in ("", "0")
        self.capacity = max(int(capacity), 1)
        self.enabled = bool(enabled)
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._recorded = 0
        # export anchor: relative monotonic timestamps re-anchor onto
        # this one wall reading; never used for deadlines
        self._anchor_unix = wall_anchor()
        self._anchor_mono = mono_now()

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def record(self, cat: str, name: str, *, dur_s: Optional[float] = None,
               trace_id: Optional[str] = None,
               span_id: Optional[str] = None,
               args: Optional[Dict[str, Any]] = None) -> None:
        if not self.enabled:  # the ~0-cost off path
            return
        evt: Dict[str, Any] = {"ts": mono_now(), "pid": os.getpid(),
                               "tid": threading.get_ident(),
                               "cat": cat, "name": name}
        if dur_s is not None:
            evt["dur-s"] = dur_s
        if trace_id is not None:
            evt["trace-id"] = trace_id
        if span_id is not None:
            evt["span-id"] = span_id
        if args:
            evt["args"] = dict(args)
        with self._lock:
            self._ring.append(evt)
            self._recorded += 1

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._recorded = 0

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            buffered = len(self._ring)
            recorded = self._recorded
        return {"enabled": self.enabled, "capacity": self.capacity,
                "recorded": recorded, "buffered": buffered,
                "dropped": max(recorded - buffered, 0)}

    # -- export ---------------------------------------------------------------

    def _abs_us(self, ts_mono: float) -> float:
        return (self._anchor_unix + (ts_mono - self._anchor_mono)) * 1e6

    def chrome_events(self) -> List[Dict[str, Any]]:
        events: List[Dict[str, Any]] = []
        for evt in self.snapshot():
            args = dict(evt.get("args") or {})
            for k in ("trace-id", "span-id"):
                if k in evt:
                    args[k] = evt[k]
            out: Dict[str, Any] = {
                "name": evt["name"], "cat": evt["cat"],
                "ts": round(self._abs_us(evt["ts"]), 3),
                "pid": evt["pid"], "tid": evt["tid"], "args": args}
            dur = evt.get("dur-s")
            if dur is not None:
                out["ph"] = "X"
                out["dur"] = round(max(dur * 1e6, 1.0), 3)
            else:
                out["ph"] = "i"
                out["s"] = "t"
            events.append(out)
        return events

    def export_chrome(self, path: str) -> str:
        """Atomically write the ring as Chrome trace-event JSON.  The
        ring is snapshotted under the lock; conversion and the write
        happen outside it (no blocking I/O under a held lock)."""
        import json

        from jepsen_tpu.atomic_io import atomic_write
        doc = chrome_document(self.chrome_events())
        atomic_write(path,
                     lambda f: json.dump(doc, f, separators=(",", ":")))
        return path


#: the process-wide recorder every instrumentation site writes to
RECORDER = FlightRecorder()
